"""Crash-recovery soak harness — the failpoint plane's proving ground.

A *scenario* runs the pipeline in a child process with filesystem
storage and a failpoint armed to ``crash`` (SIGKILL semantics — no
drain, no atexit) at a chosen data-plane site mid-ingest, then restarts
over the same storage root and lets recovery deliver the backlog. The
parent asserts the durability contract:

1. every record whose ingest **ack** was observed (the child acks a
   sequence number only after ``push`` returned, i.e. after the
   write-through landed) is delivered at least once across all runs —
   except records the scenario *declares* lossy (a torn/unflushed final
   write: the write-through contract is "a crash loses at most the
   last partial write");
2. un-finalized chunks recover to the last full write, finalized chunks
   recover completely;
3. corruption injected into an on-disk chunk is quarantined to the DLQ
   (never delivered, never silently dropped);
4. delivery is at-least-once with duplicates bounded by the redelivery
   window: a sequence delivered more than once must have been on disk
   at crash time (run-1 delivery whose chunk file outlived the crash),
   and no sequence is delivered more than ``1 + restarts +
   declared_retries`` times.

Child protocol (this module run with ``python -m
fluentbit_tpu.failpoints.soak``): failpoints arrive via
``FBTPU_FAILPOINTS`` (armed at import, before the engine exists);
``ingested.log`` records acks, ``delivered.log`` records deliveries —
both fsync'd per line so they survive the SIGKILL. The delivery sink
honors a ``soak.deliver`` failpoint so retry/backoff scenarios can be
driven from the same DSL.

fbtpu-qos extensions (QOS.md): ``--reloads N`` performs N hot-reload
generation swaps *while ingesting* — each replaces the grep filter
in-place (a full native DFA/GrepTables recompile mid-stream) and
toggles an auxiliary output add/remove — so reload-under-load soaks to
the same acked ⊆ delivered contract. ``--flood-rate BYTES/S`` puts
input 0 on a quota'd tenant (``t0``); pushes its token bucket defers
return -1 and are deliberately NOT acked, so the contract audits that
quota-deferral never loses an *admitted* record.

fbtpu-relay extensions (FAULTS.md "fbtpu-relay"): two new child modes
build a multi-process forward fan-in topology. ``aggregator`` runs a
forward *input* + windowless flux filter + soak sink and, once its
stop-file appears and the engine quiesces, dumps a deterministic
``flux.json`` (rows sorted by group key; exact count / integer sums /
min / max per column; HLL estimate + register digest per distinct
column). ``edge`` runs lib inputs + an armored forward *output*
(upstream HA file, require_ack_response, gzip, fstore spool) and pushes
integer-valued records so flux sums are order-exact; it exits only
when the engine is quiet AND the partition spool has fully replayed.
``run_relay_scenario`` drives the tentpole proof: baseline (no faults)
vs faulted (35%-class network faults on the edge, an ack-black-hole
aggregator SIGKILLed mid-run, a partition healed by starting the
surviving aggregator late) must produce byte-identical flux dumps, a
dedup ledger with every chunk absorbed exactly once, and acked ⊆
delivered — zero lost, zero double-absorbed.

Used by ``tests/test_failpoints.py``: a short deterministic matrix in
tier-1 and the full matrix behind the ``soak``/``slow`` markers.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

from . import FailpointError, fire

DELIVERED_LOG = "delivered.log"
INGESTED_LOG = "ingested.log"
STORAGE_DIR = "storage"
FLUX_DUMP = "flux.json"

#: the edge fault cocktail for the relay tentpole: connect/ack/write
#: faults well above the 35% floor the ISSUE demands, plus duplicate
#: deliveries to prove the dedup ledger (percentages are per-site).
DEFAULT_EDGE_FAULTS = (
    "forward.conn_reset=35%return;"
    "forward.partial_write=20%partial(40);"
    "forward.dup_delivery=25%return;"
    "forward.handshake=15%return"
)


def _append_line(path: str, text: str) -> None:
    """Append one line and force it to disk — the soak logs are the
    ground truth the parent audits after a SIGKILL, so a buffered line
    would make the contract check lie."""
    with open(path, "a", encoding="utf-8") as f:
        f.write(text + "\n")
        f.flush()
        os.fsync(f.fileno())


def _register_sink():
    """Register the soak delivery sink (idempotent per process)."""
    from ..codec.events import decode_events
    from ..core.config import ConfigMapEntry
    from ..core.plugin import FlushResult, OutputPlugin, registry

    if "soak_sink" in registry.outputs:
        return

    @registry.register
    class SoakSink(OutputPlugin):
        """Delivery ledger: one fsync'd line per delivered record."""

        name = "soak_sink"
        description = "crash-recovery soak delivery ledger"
        config_map = [
            ConfigMapEntry("path", "str"),
            ConfigMapEntry("run_id", "str", default="0"),
        ]

        async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
            from .. import failpoints as _fp

            if _fp.ACTIVE:
                try:
                    fire("soak.deliver")
                except FailpointError:
                    return FlushResult.RETRY
            seqs = [ev.body.get("seq") for ev in decode_events(data)]
            # one line per flush keeps the ledger append atomic enough
            # for line-based parsing after a mid-write SIGKILL
            _append_line(self.path, json.dumps(
                {"run": self.run_id, "tag": tag, "seqs": seqs}))
            return FlushResult.OK


def child_main(argv: Optional[Sequence[str]] = None) -> int:
    """Entry point for one child run (ingest or recover)."""
    import argparse

    ap = argparse.ArgumentParser(prog="fbtpu-soak-child")
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--mode",
                    choices=("ingest", "recover", "aggregator", "edge"),
                    default="ingest")
    ap.add_argument("--port", type=int, default=0,
                    help="aggregator: forward-input listen port")
    ap.add_argument("--upstream", default="",
                    help="edge: upstream HA definition file")
    ap.add_argument("--stop-file", default="",
                    help="aggregator: run until this file exists")
    ap.add_argument("--records", type=int, default=20)
    ap.add_argument("--tags", type=int, default=1,
                    help="round-robin records over N tags (N chunks)")
    ap.add_argument("--flush", default="200ms")
    ap.add_argument("--run-id", default="0")
    ap.add_argument("--final-flush", action="store_true",
                    help="call flush_now after the last push (drives "
                    "drain-time failpoints deterministically)")
    ap.add_argument("--settle", type=float, default=2.0,
                    help="recover mode: seconds to wait for redelivery")
    ap.add_argument("--reloads", type=int, default=0,
                    help="hot-reload generation swaps spread across the "
                    "ingest (grep DFA recompile + aux output toggle)")
    ap.add_argument("--flood-rate", default="",
                    help="bytes/sec quota for input 0's tenant; "
                    "deferred pushes are not acked")
    args = ap.parse_args(argv)

    import fluentbit_tpu as flb

    _register_sink()
    os.makedirs(args.workdir, exist_ok=True)
    delivered = os.path.join(args.workdir, DELIVERED_LOG)
    ingested = os.path.join(args.workdir, INGESTED_LOG)

    if args.mode == "aggregator":
        return _aggregator_main(flb, args, delivered)
    if args.mode == "edge":
        return _edge_main(flb, args, ingested)

    ctx = flb.create(flush=args.flush, grace="2", **{
        "storage.path": os.path.join(args.workdir, STORAGE_DIR),
        "storage.checksum": "on",
        "scheduler.base": "0.05", "scheduler.cap": "0.1",
    })
    in_ffd = []
    for i in range(max(1, args.tags)):
        props = {"storage.type": "filesystem"}
        if args.flood_rate:
            # per-input tenants; input 0 is the quota'd (flooding) one
            props["tenant"] = f"t{i}"
            if i == 0:
                props["tenant.rate"] = args.flood_rate
                props["tenant.overflow"] = "defer"
        in_ffd.append(ctx.input("lib", tag=f"soak.{i}", **props))
    if args.reloads:
        # a real DFA-backed filter so each reload's replace_filter is a
        # full native table recompile (the rule keeps every record:
        # Exclude on a field the records don't carry)
        ctx.filter("grep", match="soak.*", exclude="log ZZZNOPE")
    ctx.output("soak_sink", match="soak.*", path=delivered,
               run_id=args.run_id)
    ctx.start()
    try:
        if args.mode == "ingest":
            reload_every = (max(1, args.records // (args.reloads + 1))
                            if args.reloads else 0)
            done_reloads = 0
            for seq in range(args.records):
                ffd = in_ffd[seq % len(in_ffd)]
                got = ctx.push(ffd, json.dumps({"seq": seq}))
                if got:
                    # ack AFTER push returned: the write-through is on
                    # disk (quota-deferred/shed pushes are never acked)
                    _append_line(ingested, str(seq))
                # the reload trigger is independent of this push's
                # admission verdict — a deferred push at the boundary
                # must not silently skip a generation swap
                if reload_every and done_reloads < args.reloads \
                        and seq and seq % reload_every == 0:
                    txn = ctx.engine.reload_txn()
                    txn.replace_filter("grep.0")  # DFA recompile
                    if done_reloads % 2 == 0:
                        txn.add_output("null", match="aux.*")
                    else:
                        # resolve the live instance name: numbering
                        # never recycles a retired name, so the null
                        # output added two reloads ago is null.N, not
                        # a fixed null.0
                        victim = next(
                            o.name for o in ctx.engine.outputs
                            if o.plugin.name == "null")
                        txn.remove_output(victim)
                    txn.commit()
                    done_reloads += 1
            if args.final_flush:
                ctx.flush_now()
        else:  # recover: the backlog re-dispatches on the flush timer
            deadline = time.time() + args.settle
            e = ctx.engine
            while time.time() < deadline:
                if not e._backlog and not e._task_map \
                        and not e._pending_flushes \
                        and not e._pending_retries:
                    break
                time.sleep(0.05)
    finally:
        ctx.stop()
    return 0


# ----------------------------------------------------- relay children


def _engine_quiet(e) -> bool:
    return (not e._backlog and not e._task_map
            and not e._pending_flushes and not e._pending_retries)


def _flux_dump(state) -> dict:
    """Render live flux state into a canonical, comparable form.

    Everything in the dump is order-independent math (exact counts,
    integer-valued sums, min/max, HLL register max-merges), so two runs
    that absorbed the same record multiset — regardless of chunking,
    resend interleaving or replay order — serialize byte-identically.
    A double-absorb or a lost record perturbs count/sum/registers and
    the comparison fails. Rows sort by group key; HLL registers are
    reported as (estimate, sha256-of-registers) so the dump stays small
    while still pinning every register bit.
    """
    import hashlib

    import numpy as np

    rows = {}
    for key, g in state.live_groups():
        k = "|".join(
            x.decode("utf-8", "replace")
            if isinstance(x, (bytes, bytearray)) else str(x)
            for x in key)
        cols = {}
        for f, st in sorted(g.cols.items()):
            cols[f] = [st.sum, st.min_value(), st.max_value()]
        hlls = {}
        for f, h in sorted(g.hlls.items()):
            regs = np.asarray(h.registers)
            hlls[f] = [float(h.estimate()),
                       hashlib.sha256(regs.tobytes()).hexdigest()]
        rows[k] = {"count": g.count, "cols": cols, "hlls": hlls}
    return rows


def _aggregator_main(flb, args, delivered: str) -> int:
    """Forward fan-in aggregator: forward input → windowless flux →
    soak sink. Runs until the stop-file appears, settles until the
    engine is quiet, then dumps ``flux.json`` and exits."""
    ctx = flb.create(flush=args.flush, grace="2", **{
        "storage.path": os.path.join(args.workdir, STORAGE_DIR),
        "storage.checksum": "on",
        "scheduler.base": "0.05", "scheduler.cap": "0.1",
    })
    ctx.input("forward", listen="127.0.0.1", port=str(args.port),
              shared_key="soak", **{"storage.type": "filesystem"})
    # windowless flux: a running (never-closing) pane, so the dump is a
    # pure function of the absorbed record multiset — no pane-boundary
    # nondeterminism between the baseline and the faulted run
    ctx.filter("flux", match="soak.*", group_by="k",
               distinct_field="d", aggregate_field="v")
    ctx.output("soak_sink", match="soak.*", path=delivered,
               run_id=args.run_id)
    ctx.start()
    try:
        while args.stop_file and not os.path.exists(args.stop_file):
            time.sleep(0.05)
        deadline = time.time() + args.settle
        e = ctx.engine
        while time.time() < deadline:
            if _engine_quiet(e):
                break
            time.sleep(0.05)
        flux = next(f.plugin for f in e.filters
                    if f.plugin.name == "flux")
        dump = json.dumps(_flux_dump(flux.state), sort_keys=True,
                          separators=(",", ":"))
        _append_line(os.path.join(args.workdir, FLUX_DUMP), dump)
    finally:
        ctx.stop()
    return 0


def _edge_main(flb, args, ingested: str) -> int:
    """Edge relay: lib inputs → armored forward output (upstream HA,
    ack-verified, gzip-compressed, fstore spool for partitions).

    Record values are INTEGERS so the aggregator's float64 column sums
    are exact and therefore order-independent — the property the
    bit-identical flux comparison rests on. Acks a seq into
    ``ingested.log`` only after the push was admitted; exits only when
    the engine is quiet AND the partition spool has drained.
    """
    ctx = flb.create(flush=args.flush, grace="2", **{
        "storage.path": os.path.join(args.workdir, STORAGE_DIR),
        "storage.checksum": "on",
        "scheduler.base": "0.05", "scheduler.cap": "0.2",
    })
    in_ffd = [ctx.input("lib", tag=f"soak.{i}",
                        **{"storage.type": "filesystem"})
              for i in range(max(1, args.tags))]
    ctx.output("forward", match="soak.*", upstream=args.upstream,
               shared_key="soak", require_ack_response="true",
               ack_timeout="1", compress="gzip",
               storage_spool=os.path.join(args.workdir, "spool"))
    ctx.start()
    try:
        for seq in range(args.records):
            ffd = in_ffd[seq % len(in_ffd)]
            got = ctx.push(ffd, json.dumps({
                "seq": seq,
                "k": "g%d" % (seq % 3),
                "d": "u%d" % (seq % 7),
                "v": (seq * 7) % 101,
            }))
            if got:
                _append_line(ingested, str(seq))
        ctx.flush_now()
        fwd = next(o.plugin for o in ctx.engine.outputs
                   if o.plugin.name == "forward")
        deadline = time.time() + args.settle
        e = ctx.engine
        drained = False
        while time.time() < deadline:
            spool = getattr(fwd, "_spool", None)
            if _engine_quiet(e) and (spool is None
                                     or not spool.pending()):
                drained = True
                break
            time.sleep(0.05)
        if not drained:
            # a silent exit-0 here would let the parent read "all
            # delivered" off a still-loaded spool — fail loudly instead
            spool = getattr(fwd, "_spool", None)
            print("edge drain deadline: engine_quiet=%s spool=%d"
                  % (_engine_quiet(e),
                     len(spool.pending()) if spool else 0),
                  file=sys.stderr)
            return 3
    finally:
        ctx.stop()
    return 0


# ---------------------------------------------------------------- parent


class SoakOutcome:
    """What one scenario produced, parsed back from the soak logs."""

    def __init__(self, workdir: str, ingested_from: Optional[str] = None):
        self.workdir = workdir
        self.acked: List[int] = []
        self.deliveries: Dict[str, List[int]] = {}  # run id → seqs
        self.exit_codes: List[int] = []
        # relay topology: acks live in the EDGE workdir, deliveries in
        # the aggregator's — ingested_from points at the former
        ing = ingested_from or os.path.join(workdir, INGESTED_LOG)
        if os.path.exists(ing):
            with open(ing, encoding="utf-8") as f:
                self.acked = [int(s) for s in f.read().split()]
        dlv = os.path.join(workdir, DELIVERED_LOG)
        if os.path.exists(dlv):
            with open(dlv, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        obj = json.loads(line)
                    except ValueError:
                        continue  # torn final line from a mid-write kill
                    self.deliveries.setdefault(str(obj["run"]), []).extend(
                        s for s in obj["seqs"] if s is not None)

    def delivered_all(self) -> List[int]:
        return [s for seqs in self.deliveries.values() for s in seqs]

    def dlq_files(self) -> List[str]:
        d = os.path.join(self.workdir, STORAGE_DIR, "dlq")
        return sorted(os.listdir(d)) if os.path.isdir(d) else []

    def stream_files(self) -> List[str]:
        out = []
        root = os.path.join(self.workdir, STORAGE_DIR, "streams")
        for dirpath, _dirs, files in os.walk(root):
            out.extend(os.path.join(dirpath, n) for n in files)
        return sorted(out)


def _child_invocation(workdir: str, mode: str, *, failpoints: str,
                      seed: int, records: int, tags: int, flush: str,
                      run_id: str, final_flush: bool, settle: float,
                      reloads: int, flood_rate: str, port: int,
                      upstream: str, stop_file: str):
    """(cmd, env, cwd) for one soak child — shared by the blocking
    ``run_child`` and the concurrent ``spawn_child``."""
    env = dict(os.environ)
    env["FBTPU_FAILPOINTS"] = failpoints
    env["FBTPU_FAILPOINTS_SEED"] = str(seed)
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, "-m", "fluentbit_tpu.failpoints.soak",
           "--workdir", workdir, "--mode", mode,
           "--records", str(records), "--tags", str(tags),
           "--flush", flush, "--run-id", run_id,
           "--settle", str(settle)]
    if reloads:
        cmd += ["--reloads", str(reloads)]
    if flood_rate:
        cmd += ["--flood-rate", flood_rate]
    if final_flush:
        cmd.append("--final-flush")
    if port:
        cmd += ["--port", str(port)]
    if upstream:
        cmd += ["--upstream", upstream]
    if stop_file:
        cmd += ["--stop-file", stop_file]
    cwd = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return cmd, env, cwd


def run_child(workdir: str, mode: str, *, failpoints: str = "",
              seed: int = 0, records: int = 20, tags: int = 1,
              flush: str = "200ms", run_id: str = "0",
              final_flush: bool = False, settle: float = 2.0,
              reloads: int = 0, flood_rate: str = "",
              port: int = 0, upstream: str = "", stop_file: str = "",
              timeout: float = 60.0) -> int:
    """Spawn one child run; returns its exit code (negative = signal,
    matching ``subprocess`` convention — a crash failpoint shows up as
    ``-SIGKILL``)."""
    cmd, env, cwd = _child_invocation(
        workdir, mode, failpoints=failpoints, seed=seed,
        records=records, tags=tags, flush=flush, run_id=run_id,
        final_flush=final_flush, settle=settle, reloads=reloads,
        flood_rate=flood_rate, port=port, upstream=upstream,
        stop_file=stop_file)
    proc = subprocess.run(cmd, env=env, timeout=timeout,
                          capture_output=True, text=True, cwd=cwd)
    if proc.returncode not in (0, -9, 137):
        raise RuntimeError(
            f"soak child ({mode}) exited {proc.returncode}:\n"
            f"{proc.stdout}\n{proc.stderr}")
    return proc.returncode


def spawn_child(workdir: str, mode: str, *, failpoints: str = "",
                seed: int = 0, records: int = 20, tags: int = 1,
                flush: str = "200ms", run_id: str = "0",
                settle: float = 2.0, port: int = 0, upstream: str = "",
                stop_file: str = "") -> "subprocess.Popen":
    """Start one soak child WITHOUT waiting — the relay topology runs
    aggregators and the edge concurrently. stdout/stderr land in
    ``<workdir>/child.log`` for post-mortems."""
    cmd, env, cwd = _child_invocation(
        workdir, mode, failpoints=failpoints, seed=seed,
        records=records, tags=tags, flush=flush, run_id=run_id,
        final_flush=False, settle=settle, reloads=0, flood_rate="",
        port=port, upstream=upstream, stop_file=stop_file)
    os.makedirs(workdir, exist_ok=True)
    logf = open(os.path.join(workdir, "child.log"), "ab")
    try:
        return subprocess.Popen(cmd, env=env, cwd=cwd, stdout=logf,
                                stderr=subprocess.STDOUT)
    finally:
        logf.close()  # the child holds its own fd after fork


def verify_contract(outcome: SoakOutcome, *, restarts: int,
                    allowed_missing: Sequence[int] = (),
                    quarantined: Sequence[int] = (),
                    declared_retries: int = 0,
                    absorbed: Optional[Dict[str, int]] = None) -> None:
    """Assert the durability contract over a finished scenario.

    ``allowed_missing``: seqs the scenario declares lossy (the torn /
    unflushed final write). ``quarantined``: seqs whose chunk the
    harness corrupted on disk — they must NOT be delivered and their
    chunk must be in the DLQ. ``absorbed``: a dedup-ledger audit map
    (chunk-id → absorb count, from ``relay.load_ledger_counts``) —
    effectively-once means every count is exactly 1: the ledger only
    records ABSORBS, so any count above 1 is a double-absorb into the
    non-idempotent flux sketch plane.
    """
    if absorbed is not None:
        over_abs = {cid: c for cid, c in absorbed.items() if c > 1}
        assert not over_abs, (
            f"chunks absorbed more than once (ledger audit): {over_abs}")
    delivered = outcome.delivered_all()
    got = set(delivered)
    acked = set(outcome.acked)
    missing = acked - got
    illegal_missing = missing - set(allowed_missing) - set(quarantined)
    assert not illegal_missing, (
        f"acked records lost across crash/recovery: "
        f"{sorted(illegal_missing)} (acked={len(acked)}, "
        f"delivered={len(got)}, dlq={outcome.dlq_files()})")
    for s in quarantined:
        assert s not in got, f"corrupted seq {s} must not be delivered"
    if quarantined:
        assert outcome.dlq_files(), "corruption must land in the DLQ"
    # at-least-once, duplicates bounded to the redelivery window
    bound = 1 + restarts + declared_retries
    counts: Dict[int, int] = {}
    for s in delivered:
        counts[s] = counts.get(s, 0) + 1
    over = {s: c for s, c in counts.items() if c > bound}
    assert not over, f"deliveries beyond the redelivery window: {over}"
    dup_seqs = {s for s, c in counts.items() if c > 1}
    # a duplicate must be explained by redelivery: the seq was delivered
    # by an earlier run AND its chunk file outlived the crash (so a
    # later run replayed it) — i.e. it appears in 2+ distinct runs or
    # was retried within one run (declared_retries > 0)
    if dup_seqs and not declared_retries:
        per_run = [set(v) for v in outcome.deliveries.values()]
        for s in dup_seqs:
            in_runs = sum(1 for seqs in per_run if s in seqs)
            assert in_runs >= 2, (
                f"seq {s} duplicated within a single run with no "
                f"declared retries")


# ----------------------------------------------------- relay scenario


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_port(port: int, timeout: float = 15.0) -> bool:
    """Poll until a listener accepts on 127.0.0.1:port."""
    import socket

    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=0.5).close()
            return True
        except OSError:
            time.sleep(0.05)
    return False


def _write_upstream(path: str, ports: Sequence[int]) -> None:
    lines = ["[UPSTREAM]", "    name relay-soak", ""]
    for i, p in enumerate(ports):
        lines += ["[NODE]", f"    name agg{i}", "    host 127.0.0.1",
                  f"    port {p}", ""]
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(lines))


def _read_flux_dump(workdir: str) -> bytes:
    with open(os.path.join(workdir, FLUX_DUMP), "rb") as f:
        return f.read()


def run_relay_scenario(workdir: str, *, records: int = 60,
                       tags: int = 2, seed: int = 1,
                       edge_faults: str = DEFAULT_EDGE_FAULTS,
                       settle: float = 30.0,
                       partition_secs: float = 1.5) -> dict:
    """The fbtpu-relay tentpole proof (FAULTS.md "fbtpu-relay").

    Baseline: one aggregator, no faults — dump flux.json. Faulted: the
    edge fans over TWO upstreams; aggregator A is an ack black hole
    (``forward.ack_drop=return`` at 100%: it absorbs every chunk into
    its own engine but never acks, so the edge must treat every send as
    lost) and is SIGKILLed mid-run — its absorbs die with it; B does
    not exist yet (a full partition: the edge degrades to the fstore
    spool). B starts ``partition_secs`` later (the heal) and the spool
    replays — under connect resets, torn writes, duplicate deliveries
    and handshake faults on every edge socket.

    Asserts the whole contract: edge exits clean (spool drained), B's
    flux dump is byte-identical to the baseline's, B's dedup ledger
    shows every chunk absorbed exactly once, and acked ⊆ delivered
    with no sequence delivered twice. Returns the artifacts for
    further inspection.
    """
    from ..core.relay import load_ledger_counts

    os.makedirs(workdir, exist_ok=True)

    # ---- baseline: single aggregator, fault-free
    base_agg = os.path.join(workdir, "base-agg")
    base_edge = os.path.join(workdir, "base-edge")
    os.makedirs(base_agg, exist_ok=True)
    os.makedirs(base_edge, exist_ok=True)
    p0 = _free_port()
    stop0 = os.path.join(base_agg, "stop")
    up0 = os.path.join(base_edge, "upstream.conf")
    _write_upstream(up0, [p0])
    agg0 = spawn_child(base_agg, "aggregator", port=p0,
                       stop_file=stop0, run_id="base", settle=settle)
    try:
        assert _wait_port(p0), "baseline aggregator never listened"
        rc = run_child(base_edge, "edge", upstream=up0,
                       records=records, tags=tags, run_id="base",
                       settle=settle, timeout=settle + 60)
        assert rc == 0, f"baseline edge exited {rc}"
    finally:
        _append_line(stop0, "stop")
        try:
            agg0.wait(timeout=settle + 30)
        except subprocess.TimeoutExpired:
            agg0.kill()
            raise
    assert agg0.returncode == 0, \
        f"baseline aggregator exited {agg0.returncode}"
    baseline = _read_flux_dump(base_agg)

    # ---- faulted: black-hole A (SIGKILLed), late B, armored edge
    f_agg_a = os.path.join(workdir, "fault-agg-a")
    f_agg_b = os.path.join(workdir, "fault-agg-b")
    f_edge = os.path.join(workdir, "fault-edge")
    for d in (f_agg_a, f_agg_b, f_edge):
        os.makedirs(d, exist_ok=True)
    pa, pb = _free_port(), _free_port()
    stop_b = os.path.join(f_agg_b, "stop")
    up1 = os.path.join(f_edge, "upstream.conf")
    _write_upstream(up1, [pa, pb])
    agg_a = spawn_child(f_agg_a, "aggregator", port=pa,
                        stop_file=os.path.join(f_agg_a, "stop"),
                        failpoints="forward.ack_drop=return",
                        seed=seed, run_id="fault", settle=1.0)
    agg_b = None
    edge = None
    try:
        assert _wait_port(pa), "black-hole aggregator never listened"
        # the faulted edge gets extra drain allowance: the partition
        # spool replays through breaker cooldowns and armed fault sites
        edge = spawn_child(f_edge, "edge", upstream=up1,
                           records=records, tags=tags,
                           failpoints=edge_faults, seed=seed,
                           run_id="fault", settle=settle + 30)
        # let the edge burn acks against A, then hard-kill it: every
        # chunk A absorbed dies unacked — the edge must redeliver all
        # of them to B without double-absorbing any
        time.sleep(partition_secs)
        agg_a.kill()
        agg_a.wait(timeout=30)
        # the heal: B appears; the edge's breaker probes find it and
        # the partition spool replays in order
        agg_b = spawn_child(f_agg_b, "aggregator", port=pb,
                            stop_file=stop_b, run_id="fault",
                            settle=settle)
        assert _wait_port(pb), "surviving aggregator never listened"
        rc = edge.wait(timeout=settle + 120)
        assert rc == 0, (
            f"faulted edge exited {rc} — see {f_edge}/child.log")
        edge = None
    finally:
        if edge is not None:
            edge.kill()
        if agg_a.returncode is None:
            agg_a.kill()
        if agg_b is not None:
            _append_line(stop_b, "stop")
            try:
                agg_b.wait(timeout=settle + 60)
            except subprocess.TimeoutExpired:
                agg_b.kill()
                raise
    assert agg_b.returncode == 0, \
        f"surviving aggregator exited {agg_b.returncode}"
    faulted = _read_flux_dump(f_agg_b)

    # ---- the contract
    assert faulted == baseline, (
        "flux state diverged under faults:\n"
        f"  baseline: {baseline.decode()}\n"
        f"  faulted:  {faulted.decode()}")
    ledger = load_ledger_counts(os.path.join(f_agg_b, STORAGE_DIR))
    assert ledger, "surviving aggregator's dedup ledger is empty"
    outcome = SoakOutcome(
        f_agg_b,
        ingested_from=os.path.join(f_edge, INGESTED_LOG))
    verify_contract(outcome, restarts=0, absorbed=ledger)
    assert len(set(outcome.acked)) == records, (
        f"edge admitted {len(set(outcome.acked))}/{records} records")
    return {"baseline": baseline, "faulted": faulted,
            "ledger": ledger, "outcome": outcome}


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    sys.exit(child_main())
