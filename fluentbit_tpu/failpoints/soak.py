"""Crash-recovery soak harness — the failpoint plane's proving ground.

A *scenario* runs the pipeline in a child process with filesystem
storage and a failpoint armed to ``crash`` (SIGKILL semantics — no
drain, no atexit) at a chosen data-plane site mid-ingest, then restarts
over the same storage root and lets recovery deliver the backlog. The
parent asserts the durability contract:

1. every record whose ingest **ack** was observed (the child acks a
   sequence number only after ``push`` returned, i.e. after the
   write-through landed) is delivered at least once across all runs —
   except records the scenario *declares* lossy (a torn/unflushed final
   write: the write-through contract is "a crash loses at most the
   last partial write");
2. un-finalized chunks recover to the last full write, finalized chunks
   recover completely;
3. corruption injected into an on-disk chunk is quarantined to the DLQ
   (never delivered, never silently dropped);
4. delivery is at-least-once with duplicates bounded by the redelivery
   window: a sequence delivered more than once must have been on disk
   at crash time (run-1 delivery whose chunk file outlived the crash),
   and no sequence is delivered more than ``1 + restarts +
   declared_retries`` times.

Child protocol (this module run with ``python -m
fluentbit_tpu.failpoints.soak``): failpoints arrive via
``FBTPU_FAILPOINTS`` (armed at import, before the engine exists);
``ingested.log`` records acks, ``delivered.log`` records deliveries —
both fsync'd per line so they survive the SIGKILL. The delivery sink
honors a ``soak.deliver`` failpoint so retry/backoff scenarios can be
driven from the same DSL.

fbtpu-qos extensions (QOS.md): ``--reloads N`` performs N hot-reload
generation swaps *while ingesting* — each replaces the grep filter
in-place (a full native DFA/GrepTables recompile mid-stream) and
toggles an auxiliary output add/remove — so reload-under-load soaks to
the same acked ⊆ delivered contract. ``--flood-rate BYTES/S`` puts
input 0 on a quota'd tenant (``t0``); pushes its token bucket defers
return -1 and are deliberately NOT acked, so the contract audits that
quota-deferral never loses an *admitted* record.

Used by ``tests/test_failpoints.py``: a short deterministic matrix in
tier-1 and the full matrix behind the ``soak``/``slow`` markers.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

from . import FailpointError, fire

DELIVERED_LOG = "delivered.log"
INGESTED_LOG = "ingested.log"
STORAGE_DIR = "storage"


def _append_line(path: str, text: str) -> None:
    """Append one line and force it to disk — the soak logs are the
    ground truth the parent audits after a SIGKILL, so a buffered line
    would make the contract check lie."""
    with open(path, "a", encoding="utf-8") as f:
        f.write(text + "\n")
        f.flush()
        os.fsync(f.fileno())


def _register_sink():
    """Register the soak delivery sink (idempotent per process)."""
    from ..codec.events import decode_events
    from ..core.config import ConfigMapEntry
    from ..core.plugin import FlushResult, OutputPlugin, registry

    if "soak_sink" in registry.outputs:
        return

    @registry.register
    class SoakSink(OutputPlugin):
        """Delivery ledger: one fsync'd line per delivered record."""

        name = "soak_sink"
        description = "crash-recovery soak delivery ledger"
        config_map = [
            ConfigMapEntry("path", "str"),
            ConfigMapEntry("run_id", "str", default="0"),
        ]

        async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
            from .. import failpoints as _fp

            if _fp.ACTIVE:
                try:
                    fire("soak.deliver")
                except FailpointError:
                    return FlushResult.RETRY
            seqs = [ev.body.get("seq") for ev in decode_events(data)]
            # one line per flush keeps the ledger append atomic enough
            # for line-based parsing after a mid-write SIGKILL
            _append_line(self.path, json.dumps(
                {"run": self.run_id, "tag": tag, "seqs": seqs}))
            return FlushResult.OK


def child_main(argv: Optional[Sequence[str]] = None) -> int:
    """Entry point for one child run (ingest or recover)."""
    import argparse

    ap = argparse.ArgumentParser(prog="fbtpu-soak-child")
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--mode", choices=("ingest", "recover"),
                    default="ingest")
    ap.add_argument("--records", type=int, default=20)
    ap.add_argument("--tags", type=int, default=1,
                    help="round-robin records over N tags (N chunks)")
    ap.add_argument("--flush", default="200ms")
    ap.add_argument("--run-id", default="0")
    ap.add_argument("--final-flush", action="store_true",
                    help="call flush_now after the last push (drives "
                    "drain-time failpoints deterministically)")
    ap.add_argument("--settle", type=float, default=2.0,
                    help="recover mode: seconds to wait for redelivery")
    ap.add_argument("--reloads", type=int, default=0,
                    help="hot-reload generation swaps spread across the "
                    "ingest (grep DFA recompile + aux output toggle)")
    ap.add_argument("--flood-rate", default="",
                    help="bytes/sec quota for input 0's tenant; "
                    "deferred pushes are not acked")
    args = ap.parse_args(argv)

    import fluentbit_tpu as flb

    _register_sink()
    os.makedirs(args.workdir, exist_ok=True)
    delivered = os.path.join(args.workdir, DELIVERED_LOG)
    ingested = os.path.join(args.workdir, INGESTED_LOG)

    ctx = flb.create(flush=args.flush, grace="2", **{
        "storage.path": os.path.join(args.workdir, STORAGE_DIR),
        "storage.checksum": "on",
        "scheduler.base": "0.05", "scheduler.cap": "0.1",
    })
    in_ffd = []
    for i in range(max(1, args.tags)):
        props = {"storage.type": "filesystem"}
        if args.flood_rate:
            # per-input tenants; input 0 is the quota'd (flooding) one
            props["tenant"] = f"t{i}"
            if i == 0:
                props["tenant.rate"] = args.flood_rate
                props["tenant.overflow"] = "defer"
        in_ffd.append(ctx.input("lib", tag=f"soak.{i}", **props))
    if args.reloads:
        # a real DFA-backed filter so each reload's replace_filter is a
        # full native table recompile (the rule keeps every record:
        # Exclude on a field the records don't carry)
        ctx.filter("grep", match="soak.*", exclude="log ZZZNOPE")
    ctx.output("soak_sink", match="soak.*", path=delivered,
               run_id=args.run_id)
    ctx.start()
    try:
        if args.mode == "ingest":
            reload_every = (max(1, args.records // (args.reloads + 1))
                            if args.reloads else 0)
            done_reloads = 0
            for seq in range(args.records):
                ffd = in_ffd[seq % len(in_ffd)]
                got = ctx.push(ffd, json.dumps({"seq": seq}))
                if got:
                    # ack AFTER push returned: the write-through is on
                    # disk (quota-deferred/shed pushes are never acked)
                    _append_line(ingested, str(seq))
                # the reload trigger is independent of this push's
                # admission verdict — a deferred push at the boundary
                # must not silently skip a generation swap
                if reload_every and done_reloads < args.reloads \
                        and seq and seq % reload_every == 0:
                    txn = ctx.engine.reload_txn()
                    txn.replace_filter("grep.0")  # DFA recompile
                    if done_reloads % 2 == 0:
                        txn.add_output("null", match="aux.*")
                    else:
                        # resolve the live instance name: numbering
                        # never recycles a retired name, so the null
                        # output added two reloads ago is null.N, not
                        # a fixed null.0
                        victim = next(
                            o.name for o in ctx.engine.outputs
                            if o.plugin.name == "null")
                        txn.remove_output(victim)
                    txn.commit()
                    done_reloads += 1
            if args.final_flush:
                ctx.flush_now()
        else:  # recover: the backlog re-dispatches on the flush timer
            deadline = time.time() + args.settle
            e = ctx.engine
            while time.time() < deadline:
                if not e._backlog and not e._task_map \
                        and not e._pending_flushes \
                        and not e._pending_retries:
                    break
                time.sleep(0.05)
    finally:
        ctx.stop()
    return 0


# ---------------------------------------------------------------- parent


class SoakOutcome:
    """What one scenario produced, parsed back from the soak logs."""

    def __init__(self, workdir: str):
        self.workdir = workdir
        self.acked: List[int] = []
        self.deliveries: Dict[str, List[int]] = {}  # run id → seqs
        self.exit_codes: List[int] = []
        ing = os.path.join(workdir, INGESTED_LOG)
        if os.path.exists(ing):
            with open(ing, encoding="utf-8") as f:
                self.acked = [int(s) for s in f.read().split()]
        dlv = os.path.join(workdir, DELIVERED_LOG)
        if os.path.exists(dlv):
            with open(dlv, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        obj = json.loads(line)
                    except ValueError:
                        continue  # torn final line from a mid-write kill
                    self.deliveries.setdefault(str(obj["run"]), []).extend(
                        s for s in obj["seqs"] if s is not None)

    def delivered_all(self) -> List[int]:
        return [s for seqs in self.deliveries.values() for s in seqs]

    def dlq_files(self) -> List[str]:
        d = os.path.join(self.workdir, STORAGE_DIR, "dlq")
        return sorted(os.listdir(d)) if os.path.isdir(d) else []

    def stream_files(self) -> List[str]:
        out = []
        root = os.path.join(self.workdir, STORAGE_DIR, "streams")
        for dirpath, _dirs, files in os.walk(root):
            out.extend(os.path.join(dirpath, n) for n in files)
        return sorted(out)


def run_child(workdir: str, mode: str, *, failpoints: str = "",
              seed: int = 0, records: int = 20, tags: int = 1,
              flush: str = "200ms", run_id: str = "0",
              final_flush: bool = False, settle: float = 2.0,
              reloads: int = 0, flood_rate: str = "",
              timeout: float = 60.0) -> int:
    """Spawn one child run; returns its exit code (negative = signal,
    matching ``subprocess`` convention — a crash failpoint shows up as
    ``-SIGKILL``)."""
    env = dict(os.environ)
    env["FBTPU_FAILPOINTS"] = failpoints
    env["FBTPU_FAILPOINTS_SEED"] = str(seed)
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, "-m", "fluentbit_tpu.failpoints.soak",
           "--workdir", workdir, "--mode", mode,
           "--records", str(records), "--tags", str(tags),
           "--flush", flush, "--run-id", run_id,
           "--settle", str(settle)]
    if reloads:
        cmd += ["--reloads", str(reloads)]
    if flood_rate:
        cmd += ["--flood-rate", flood_rate]
    if final_flush:
        cmd.append("--final-flush")
    proc = subprocess.run(cmd, env=env, timeout=timeout,
                          capture_output=True, text=True,
                          cwd=os.path.dirname(os.path.dirname(
                              os.path.dirname(os.path.abspath(__file__)))))
    if proc.returncode not in (0, -9, 137):
        raise RuntimeError(
            f"soak child ({mode}) exited {proc.returncode}:\n"
            f"{proc.stdout}\n{proc.stderr}")
    return proc.returncode


def verify_contract(outcome: SoakOutcome, *, restarts: int,
                    allowed_missing: Sequence[int] = (),
                    quarantined: Sequence[int] = (),
                    declared_retries: int = 0) -> None:
    """Assert the durability contract over a finished scenario.

    ``allowed_missing``: seqs the scenario declares lossy (the torn /
    unflushed final write). ``quarantined``: seqs whose chunk the
    harness corrupted on disk — they must NOT be delivered and their
    chunk must be in the DLQ.
    """
    delivered = outcome.delivered_all()
    got = set(delivered)
    acked = set(outcome.acked)
    missing = acked - got
    illegal_missing = missing - set(allowed_missing) - set(quarantined)
    assert not illegal_missing, (
        f"acked records lost across crash/recovery: "
        f"{sorted(illegal_missing)} (acked={len(acked)}, "
        f"delivered={len(got)}, dlq={outcome.dlq_files()})")
    for s in quarantined:
        assert s not in got, f"corrupted seq {s} must not be delivered"
    if quarantined:
        assert outcome.dlq_files(), "corruption must land in the DLQ"
    # at-least-once, duplicates bounded to the redelivery window
    bound = 1 + restarts + declared_retries
    counts: Dict[int, int] = {}
    for s in delivered:
        counts[s] = counts.get(s, 0) + 1
    over = {s: c for s, c in counts.items() if c > bound}
    assert not over, f"deliveries beyond the redelivery window: {over}"
    dup_seqs = {s for s, c in counts.items() if c > 1}
    # a duplicate must be explained by redelivery: the seq was delivered
    # by an earlier run AND its chunk file outlived the crash (so a
    # later run replayed it) — i.e. it appears in 2+ distinct runs or
    # was retried within one run (declared_retries > 0)
    if dup_seqs and not declared_retries:
        per_run = [set(v) for v in outcome.deliveries.values()]
        for s in dup_seqs:
            in_runs = sum(1 for seqs in per_run if s in seqs)
            assert in_runs >= 2, (
                f"seq {s} duplicated within a single run with no "
                f"declared retries")


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    sys.exit(child_main())
