"""fbtpu-failpoints — deterministic fault-injection plane.

Modeled on etcd's gofail / tikv's fail-rs: the data plane carries named
*failpoints* — storage appends, flush dispatch, retry scheduling,
upstream I/O, the native-codec decline path, device attach — and each
can be armed at runtime with a small action DSL. Unarmed, the whole
plane costs one module-level boolean check per site (``ACTIVE``); the
hot path is untouched and bit-exact.

DSL (one spec per failpoint)::

    spec   := term ( "->" term )*        terms consumed left to right
    term   := [pct "%"] [cnt "*"] action [ "(" arg ")" ]
    action := off | return | delay | partial | panic | crash

- ``off``          no-op (with ``cnt*`` it skips the first cnt hits)
- ``return(err)``  raise :class:`FailpointError` (an ``OSError``
  subclass, so existing socket/file error handling — retries, pool
  drops, backoff — engages exactly as for a real fault)
- ``delay(ms)``    sleep ``ms`` milliseconds, then continue
- ``hang(ms)``     alias for ``delay`` with a 10-minute default — the
  hung-peer shape the fbtpu-guard deadline/breaker plane is built to
  survive; at :func:`fire_async` sites the sleep is an
  ``asyncio.sleep`` (one hung coroutine, not a stalled loop)
- ``partial(n)``   hand the site a ``("partial", n)`` directive — write
  sites truncate the operation's payload to ``n`` bytes (a torn write)
- ``panic``        raise ``RuntimeError`` (a plugin bug, not an I/O
  error: broad except-and-log paths engage, retries do not)
- ``crash``        kill the process immediately (SIGKILL semantics —
  no atexit, no flush, no drain; the crash-recovery soak harness's
  primitive)

A term with ``cnt*`` fires at most ``cnt`` times, then control moves to
the next term: ``2*off->1*crash`` crashes on the third hit. A term with
``pct%`` fires with that probability per hit, drawn from a
*deterministic per-site RNG* seeded from ``FBTPU_FAILPOINTS_SEED`` and
the failpoint name — identical runs replay identical fault schedules.

Control surfaces (mirroring the chunk-trace tap):

- env: ``FBTPU_FAILPOINTS="storage.append=2*off->1*crash;upstream.send=25%return(reset)"``
- programmatic: :func:`enable` / :func:`disable` / :func:`reset` /
  :func:`snapshot`
- HTTP: ``GET/POST/DELETE /api/v1/failpoints[/<name>]`` on the admin
  server

Every trigger is observable: the engine exports
``fluentbit_failpoint_triggered_total{name}`` via a listener hook
(:func:`add_listener`), and :func:`snapshot` reports per-site
evaluated/triggered counts.
"""

from __future__ import annotations

import logging
import os
import random
import re
import signal
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

log = logging.getLogger("flb.failpoints")

#: The one hot-path cost of the plane: sites check ``failpoints.ACTIVE``
#: before calling :func:`fire`. False whenever no failpoint is armed.
ACTIVE = False

ENV_VAR = "FBTPU_FAILPOINTS"
SEED_VAR = "FBTPU_FAILPOINTS_SEED"
HTTP_VAR = "FBTPU_FAILPOINTS_HTTP"

#: Documented injection sites (the inventory FAULTS.md describes).
#: :func:`fire` accepts any name — tests may add ad-hoc sites (the soak
#: sink's ``soak.deliver``) — but these are the ones threaded through
#: the shipped data plane.
SITES: Tuple[str, ...] = (
    "storage.append",            # Storage.write_through, before the write
    "storage.flush",             # Storage.write_through, write buffered / not yet flushed
    "storage.finalize",          # Storage.finalize, before the CRC stamp
    "storage.crc_verify",        # Storage._read_chunk_file, before the CRC check
    "storage.backlog_load",      # Storage.scan_backlog, before the walk
    "engine.flush_dispatch",     # Engine.flush_all, chunks finalized, tasks not yet spawned
    "engine.retry_schedule",     # Engine._schedule_retry, before the timer registers
    "engine.shutdown_quarantine",  # Engine._flush_one / _drop_retry, before quarantine
    "engine.reload_commit",      # ReloadTxn.commit: new tables built, old
                                 # generation still live (crash → old config)
    "qos.admit",                 # Qos.admit, before the token-bucket take
    "upstream.connect",          # tls.open_connection, before the dial
    "upstream.send",             # outputs_aws._http_request, before the request write
    "upstream.recv",             # outputs_aws._http_request, before the response read
    "output.flush",              # Engine._flush_body, before the plugin flush (async
                                 # site; the instance-scoped variant
                                 # "output.flush.<output>" fires right after it, so
                                 # one output can be hung while its siblings flow)
    "output.worker_flush",       # OutputWorkerPool.submit, before the handoff
    "output.worker_start",       # OutputWorkerPool._worker, before the ready barrier
    "codec.fallback",            # filter_parser batched JSON path: forced decline
    "device.attach",             # ops.device._attach_once, before backend init
                                 # (fires once per RETRY attempt — fbtpu-armor)
    "device.dispatch",           # ops.fault.DeviceLane, post-launch boundary:
                                 # donated staged buffers already consumed, so
                                 # return() exercises the re-stage-on-retry hazard
    "device.launch_hang",        # ops.fault.DeviceLane, before the launch — a
                                 # hang() here is the wedged-launch shape the
                                 # lane deadline soft-kills to the CPU fallback
    "mesh.device_lost",          # ops.fault.DeviceLane — return() marks the
                                 # launch as device loss: mesh shrinks to the
                                 # survivors, regrows when the breaker re-closes
    "flux.device_update",        # flux device sketch/count launches (inside the
                                 # flux lane's watched closure)
    "flux.snapshot",             # FluxState.persist, tmp written+fsynced, before
                                 # the atomic rename (crash → old file intact)
    "s3.upload_part",            # outputs_aws._mp_upload_part (RETRY repro site)
    "s3.complete",               # outputs_aws._mp_complete
    "forward.handshake",         # out_forward._handshake, before HELO read — a
                                 # return() here is an aggregator that accepts
                                 # the dial but never completes auth
    "forward.conn_reset",        # out_forward._send_chunk, before the frame
                                 # write: connection torn mid-stream (RST shape)
    "forward.partial_write",     # out_forward._send_chunk — partial(n) truncates
                                 # the frame after n bytes then tears the
                                 # connection: the receiver sees a torn msgpack
                                 # tail it must discard without absorbing
    "forward.dup_delivery",      # out_forward._send_chunk, after the ack: the
                                 # SAME frame is written again (network dup /
                                 # ambiguous-ack resend) — the aggregator's
                                 # dedup ledger must absorb it zero times
    "forward.ack_drop",          # in_forward._dispatch, absorb recorded, before
                                 # the ack write: the classic lost-ack window —
                                 # the edge resends, the ledger dedups
)


class FailpointError(OSError):
    """The injected failure for ``return(err)`` terms.

    Subclasses ``OSError`` deliberately: I/O sites funnel it through
    their real error handling (connection-retry, pool-drop, RETRY
    backoff) instead of needing failpoint-aware except clauses.
    """


_ACTIONS = ("off", "return", "delay", "hang", "partial", "panic", "crash")

#: ``hang`` with no argument sleeps this long — "forever" on test
#: timescales, finite so an abandoned arm cannot wedge a process for real
HANG_DEFAULT_MS = 600000.0

_TERM_RE = re.compile(
    r"^(?:(?P<pct>\d+(?:\.\d+)?)%)?"
    r"(?:(?P<cnt>\d+)\*)?"
    r"(?P<action>[a-z]+)"
    r"(?:\((?P<arg>[^)]*)\))?$")


class _Term:
    __slots__ = ("pct", "limit", "action", "arg", "fired")

    def __init__(self, pct: Optional[float], limit: Optional[int],
                 action: str, arg: str):
        self.pct = pct        # None = always
        self.limit = limit    # None = unlimited (terminal term)
        self.action = action
        self.arg = arg
        self.fired = 0


def parse_spec(spec: str) -> List[_Term]:
    """Parse a DSL spec into terms; raises ``ValueError`` on bad input
    (the admin endpoint surfaces the message as a 400)."""
    terms: List[_Term] = []
    text = spec.strip()
    if not text:
        raise ValueError("empty failpoint spec")
    for part in text.split("->"):
        m = _TERM_RE.match(part.strip())
        if m is None:
            raise ValueError(f"bad failpoint term {part.strip()!r}")
        action = m.group("action")
        if action not in _ACTIONS:
            raise ValueError(
                f"unknown failpoint action {action!r} "
                f"(one of {', '.join(_ACTIONS)})")
        pct = float(m.group("pct")) if m.group("pct") else None
        cnt = int(m.group("cnt")) if m.group("cnt") else None
        arg = m.group("arg") or ""
        if action in ("delay", "hang"):
            float(arg or "0")  # validate now, not at fire time
        elif action == "partial":
            int(arg or "0")
        terms.append(_Term(pct, cnt, action, arg))
    return terms


class Failpoint:
    """One armed site: its parsed terms + deterministic RNG + stats."""

    __slots__ = ("name", "spec", "terms", "rng", "evaluated", "triggered")

    def __init__(self, name: str, spec: str, seed: int):
        self.name = name
        self.spec = spec
        self.terms = parse_spec(spec)
        # per-site stream: the schedule at one site never shifts when
        # another site is armed or fires (gofail's determinism contract)
        self.rng = random.Random(f"{seed}:{name}")
        self.evaluated = 0
        self.triggered = 0


_lock = threading.Lock()
_registry: Dict[str, Failpoint] = {}
_listeners: List[Callable[[str, str], None]] = []


def _seed() -> int:
    try:
        return int(os.environ.get(SEED_VAR, "0"))
    except ValueError:
        return 0


def _refresh_active() -> None:
    global ACTIVE
    ACTIVE = bool(_registry)


def enable(name: str, spec: str) -> Failpoint:
    """Arm (or re-arm, resetting counts) a failpoint."""
    fp = Failpoint(name, spec, _seed())
    with _lock:
        _registry[name] = fp
        _refresh_active()
    log.warning("failpoint armed: %s = %s", name, spec)
    return fp


def disable(name: str) -> bool:
    """Disarm one failpoint; True when it was armed."""
    with _lock:
        found = _registry.pop(name, None) is not None
        _refresh_active()
    return found


def reset() -> None:
    """Disarm everything (tests call this between cases)."""
    with _lock:
        _registry.clear()
        _refresh_active()


def snapshot() -> Dict[str, dict]:
    """Per-site spec + counters (the admin GET body)."""
    with _lock:
        return {
            name: {"spec": fp.spec, "evaluated": fp.evaluated,
                   "triggered": fp.triggered}
            for name, fp in _registry.items()
        }


def http_control_enabled() -> bool:
    """Whether the admin server may ARM/DISARM failpoints over HTTP.

    The admin port is routinely exposed for Prometheus scraping; an
    always-on arm surface would be a remote kill switch (``crash`` is
    SIGKILL). Mutation therefore requires a launch-time opt-in —
    ``FBTPU_FAILPOINTS_HTTP=1`` (gofail's GOFAIL_HTTP stance) or a
    process that already opted into fault injection via
    ``FBTPU_FAILPOINTS``. GET stays available: reading counters is
    harmless and belongs on dashboards.
    """
    flag = os.environ.get(HTTP_VAR, "").lower()
    if flag in ("1", "on", "true", "yes"):
        return True
    if flag in ("0", "off", "false", "no"):
        return False  # explicit opt-OUT wins even when env-armed
    return bool(os.environ.get(ENV_VAR))


def add_listener(cb: Callable[[str, str], None]) -> None:
    """Register a trigger hook ``cb(name, action)`` — the engine wires
    its ``fluentbit_failpoint_triggered_total`` counter here."""
    with _lock:
        if cb not in _listeners:
            _listeners.append(cb)


def remove_listener(cb: Callable[[str, str], None]) -> None:
    with _lock:
        if cb in _listeners:
            _listeners.remove(cb)


def load_env(env: Optional[str] = None) -> int:
    """Arm failpoints from ``FBTPU_FAILPOINTS`` (``name=spec`` pairs,
    ``;``-separated). Returns how many were armed; bad entries log and
    are skipped (a fat-fingered env var must not take the pipeline
    down — fault injection is opt-in chaos, not a config gate)."""
    text = os.environ.get(ENV_VAR, "") if env is None else env
    n = 0
    for pair in text.split(";"):
        pair = pair.strip()
        if not pair:
            continue
        name, sep, spec = pair.partition("=")
        if not sep or not name.strip():
            log.error("failpoints: bad env entry %r (want name=spec)", pair)
            continue
        try:
            enable(name.strip(), spec)
            n += 1
        except ValueError as e:
            log.error("failpoints: bad spec for %s: %s", name.strip(), e)
    return n


def _crash() -> None:
    # SIGKILL semantics: no atexit, no buffered-file flush, no grace —
    # exactly what the soak harness needs a crash point to mean
    try:
        os.kill(os.getpid(), signal.SIGKILL)
    except OSError:  # platforms without SIGKILL delivery to self
        pass
    os._exit(137)


def _decide(name: str) -> Optional[Tuple[str, str]]:
    """Registry bookkeeping for one site hit: consume the current term,
    fire the listeners + trigger log, and return ``(action, arg)`` for
    the caller to apply — or ``None`` when nothing triggers. All side
    effects (sleeps, raises, crash) happen OUTSIDE the registry lock."""
    with _lock:
        fp = _registry.get(name)
        if fp is None:
            return None
        fp.evaluated += 1
        term = None
        for t in fp.terms:
            if t.limit is None or t.fired < t.limit:
                term = t
                break
        if term is None:
            return None
        if term.pct is not None and fp.rng.uniform(0, 100) >= term.pct:
            return None  # probability gate: count not consumed
        term.fired += 1
        action, arg = term.action, term.arg
        if action == "off":
            return None
        fp.triggered += 1
        listeners = list(_listeners)
    for cb in listeners:
        try:
            cb(name, action)
        except Exception:
            log.exception("failpoint listener failed")
    log.warning("failpoint triggered: %s -> %s(%s)", name, action, arg)
    return (action, arg)


def _hang_ms(action: str, arg: str) -> float:
    if action == "hang":
        return float(arg) if arg else HANG_DEFAULT_MS
    return float(arg or "0")


def _apply(name: str, action: str, arg: str) -> Optional[Tuple[str, int]]:
    """The non-sleeping action side effects shared by fire/fire_async."""
    if action == "return":
        raise FailpointError(f"failpoint {name}: injected error"
                             + (f" ({arg})" if arg else ""))
    if action == "partial":
        return ("partial", int(arg or "0"))
    if action == "panic":
        raise RuntimeError(f"failpoint {name}: injected panic")
    if action == "crash":
        _crash()
    return None


def fire(name: str) -> Optional[Tuple[str, int]]:
    """Evaluate the failpoint at site ``name``.

    Returns ``None`` (not armed / term not taken / no-op action), or a
    site-interpreted directive tuple — currently only
    ``("partial", n)``. Raises :class:`FailpointError` for ``return``,
    ``RuntimeError`` for ``panic``; ``crash`` does not return;
    ``delay``/``hang`` block the calling thread.

    Sites guard the call with ``if failpoints.ACTIVE:`` so an unarmed
    plane costs one module-attribute read.
    """
    decided = _decide(name)
    if decided is None:
        return None
    action, arg = decided
    if action in ("delay", "hang"):
        time.sleep(_hang_ms(action, arg) / 1000.0)
        return None
    return _apply(name, action, arg)


async def fire_async(name: str) -> Optional[Tuple[str, int]]:
    """:func:`fire` for coroutine sites: ``delay``/``hang`` become an
    ``asyncio.sleep``, so the fault suspends ONE coroutine (a hung
    flush) instead of stalling the whole event loop — and stays
    cancellable by the fbtpu-guard deadline watchdog. Every other
    action behaves exactly like :func:`fire`."""
    decided = _decide(name)
    if decided is None:
        return None
    action, arg = decided
    if action in ("delay", "hang"):
        import asyncio

        await asyncio.sleep(_hang_ms(action, arg) / 1000.0)
        return None
    return _apply(name, action, arg)


# arm from the environment at import: subprocess harnesses (the soak
# children) configure the whole plane before the engine exists
if os.environ.get(ENV_VAR):
    load_env()
