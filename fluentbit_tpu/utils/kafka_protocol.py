"""Kafka wire protocol — the produce path, from scratch.

Reference: plugins/out_kafka links librdkafka; this module speaks the
broker protocol directly: request framing (4-byte length + header v1),
Metadata v1 (partition leaders), Produce v3 carrying magic-v2
RecordBatches (crc32c over the post-crc section, zigzag-varint record
fields) — the subset a producer needs, kept wire-compatible with real
brokers (KIP-98 batch format).
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

from .snappy import crc32c

API_PRODUCE = 0
API_METADATA = 3


class KafkaProtocolError(ValueError):
    pass


# --------------------------------------------------------- primitives

def _str(s: Optional[str]) -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    b = s.encode("utf-8")
    return struct.pack(">h", len(b)) + b


def _bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _varint(n: int) -> bytes:
    u = _zigzag(n) & 0xFFFFFFFFFFFFFFFF
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class _Reader:
    __slots__ = ("b", "pos")

    def __init__(self, b: bytes):
        self.b = b
        self.pos = 0

    def take(self, n: int) -> bytes:
        v = self.b[self.pos:self.pos + n]
        if len(v) != n:
            raise KafkaProtocolError("truncated response")
        self.pos += n
        return v

    def i8(self) -> int:
        return struct.unpack(">b", self.take(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self.take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self.take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self.take(8))[0]

    def string(self) -> Optional[str]:
        n = self.i16()
        if n < 0:
            return None
        return self.take(n).decode("utf-8")

    def uvarint(self) -> int:
        shift = 0
        result = 0
        while True:
            b = self.take(1)[0]
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                return result
            shift += 7

    def varint(self) -> int:
        u = self.uvarint()
        return (u >> 1) ^ -(u & 1)


# ----------------------------------------------------------- requests

def request(api_key: int, api_version: int, correlation_id: int,
            client_id: str, body: bytes) -> bytes:
    hdr = struct.pack(">hhi", api_key, api_version, correlation_id) \
        + _str(client_id)
    payload = hdr + body
    return struct.pack(">i", len(payload)) + payload


def metadata_request(topics: List[str]) -> bytes:
    body = struct.pack(">i", len(topics))
    for t in topics:
        body += _str(t)
    return body


def parse_metadata_response(data: bytes):
    """v1 → (brokers {node_id: (host, port)},
             topics {name: {partition: leader_node_id}}, errors)."""
    r = _Reader(data)
    brokers: Dict[int, Tuple[str, int]] = {}
    for _ in range(r.i32()):
        node = r.i32()
        host = r.string() or ""
        port = r.i32()
        r.string()  # rack
        brokers[node] = (host, port)
    r.i32()  # controller id
    topics: Dict[str, Dict[int, int]] = {}
    errors: Dict[str, int] = {}
    for _ in range(r.i32()):
        terr = r.i16()
        name = r.string() or ""
        r.i8()  # is_internal
        parts: Dict[int, int] = {}
        for _ in range(r.i32()):
            perr = r.i16()
            pid = r.i32()
            leader = r.i32()
            for _ in range(r.i32()):
                r.i32()  # replicas
            for _ in range(r.i32()):
                r.i32()  # isr
            if perr == 0:
                parts[pid] = leader
        if terr:
            # an errored topic (e.g. UNKNOWN_TOPIC during creation)
            # must NOT enter the cache — callers would stop refreshing
            errors[name] = terr
        else:
            topics[name] = parts
    return brokers, topics, errors


# --------------------------------------------------- record batch v2

def encode_record_batch(records: List[Tuple[Optional[bytes], bytes]],
                        base_ts_ms: int) -> bytes:
    """records: [(key|None, value)] → one magic-v2 RecordBatch."""
    body = bytearray()
    for i, (key, value) in enumerate(records):
        rec = bytearray()
        rec += b"\x00"                       # attributes
        rec += _varint(0)                    # timestampDelta
        rec += _varint(i)                    # offsetDelta
        if key is None:
            rec += _varint(-1)
        else:
            rec += _varint(len(key))
            rec += key
        rec += _varint(len(value))
        rec += value
        rec += _varint(0)                    # headers
        body += _varint(len(rec))
        body += rec
    n = len(records)
    # post-crc section: attributes .. records
    post = struct.pack(">hiqqqhii", 0, n - 1, base_ts_ms, base_ts_ms,
                       -1, -1, -1, n) + bytes(body)
    crc = crc32c(post)
    # batchLength counts from partitionLeaderEpoch onward
    batch_tail = struct.pack(">ib", -1, 2) \
        + struct.pack(">I", crc) + post
    return struct.pack(">q", 0) + struct.pack(">i", len(batch_tail)) \
        + batch_tail


def produce_request(topic_batches: Dict[str, Dict[int, bytes]],
                    acks: int = 1, timeout_ms: int = 30000) -> bytes:
    """{topic: {partition: record_set_bytes}} → Produce v3 body."""
    body = _str(None)  # transactional_id
    body += struct.pack(">hi", acks, timeout_ms)
    body += struct.pack(">i", len(topic_batches))
    for topic, parts in topic_batches.items():
        body += _str(topic)
        body += struct.pack(">i", len(parts))
        for pid, record_set in parts.items():
            body += struct.pack(">i", pid)
            body += _bytes(record_set)
    return body


def parse_produce_response(data: bytes):
    """v3 → [(topic, partition, error_code, base_offset)]."""
    r = _Reader(data)
    out = []
    for _ in range(r.i32()):
        topic = r.string() or ""
        for _ in range(r.i32()):
            pid = r.i32()
            err = r.i16()
            base = r.i64()
            r.i64()  # log_append_time
            out.append((topic, pid, err, base))
    r.i32()  # throttle_time
    return out


def parse_response_header(data: bytes) -> Tuple[int, bytes]:
    """→ (correlation_id, rest)."""
    if len(data) < 4:
        raise KafkaProtocolError("short response")
    return struct.unpack(">i", data[:4])[0], data[4:]


# ------------------------------------------ decode (for tests/consumers)

def decode_record_batch(data: bytes):
    """RecordBatch bytes → (crc_ok, [(key, value|None, ts_ms,
    offset_delta)], last_offset_delta). value None = tombstone
    (compacted topics); offset deltas matter on compacted batches where
    records were removed."""
    r = _Reader(data)
    r.i64()  # base offset
    r.i32()  # batch length
    r.i32()  # partition leader epoch
    magic = r.i8()
    if magic != 2:
        raise KafkaProtocolError(f"unsupported magic {magic}")
    crc = struct.unpack(">I", r.take(4))[0]
    post = data[r.pos:]
    crc_ok = crc32c(post) == crc
    r.i16()  # attributes
    last_offset_delta = r.i32()
    base_ts = r.i64()
    r.i64()  # max ts
    r.i64()  # producer id
    r.i16()  # producer epoch
    r.i32()  # base sequence
    n = r.i32()
    records = []
    for _ in range(n):
        r.varint()  # record length
        r.i8()      # attributes
        ts_delta = r.varint()
        offset_delta = r.varint()
        klen = r.varint()
        key = bytes(r.take(klen)) if klen >= 0 else None
        vlen = r.varint()
        value = bytes(r.take(vlen)) if vlen >= 0 else None  # tombstone
        for _ in range(r.varint()):  # headers
            hk = r.varint()
            r.take(hk)
            hv = r.varint()
            if hv >= 0:
                r.take(hv)
        records.append((key, value, base_ts + ts_delta, offset_delta))
    return crc_ok, records, last_offset_delta


# ------------------------------------------------ consumer-side APIs

API_FETCH = 1
API_LIST_OFFSETS = 2


def list_offsets_request(parts: Dict[str, List[int]],
                         timestamp: int = -1) -> bytes:
    """v1 body: -1 = latest, -2 = earliest."""
    body = struct.pack(">i", -1)  # replica id
    body += struct.pack(">i", len(parts))
    for topic, pids in parts.items():
        body += _str(topic)
        body += struct.pack(">i", len(pids))
        for pid in pids:
            body += struct.pack(">iq", pid, timestamp)
    return body


def parse_list_offsets_response(data: bytes):
    """v1 → [(topic, partition, error, offset)]."""
    r = _Reader(data)
    out = []
    for _ in range(r.i32()):
        topic = r.string() or ""
        for _ in range(r.i32()):
            pid = r.i32()
            err = r.i16()
            r.i64()  # timestamp
            off = r.i64()
            out.append((topic, pid, err, off))
    return out


def fetch_request(parts: Dict[str, List[Tuple[int, int]]],
                  max_wait_ms: int = 500, min_bytes: int = 1,
                  max_bytes: int = 4 * 1024 * 1024) -> bytes:
    """v4 body; parts: {topic: [(partition, fetch_offset)]}."""
    body = struct.pack(">iiiib", -1, max_wait_ms, min_bytes,
                       max_bytes, 0)
    body += struct.pack(">i", len(parts))
    for topic, plist in parts.items():
        body += _str(topic)
        body += struct.pack(">i", len(plist))
        for pid, off in plist:
            body += struct.pack(">iqi", pid, off, max_bytes)
    return body


def parse_fetch_response(data: bytes):
    """v4 → [(topic, partition, error, high_watermark, record_set)]."""
    r = _Reader(data)
    r.i32()  # throttle
    out = []
    for _ in range(r.i32()):
        topic = r.string() or ""
        for _ in range(r.i32()):
            pid = r.i32()
            err = r.i16()
            hw = r.i64()
            r.i64()  # last stable offset
            for _ in range(r.i32()):  # aborted txns
                r.i64()
                r.i64()
            blen = r.i32()
            record_set = r.take(blen) if blen > 0 else b""
            out.append((topic, pid, err, hw, bytes(record_set)))
    return out


def iter_record_batches(record_set: bytes):
    """A fetch record_set may concatenate several RecordBatches; yield
    (base_offset, crc_ok, records, next_offset) per batch —
    next_offset honors lastOffsetDelta, NOT len(records), so compacted
    batches (records removed mid-batch) still advance correctly."""
    pos = 0
    n = len(record_set)
    while pos + 17 <= n:
        base_offset = struct.unpack_from(">q", record_set, pos)[0]
        batch_len = struct.unpack_from(">i", record_set, pos + 8)[0]
        end = pos + 12 + batch_len
        if batch_len <= 0 or end > n:
            return  # partial batch at the tail (broker may truncate)
        crc_ok, records, last_delta = \
            decode_record_batch(record_set[pos:end])
        yield base_offset, crc_ok, records, base_offset + last_delta + 1
        pos = end


# ------------------------------------------- consumer group protocol
# (librdkafka's group coordination surface: FindCoordinator, Join,
# Sync, Heartbeat, OffsetCommit/Fetch, LeaveGroup)

API_OFFSET_COMMIT = 8
API_OFFSET_FETCH = 9
API_FIND_COORDINATOR = 10
API_JOIN_GROUP = 11
API_HEARTBEAT = 12
API_LEAVE_GROUP = 13
API_SYNC_GROUP = 14

# error codes the group state machine reacts to
ERR_OFFSET_OUT_OF_RANGE = 1
ERR_ILLEGAL_GENERATION = 22
ERR_UNKNOWN_MEMBER_ID = 25
ERR_REBALANCE_IN_PROGRESS = 27


def find_coordinator_request(group: str) -> bytes:
    """v0 body: just the group id."""
    return _str(group)


def parse_find_coordinator_response(data: bytes):
    """v0 → (error, node_id, host, port)."""
    r = _Reader(data)
    err = r.i16()
    node = r.i32()
    host = r.string() or ""
    port = r.i32()
    return err, node, host, port


def consumer_metadata(topics: List[str]) -> bytes:
    """Consumer protocol subscription metadata (version 0)."""
    out = struct.pack(">hi", 0, len(topics))
    for t in topics:
        out += _str(t)
    out += struct.pack(">i", -1)  # userdata (null bytes)
    return out


def parse_consumer_metadata(data: bytes) -> List[str]:
    r = _Reader(data)
    r.i16()  # version
    return [r.string() or "" for _ in range(r.i32())]


def consumer_assignment(parts: Dict[str, List[int]]) -> bytes:
    """Consumer protocol assignment (version 0)."""
    out = struct.pack(">hi", 0, len(parts))
    for topic, pids in sorted(parts.items()):
        out += _str(topic)
        out += struct.pack(">i", len(pids))
        for pid in pids:
            out += struct.pack(">i", pid)
    out += struct.pack(">i", -1)  # userdata
    return out


def parse_consumer_assignment(data: bytes) -> Dict[str, List[int]]:
    if not data:
        return {}
    r = _Reader(data)
    r.i16()  # version
    out: Dict[str, List[int]] = {}
    for _ in range(r.i32()):
        topic = r.string() or ""
        out[topic] = [r.i32() for _ in range(r.i32())]
    return out


def join_group_request(group: str, session_timeout_ms: int,
                       member_id: str, topics: List[str]) -> bytes:
    """v0 body; one supported assignor: range."""
    body = _str(group)
    body += struct.pack(">i", session_timeout_ms)
    body += _str(member_id)
    body += _str("consumer")
    body += struct.pack(">i", 1)  # one protocol
    body += _str("range")
    body += _bytes(consumer_metadata(topics))
    return body


def parse_join_group_response(data: bytes):
    """v0 → (err, generation, protocol, leader, member_id,
    members=[(member_id, metadata_bytes)])."""
    r = _Reader(data)
    err = r.i16()
    generation = r.i32()
    protocol = r.string() or ""
    leader = r.string() or ""
    member_id = r.string() or ""
    members = []
    for _ in range(r.i32()):
        mid = r.string() or ""
        n = r.i32()
        meta = bytes(r.take(n)) if n > 0 else b""
        members.append((mid, meta))
    return err, generation, protocol, leader, member_id, members


def sync_group_request(group: str, generation: int, member_id: str,
                       assignments: List[Tuple[str, bytes]]) -> bytes:
    """v0; non-leaders send an empty assignment list."""
    body = _str(group)
    body += struct.pack(">i", generation)
    body += _str(member_id)
    body += struct.pack(">i", len(assignments))
    for mid, blob in assignments:
        body += _str(mid)
        body += _bytes(blob)
    return body


def parse_sync_group_response(data: bytes):
    """v0 → (err, assignment_bytes)."""
    r = _Reader(data)
    err = r.i16()
    n = r.i32()
    return err, (bytes(r.take(n)) if n > 0 else b"")


def heartbeat_request(group: str, generation: int,
                      member_id: str) -> bytes:
    return _str(group) + struct.pack(">i", generation) + _str(member_id)


def parse_error_response(data: bytes) -> int:
    return _Reader(data).i16()


def leave_group_request(group: str, member_id: str) -> bytes:
    return _str(group) + _str(member_id)


def offset_commit_request(group: str, generation: int, member_id: str,
                          offsets: Dict[Tuple[str, int], int]) -> bytes:
    """v2 body; offsets: {(topic, partition): next_offset}."""
    body = _str(group)
    body += struct.pack(">i", generation)
    body += _str(member_id)
    body += struct.pack(">q", -1)  # retention: broker default
    topics: Dict[str, List[Tuple[int, int]]] = {}
    for (topic, pid), off in offsets.items():
        topics.setdefault(topic, []).append((pid, off))
    body += struct.pack(">i", len(topics))
    for topic, plist in topics.items():
        body += _str(topic)
        body += struct.pack(">i", len(plist))
        for pid, off in plist:
            body += struct.pack(">iq", pid, off)
            body += _str("")  # metadata
    return body


def parse_offset_commit_response(data: bytes):
    """v2 → [(topic, partition, error)]."""
    r = _Reader(data)
    out = []
    for _ in range(r.i32()):
        topic = r.string() or ""
        for _ in range(r.i32()):
            out.append((topic, r.i32(), r.i16()))
    return out


def offset_fetch_request(group: str,
                         parts: Dict[str, List[int]]) -> bytes:
    """v1 body (committed offsets from the coordinator)."""
    body = _str(group)
    body += struct.pack(">i", len(parts))
    for topic, pids in parts.items():
        body += _str(topic)
        body += struct.pack(">i", len(pids))
        for pid in pids:
            body += struct.pack(">i", pid)
    return body


def parse_offset_fetch_response(data: bytes):
    """v1 → [(topic, partition, offset, error)] (offset -1 = none)."""
    r = _Reader(data)
    out = []
    for _ in range(r.i32()):
        topic = r.string() or ""
        for _ in range(r.i32()):
            pid = r.i32()
            off = r.i64()
            r.string()  # metadata
            out.append((topic, pid, off, r.i16()))
    return out


def range_assign(members: List[Tuple[str, bytes]],
                 partitions: Dict[str, List[int]]
                 ) -> Dict[str, Dict[str, List[int]]]:
    """The range assignor (leader side): per topic, contiguous
    partition spans to subscribed members in member-id order."""
    out: Dict[str, Dict[str, List[int]]] = {m: {} for m, _ in members}
    subs: Dict[str, List[str]] = {}
    for mid, meta in members:
        try:
            topics = parse_consumer_metadata(meta)
        except KafkaProtocolError:
            topics = []
        for t in topics:
            subs.setdefault(t, []).append(mid)
    for topic, mids in subs.items():
        pids = sorted(partitions.get(topic, []))
        if not pids:
            continue
        mids = sorted(mids)
        per = len(pids) // len(mids)
        extra = len(pids) % len(mids)
        at = 0
        for i, mid in enumerate(mids):
            take = per + (1 if i < extra else 0)
            if take:
                out[mid][topic] = pids[at:at + take]
            at += take
    return out
