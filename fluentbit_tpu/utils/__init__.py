"""Foundation utilities — compression, crypto, encoding.

Reference: src/flb_gzip.c, src/flb_snappy.c, src/flb_zstd.c,
src/flb_compression.c (payload compression for outputs/forward);
src/flb_crypto.c, src/flb_hmac.c, src/flb_base64.c, src/flb_uri.c,
src/flb_utf8.c (hashing, signing, encoding). Python's stdlib provides
gzip/zlib/base64/hmac/hashlib; snappy is implemented from scratch in
``utils/snappy.py`` (block + framing formats); zstd and lz4 bind the
system libraries via ctypes (``utils/zstd.py`` / ``utils/lz4.py`` —
the src/flb_zstd.c role) and fail with a clear CompressionError when
the shared library is genuinely absent.
"""

from __future__ import annotations

import base64 as _b64
import gzip as _gzip
import hashlib
import hmac as _hmac
import urllib.parse as _url
import zlib
from typing import Optional


class CompressionError(ValueError):
    pass


def compress(algo: str, data: bytes, level: int = 6) -> bytes:
    """flb_compression_compress equivalent."""
    a = (algo or "gzip").lower()
    if a == "gzip":
        return _gzip.compress(data, compresslevel=level)
    if a in ("zlib", "deflate"):
        return zlib.compress(data, level)
    if a == "snappy":
        from . import snappy as _snappy
        return _snappy.compress(data)
    if a in ("zstd", "lz4"):
        from . import lz4 as _lz4
        from . import zstd as _zstd
        mod = _zstd if a == "zstd" else _lz4
        try:
            return mod.compress(data)
        except OSError as e:
            raise CompressionError(f"{a} unavailable: {e}") from e
        except ValueError as e:
            raise CompressionError(str(e)) from e
    raise CompressionError(f"unknown compression algorithm {algo!r}")


def decompress(algo: str, data: bytes) -> bytes:
    a = (algo or "gzip").lower()
    if a == "gzip":
        return _gzip.decompress(data)
    if a in ("zlib", "deflate"):
        return zlib.decompress(data)
    if a == "snappy":
        from . import snappy as _snappy
        return _snappy.decompress(data)
    if a in ("zstd", "lz4"):
        from . import lz4 as _lz4
        from . import zstd as _zstd
        mod = _zstd if a == "zstd" else _lz4
        try:
            return mod.decompress(data)
        except OSError as e:
            raise CompressionError(f"{a} unavailable: {e}") from e
        except ValueError as e:
            raise CompressionError(str(e)) from e
    raise CompressionError(f"unknown compression algorithm {algo!r}")


def compression_available(algo: str) -> bool:
    """Init-time probe so a configured codec missing from the host
    fails at startup, not on every flush."""
    a = (algo or "").lower()
    if a in ("gzip", "zlib", "deflate", "snappy"):
        return True
    if a == "zstd":
        from . import zstd as _zstd
        return _zstd.available()
    if a == "lz4":
        from . import lz4 as _lz4
        return _lz4.available()
    return False


def crc32(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def plain_http_request(host: str, port: int, method: str, path: str,
                       headers=None, body: bytes = b"",
                       timeout: float = 2.0):
    """Minimal blocking HTTP/1.1 request → (status, body) or None on
    socket failure. The shared helper for metadata-style fetches
    (filter_kubernetes kube_url, filter_aws IMDS, filter_ecs) — a
    status+body view over sync_http_request."""
    got = sync_http_request(host, port, method, path, headers=headers,
                            body=body, timeout=timeout)
    if got is None:
        return None
    status, _hdrs, resp = got
    return status, resp


def sync_http_request(host: str, port: int, method: str, path: str,
                      headers=None, body: bytes = b"", tls: bool = False,
                      tls_verify: bool = True, timeout: float = 10.0,
                      max_bytes: int = 64 * 1024 * 1024,
                      tls_ca_file: Optional[str] = None):
    """Blocking HTTP/1.1 request with optional TLS →
    (status, headers_dict, body) or None. The synchronous-upstream
    analogue (reference flb_stream_disable_async_mode +
    flb_http_client, used by control-plane style init-time calls:
    out_calyptia api_agent_create, filter_nightfall scan_log).
    ``tls_ca_file`` pins a private CA (kubernetes service-account
    ca.crt)."""
    import socket as _socket
    import ssl as _ssl

    try:
        s = _socket.create_connection((host, port), timeout=timeout)
        if tls:
            ctx = _ssl.create_default_context(cafile=tls_ca_file)
            if not tls_verify:
                ctx.check_hostname = False
                ctx.verify_mode = _ssl.CERT_NONE
            s = ctx.wrap_socket(s, server_hostname=host)
        req = [f"{method} {path} HTTP/1.1", f"Host: {host}:{port}",
               "Connection: close", f"Content-Length: {len(body)}"]
        for k, v in (headers or {}).items():
            req.append(f"{k}: {v}")
        s.sendall(("\r\n".join(req) + "\r\n\r\n").encode() + body)
        data = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            data += chunk
            if len(data) > max_bytes:
                # a response past the cap is abandoned, not truncated —
                # callers must never see a silently cut body
                s.close()
                return None
        s.close()
        head, _, resp = data.partition(b"\r\n\r\n")
        lines = head.split(b"\r\n")
        status = int(lines[0].split(b" ", 2)[1])
        hdrs = {}
        for line in lines[1:]:
            k, _, v = line.partition(b":")
            hdrs[k.strip().decode("latin-1").lower()] = \
                v.strip().decode("latin-1")
        if hdrs.get("transfer-encoding", "").lower() == "chunked":
            out, rest = b"", resp
            while rest:
                size_line, _, rest = rest.partition(b"\r\n")
                size = int(size_line.split(b";")[0], 16)
                if size == 0:
                    break
                out += rest[:size]
                rest = rest[size + 2:]
            resp = out
        return status, hdrs, resp
    except (OSError, ValueError, IndexError, _ssl.SSLError):
        return None


# -- crypto (flb_crypto/flb_hmac: SHA-family digests + HMAC signing) --

_DIGESTS = {"sha256", "sha512", "sha1", "md5", "sha384", "sha224"}


def digest(algo: str, data: bytes) -> bytes:
    a = algo.lower().replace("-", "")
    if a not in _DIGESTS:
        raise ValueError(f"unsupported digest {algo!r}")
    return hashlib.new(a, data).digest()


def hmac_sign(algo: str, key: bytes, data: bytes) -> bytes:
    a = algo.lower().replace("-", "")
    if a not in _DIGESTS:
        raise ValueError(f"unsupported digest {algo!r}")
    return _hmac.new(key, data, a).digest()


# -- encoding (flb_base64 / flb_uri) --

def base64_encode(data: bytes) -> bytes:
    return _b64.b64encode(data)


def base64_decode(data: bytes) -> bytes:
    return _b64.b64decode(data)


def uri_encode(text: str, safe: str = "/") -> str:
    return _url.quote(text, safe=safe)


def uri_decode(text: str) -> str:
    return _url.unquote(text)


async def async_plain_http_request(host: str, port: int, method: str,
                                   path: str, headers=None,
                                   body: bytes = b"",
                                   timeout: float = 3.0):
    """Async twin of plain_http_request — for interval collectors that
    run ON the engine loop and must never block it."""
    import asyncio

    host_hdr = host if port in (80, None) else f"{host}:{port}"
    writer = None
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout
        )
        req = [f"{method} {path} HTTP/1.1", f"Host: {host_hdr}",
               "Connection: close", f"Content-Length: {len(body)}"]
        for k, v in (headers or {}).items():
            req.append(f"{k}: {v}")
        writer.write(("\r\n".join(req) + "\r\n\r\n").encode() + body)
        await asyncio.wait_for(writer.drain(), timeout)
        data = b""
        while True:
            chunk = await asyncio.wait_for(reader.read(65536), timeout)
            if not chunk:
                break
            data += chunk
        head, _, resp = data.partition(b"\r\n\r\n")
        return int(head.split(b" ", 2)[1]), resp
    except (OSError, ValueError, IndexError, asyncio.TimeoutError):
        return None
    finally:
        if writer is not None:  # never leak the transport on timeout
            try:
                writer.close()
            except Exception:
                pass


def uri_field(uri: str, index: int) -> Optional[str]:
    """flb_uri_get: the Nth path segment of a URI (1-based)."""
    parts = [p for p in uri.split("?")[0].split("/") if p]
    return parts[index - 1] if 1 <= index <= len(parts) else None
