"""LZ4 frame codec bound to the system liblz4 via ctypes.

Reference: fluent-bit links lz4 through its vendored deps (e.g. the
chunkio/journal paths); the compression surface here mirrors
`utils/zstd.py` — one-shot frame compress/decompress via
LZ4F_compressFrame / LZ4F_decompress with the frame API, so output
interoperates with the standard `lz4` CLI and libraries.
"""

from __future__ import annotations

import ctypes
import ctypes.util
from typing import Optional

_lib: Optional[ctypes.CDLL] = None
_load_error: Optional[str] = None

_LZ4F_VERSION = 100


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_error
    if _lib is not None or _load_error is not None:
        return _lib
    name = ctypes.util.find_library("lz4") or "liblz4.so.1"
    try:
        lib = ctypes.CDLL(name)
        lib.LZ4F_compressFrameBound.restype = ctypes.c_size_t
        lib.LZ4F_compressFrameBound.argtypes = [ctypes.c_size_t,
                                                ctypes.c_void_p]
        lib.LZ4F_compressFrame.restype = ctypes.c_size_t
        lib.LZ4F_compressFrame.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t, ctypes.c_void_p,
            ctypes.c_size_t, ctypes.c_void_p]
        lib.LZ4F_isError.restype = ctypes.c_uint
        lib.LZ4F_isError.argtypes = [ctypes.c_size_t]
        lib.LZ4F_createDecompressionContext.restype = ctypes.c_size_t
        lib.LZ4F_createDecompressionContext.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_uint]
        lib.LZ4F_freeDecompressionContext.restype = ctypes.c_size_t
        lib.LZ4F_freeDecompressionContext.argtypes = [ctypes.c_void_p]
        lib.LZ4F_decompress.restype = ctypes.c_size_t
        lib.LZ4F_decompress.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_size_t), ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_size_t), ctypes.c_void_p]
    except (OSError, AttributeError) as e:
        _load_error = str(e)
        return None
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def compress(data: bytes) -> bytes:
    lib = _load()
    if lib is None:
        raise OSError(f"liblz4 unavailable: {_load_error}")
    bound = lib.LZ4F_compressFrameBound(len(data), None)
    dst = ctypes.create_string_buffer(bound)
    n = lib.LZ4F_compressFrame(dst, bound, data, len(data), None)
    if lib.LZ4F_isError(n):
        raise ValueError("lz4 frame compression failed")
    return dst.raw[:n]


def decompress(data: bytes,
               max_output: int = 256 * 1024 * 1024) -> bytes:
    lib = _load()
    if lib is None:
        raise OSError(f"liblz4 unavailable: {_load_error}")
    ctx = ctypes.c_void_p()
    if lib.LZ4F_isError(
            lib.LZ4F_createDecompressionContext(
                ctypes.byref(ctx), _LZ4F_VERSION)):
        raise ValueError("lz4 context creation failed")
    try:
        out = bytearray()
        src = ctypes.create_string_buffer(data, len(data))  # one copy
        src_off = 0
        code = None
        chunk = ctypes.create_string_buffer(256 * 1024)
        while src_off < len(data):
            dst_size = ctypes.c_size_t(len(chunk))
            src_size = ctypes.c_size_t(len(data) - src_off)
            code = lib.LZ4F_decompress(
                ctx, chunk, ctypes.byref(dst_size),
                ctypes.byref(src, src_off), ctypes.byref(src_size),
                None)
            if lib.LZ4F_isError(code):
                raise ValueError("corrupt lz4 frame")
            if src_size.value == 0 and dst_size.value == 0:
                raise ValueError("lz4 frame stalled (truncated input)")
            out += chunk.raw[:dst_size.value]
            if len(out) > max_output:
                raise ValueError("lz4 output exceeds limit")
            src_off += src_size.value
            if code == 0 and src_off >= len(data):
                break
        # hint code 0 means the frame completed; anything else at EOF
        # is a truncated frame (the silent-partial-output trap)
        if code != 0:
            raise ValueError("truncated lz4 frame")
        return bytes(out)
    finally:
        lib.LZ4F_freeDecompressionContext(ctx)
