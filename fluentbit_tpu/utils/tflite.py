"""TF-Lite model loader + batched float32 executor.

Reference: plugins/filter_tensorflow/tensorflow.c drives the vendored
TF-Lite C API (TfLiteModelCreateFromFile → Invoke); here the .tflite
FlatBuffers schema (tensorflow/lite/schema/schema.fbs) is read with
`utils/flatbuf.py` and a float32 subset of the builtin operators is
executed with numpy over a whole BATCH of inputs at once — the filter
stacks every record in the chunk into one forward pass instead of one
Invoke per record.

Field ids below follow schema.fbs declaration order (flatbuffers
assigns id = position unless annotated). Supported builtins:
FULLY_CONNECTED, CONV_2D (NHWC), MAX_POOL_2D, AVERAGE_POOL_2D, ADD,
MUL, SUB, RELU, RELU6, LOGISTIC, TANH, SOFTMAX, RESHAPE, MEAN.
Anything else raises TFLiteError naming the op, so unsupported models
fail loudly at load (the reference fails inside TfLiteInterpreter the
same way).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from .flatbuf import root

# TensorType enum (schema.fbs)
FLOAT32, INT32, UINT8, INT64 = 0, 2, 3, 4

# BuiltinOperator codes (schema.fbs)
OP_ADD = 0
OP_AVERAGE_POOL_2D = 1
OP_CONV_2D = 3
OP_FULLY_CONNECTED = 9
OP_LOGISTIC = 14
OP_MAX_POOL_2D = 17
OP_MUL = 18
OP_RELU = 19
OP_RELU6 = 21
OP_RESHAPE = 22
OP_SOFTMAX = 25
OP_TANH = 28
OP_SUB = 41
OP_MEAN = 40

_OP_NAMES = {
    OP_ADD: "ADD", OP_AVERAGE_POOL_2D: "AVERAGE_POOL_2D",
    OP_CONV_2D: "CONV_2D", OP_FULLY_CONNECTED: "FULLY_CONNECTED",
    OP_LOGISTIC: "LOGISTIC", OP_MAX_POOL_2D: "MAX_POOL_2D",
    OP_MUL: "MUL", OP_RELU: "RELU", OP_RELU6: "RELU6",
    OP_RESHAPE: "RESHAPE", OP_SOFTMAX: "SOFTMAX", OP_TANH: "TANH",
    OP_SUB: "SUB", OP_MEAN: "MEAN",
}

# ActivationFunctionType enum
ACT_NONE, ACT_RELU, ACT_RELU_N1_TO_1, ACT_RELU6, ACT_TANH = 0, 1, 2, 3, 4


class TFLiteError(ValueError):
    pass


def _activation(x: np.ndarray, act: int) -> np.ndarray:
    if act == ACT_NONE:
        return x
    if act == ACT_RELU:
        return np.maximum(x, 0.0)
    if act == ACT_RELU_N1_TO_1:
        return np.clip(x, -1.0, 1.0)
    if act == ACT_RELU6:
        return np.clip(x, 0.0, 6.0)
    if act == ACT_TANH:
        return np.tanh(x)
    raise TFLiteError(f"unsupported fused activation {act}")


class _TensorInfo:
    __slots__ = ("shape", "dtype", "buffer", "name")

    def __init__(self, shape, dtype, buffer, name):
        self.shape = shape
        self.dtype = dtype
        self.buffer = buffer
        self.name = name


class Model:
    """One loaded subgraph, runnable over a batch of inputs."""

    def __init__(self, binary: bytes):
        if len(binary) < 8:
            raise TFLiteError("truncated tflite file")
        # file_identifier "TFL3" at offset 4 (optional but emitted by
        # every converter)
        if binary[4:8] not in (b"TFL3", b"\x00\x00\x00\x00"):
            raise TFLiteError("not a TFLite flatbuffer (missing TFL3)")
        m = root(binary)
        # Model: version(0) operator_codes(1) subgraphs(2)
        # description(3) buffers(4)
        self.version = m.u32(0, 0)
        opcodes = m.table_vector(1)
        subgraphs = m.table_vector(2)
        buffers = m.table_vector(4)
        if not subgraphs:
            raise TFLiteError("model has no subgraph")
        self._builtins: List[int] = []
        for oc in opcodes:
            # OperatorCode: deprecated_builtin_code(0, i8),
            # custom_code(1), version(2), builtin_code(3, i32)
            code = oc.i32(3, 0)
            if code == 0:
                code = oc.i8(0, 0)
            self._builtins.append(code)
        g = subgraphs[0]
        # SubGraph: tensors(0) inputs(1) outputs(2) operators(3) name(4)
        self.tensors: List[_TensorInfo] = []
        for t in g.table_vector(0):
            # Tensor: shape(0) type(1) buffer(2) name(3) quantization(4)
            shape = t.i32_vector(0)
            dtype = t.i8(1, 0)
            bidx = t.u32(2, 0)
            data = buffers[bidx].bytes_vector(0) if bidx < len(buffers) \
                else b""
            self.tensors.append(
                _TensorInfo(shape, dtype, data, t.string(3)))
        self.inputs = g.i32_vector(1)
        self.outputs = g.i32_vector(2)
        self.operators = []
        for op in g.table_vector(3):
            # Operator: opcode_index(0) inputs(1) outputs(2)
            # builtin_options_type(3) builtin_options(4)
            idx = op.u32(0, 0)
            if idx >= len(self._builtins):
                raise TFLiteError("bad opcode index")
            code = self._builtins[idx]
            if code not in _OP_NAMES:
                raise TFLiteError(
                    f"unsupported builtin operator {code}")
            self.operators.append(
                (code, op.i32_vector(1), op.i32_vector(2),
                 op.table(4)))
        if len(self.inputs) != 1 or len(self.outputs) != 1:
            raise TFLiteError("exactly one input and one output "
                              "tensor are supported")
        ti = self.tensors[self.inputs[0]]
        if ti.dtype != FLOAT32:
            raise TFLiteError("only float32 input tensors supported")
        self.input_shape = list(ti.shape)
        self.output_shape = list(self.tensors[self.outputs[0]].shape)

    # -- constants -----------------------------------------------------

    def _const(self, idx: int) -> np.ndarray:
        t = self.tensors[idx]
        if not t.buffer:
            raise TFLiteError(
                f"tensor {idx} ({t.name}) has no constant data")
        if t.dtype == FLOAT32:
            arr = np.frombuffer(t.buffer, dtype=np.float32)
        elif t.dtype == INT32:
            arr = np.frombuffer(t.buffer, dtype=np.int32)
        elif t.dtype == INT64:
            arr = np.frombuffer(t.buffer, dtype=np.int64)
        else:
            raise TFLiteError(f"unsupported constant dtype {t.dtype}")
        return arr.reshape(t.shape) if t.shape else arr

    # -- execution -----------------------------------------------------

    def run(self, batch: np.ndarray) -> np.ndarray:
        """batch: [N, *input_shape[1:]] float32 → [N, *output[1:]]."""
        vals: Dict[int, np.ndarray] = {}
        x = np.asarray(batch, dtype=np.float32)
        per_rec = list(self.input_shape[1:])
        x = x.reshape([x.shape[0]] + per_rec)
        vals[self.inputs[0]] = x
        n = x.shape[0]
        for code, ins, outs, opts in self.operators:
            get = (lambda i: vals[i] if i in vals else self._const(i))
            if code == OP_FULLY_CONNECTED:
                a = get(ins[0])
                w = get(ins[1])  # [units, in]
                a2 = a.reshape(n, -1)
                y = a2 @ w.T
                if len(ins) > 2 and ins[2] >= 0:
                    y = y + get(ins[2])
                # FullyConnectedOptions: fused_activation_function(0)
                y = _activation(y, opts.i8(0, 0) if opts else 0)
            elif code == OP_CONV_2D:
                # optional bias is encoded as tensor index -1; get(-1)
                # would silently read an unrelated tensor
                y = self._conv2d(get(ins[0]), get(ins[1]),
                                 get(ins[2])
                                 if len(ins) > 2 and ins[2] >= 0
                                 else None,
                                 opts)
            elif code in (OP_MAX_POOL_2D, OP_AVERAGE_POOL_2D):
                y = self._pool(get(ins[0]), opts,
                               avg=(code == OP_AVERAGE_POOL_2D))
            elif code == OP_ADD:
                y = _activation(get(ins[0]) + get(ins[1]),
                                opts.i8(0, 0) if opts else 0)
            elif code == OP_SUB:
                y = _activation(get(ins[0]) - get(ins[1]),
                                opts.i8(0, 0) if opts else 0)
            elif code == OP_MUL:
                y = _activation(get(ins[0]) * get(ins[1]),
                                opts.i8(0, 0) if opts else 0)
            elif code == OP_RELU:
                y = np.maximum(get(ins[0]), 0.0)
            elif code == OP_RELU6:
                y = np.clip(get(ins[0]), 0.0, 6.0)
            elif code == OP_LOGISTIC:
                y = 1.0 / (1.0 + np.exp(-get(ins[0])))
            elif code == OP_TANH:
                y = np.tanh(get(ins[0]))
            elif code == OP_SOFTMAX:
                # SoftmaxOptions: beta(0) — softmax(beta * x)
                a = get(ins[0]) * (opts.f32(0, 1.0) if opts else 1.0)
                e = np.exp(a - a.max(axis=-1, keepdims=True))
                y = e / e.sum(axis=-1, keepdims=True)
            elif code == OP_RESHAPE:
                shape = (list(get(ins[1]).astype(int))
                         if len(ins) > 1 else
                         list(opts.i32_vector(0)) if opts else [])
                if not shape:
                    raise TFLiteError("reshape without target shape")
                shape = [n if i == 0 else int(s)
                         for i, s in enumerate(shape)]
                y = get(ins[0]).reshape(shape)
            elif code == OP_MEAN:
                axes = tuple(int(a) for a in get(ins[1]).ravel())
                y = get(ins[0]).mean(axis=axes)
            else:  # pragma: no cover — load() already rejected it
                raise TFLiteError(
                    f"unsupported op {_OP_NAMES.get(code, code)}")
            vals[outs[0]] = np.asarray(y, dtype=np.float32)
        return vals[self.outputs[0]].reshape(n, -1)

    @staticmethod
    def _conv2d(x, w, b, opts):
        # Conv2DOptions: padding(0) stride_w(1) stride_h(2)
        # fused_activation_function(3)
        padding = opts.i8(0, 0) if opts else 0  # 0=SAME 1=VALID
        sw = opts.i32(1, 1) if opts else 1
        sh = opts.i32(2, 1) if opts else 1
        act = opts.i8(3, 0) if opts else 0
        n, h, wd, cin = x.shape
        co, kh, kw, _ = w.shape  # [out, kh, kw, in]
        if padding == 0:  # SAME
            oh = -(-h // sh)
            ow = -(-wd // sw)
            ph = max(0, (oh - 1) * sh + kh - h)
            pw = max(0, (ow - 1) * sw + kw - wd)
            x = np.pad(x, ((0, 0), (ph // 2, ph - ph // 2),
                           (pw // 2, pw - pw // 2), (0, 0)))
            h, wd = x.shape[1], x.shape[2]
        oh = (h - kh) // sh + 1
        ow = (wd - kw) // sw + 1
        out = np.zeros((n, oh, ow, co), dtype=np.float32)
        for i in range(kh):
            for j in range(kw):
                patch = x[:, i:i + oh * sh:sh, j:j + ow * sw:sw, :]
                out += np.einsum("nhwc,oc->nhwo", patch, w[:, i, j, :])
        if b is not None:
            out = out + b
        return _activation(out, act)

    @staticmethod
    def _pool(x, opts, avg: bool):
        # Pool2DOptions: padding(0) stride_w(1) stride_h(2)
        # filter_width(3) filter_height(4) fused_activation(5)
        padding = opts.i8(0, 0) if opts else 0  # 0=SAME 1=VALID
        sw = opts.i32(1, 1) if opts else 1
        sh = opts.i32(2, 1) if opts else 1
        fw = opts.i32(3, 1) if opts else 1
        fh = opts.i32(4, 1) if opts else 1
        act = opts.i8(5, 0) if opts else 0
        n, h, wd, c = x.shape
        counts = None
        if padding == 0:  # SAME: ceil-div output, edge padding
            oh = -(-h // sh)
            ow = -(-wd // sw)
            ph = max(0, (oh - 1) * sh + fh - h)
            pw = max(0, (ow - 1) * sw + fw - wd)
            pad_spec = ((0, 0), (ph // 2, ph - ph // 2),
                        (pw // 2, pw - pw // 2), (0, 0))
            if avg:
                # TFLite SAME avg pool averages VALID elements only
                ones = np.pad(np.ones_like(x), pad_spec)
                x = np.pad(x, pad_spec)
                counts = ones
            else:
                x = np.pad(x, pad_spec,
                           constant_values=-np.float32(np.inf))
            h, wd = x.shape[1], x.shape[2]
        oh = (h - fh) // sh + 1
        ow = (wd - fw) // sw + 1
        stack = []
        cstack = []
        for i in range(fh):
            for j in range(fw):
                stack.append(x[:, i:i + oh * sh:sh,
                               j:j + ow * sw:sw, :])
                if counts is not None:
                    cstack.append(counts[:, i:i + oh * sh:sh,
                                         j:j + ow * sw:sw, :])
        block = np.stack(stack)
        if avg:
            if counts is not None:
                y = block.sum(axis=0) / np.maximum(
                    np.stack(cstack).sum(axis=0), 1.0)
            else:
                y = block.mean(axis=0)
        else:
            y = block.max(axis=0)
        return _activation(y, act)
