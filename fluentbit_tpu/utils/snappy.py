"""Snappy codec — block format + framing format, from scratch.

Reference: src/flb_snappy.c wraps the vendored C++ lib/snappy-fef67ac;
this build implements the format directly (format_description.txt and
framing_format.txt from the public spec) so the remote-write plugins
(plugins/in_prometheus_remote_write, plugins/out_prometheus_remote_write)
and forward's snappy option need no vendored runtime.

Block format: a varint32 preamble with the uncompressed length, then a
sequence of elements tagged by the low 2 bits of the first byte —
00 literal (length in the high 6 bits, or 60..63 selecting 1..4
little-endian length bytes), 01 copy with 3-bit length + 11-bit offset,
10 copy with 6-bit length + 16-bit offset, 11 copy with 32-bit offset.

Framing format: 4-byte chunk headers (type + 24-bit length); stream
identifier chunk 0xFF "sNaPpY", compressed (0x00) / uncompressed (0x01)
data chunks carrying a masked CRC-32C of the uncompressed data.
"""

from __future__ import annotations

import struct

_MAX_BLOCK = 65536
_HASH_BITS = 14
_HASH_SIZE = 1 << _HASH_BITS


class SnappyError(ValueError):
    pass


# ------------------------------------------------------------ varint

def _put_varint(n: int, out: bytearray) -> None:
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)


def _get_varint(data, pos: int):
    shift = 0
    result = 0
    while True:
        if pos >= len(data):
            raise SnappyError("truncated varint preamble")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 35:
            raise SnappyError("varint preamble overflow")


# -------------------------------------------------------- decompress

def decompress(data: bytes) -> bytes:
    """Snappy block-format decode (format_description.txt §2-4)."""
    if not isinstance(data, (bytes, bytearray, memoryview)):
        raise TypeError("snappy.decompress expects bytes")
    data = bytes(data)
    expected, pos = _get_varint(data, 0)
    # no element emits more than 64 bytes per 3 input bytes — a larger
    # declared size can never be honest
    if expected > (len(data) * 64) // 3 + 64:
        raise SnappyError("preamble length impossible for input size")
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            length = tag >> 2
            if length >= 60:
                extra = length - 59
                if pos + extra > n:
                    raise SnappyError("truncated literal length")
                length = int.from_bytes(data[pos:pos + extra], "little")
                pos += extra
            length += 1
            if pos + length > n:
                raise SnappyError("truncated literal body")
            if len(out) + length > expected:
                # bound the expansion as we go: crafted bodies must not
                # allocate past the declared size (network-facing path)
                raise SnappyError("output exceeds preamble length")
            out += data[pos:pos + length]
            pos += length
            continue
        if kind == 1:  # copy, 1-byte offset
            if pos >= n:
                raise SnappyError("truncated copy-1 offset")
            length = 4 + ((tag >> 2) & 0x7)
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:  # copy, 2-byte offset
            if pos + 2 > n:
                raise SnappyError("truncated copy-2 offset")
            length = (tag >> 2) + 1
            offset = data[pos] | (data[pos + 1] << 8)
            pos += 2
        else:  # copy, 4-byte offset
            if pos + 4 > n:
                raise SnappyError("truncated copy-4 offset")
            length = (tag >> 2) + 1
            offset = int.from_bytes(data[pos:pos + 4], "little")
            pos += 4
        if offset == 0 or offset > len(out):
            raise SnappyError("copy offset out of range")
        if len(out) + length > expected:
            raise SnappyError("output exceeds preamble length")
        # overlapping copies are legal and meaningful (RLE-style)
        if offset >= length:
            start = len(out) - offset
            out += out[start:start + length]
        else:
            start = len(out) - offset
            for i in range(length):
                out.append(out[start + i])
    if len(out) != expected:
        raise SnappyError(
            f"decompressed length {len(out)} != preamble {expected}")
    return bytes(out)


# ---------------------------------------------------------- compress

def _emit_literal(data, start: int, end: int, out: bytearray) -> None:
    length = end - start
    if length <= 0:
        return
    n = length - 1
    if n < 60:
        out.append(n << 2)
    elif n < (1 << 8):
        out.append(60 << 2)
        out.append(n)
    elif n < (1 << 16):
        out.append(61 << 2)
        out += n.to_bytes(2, "little")
    elif n < (1 << 24):
        out.append(62 << 2)
        out += n.to_bytes(3, "little")
    else:
        out.append(63 << 2)
        out += n.to_bytes(4, "little")
    out += data[start:end]


def _emit_copy(offset: int, length: int, out: bytearray) -> None:
    # copy-2 carries length 1..64; split longer matches
    while length > 64:
        out.append((63 << 2) | 2)
        out += offset.to_bytes(2, "little")
        length -= 64
    if 4 <= length <= 11 and offset < 2048:
        out.append(((offset >> 8) << 5) | ((length - 4) << 2) | 1)
        out.append(offset & 0xFF)
    else:
        out.append(((length - 1) << 2) | 2)
        out += offset.to_bytes(2, "little")


def _compress_block(data: bytes, out: bytearray) -> None:
    n = len(data)
    if n < 4:
        _emit_literal(data, 0, n, out)
        return
    table = [0] * _HASH_SIZE
    # table stores pos+1 (0 == empty)
    shift = 32 - _HASH_BITS
    lit_start = 0
    pos = 0
    limit = n - 3
    u32 = struct.unpack_from
    while pos < limit:
        cur = u32("<I", data, pos)[0]
        h = (cur * 0x1E35A7BD & 0xFFFFFFFF) >> shift
        cand = table[h] - 1
        table[h] = pos + 1
        if cand >= 0 and u32("<I", data, cand)[0] == cur:
            # extend the match
            m = pos + 4
            c = cand + 4
            while m < n and data[m] == data[c]:
                m += 1
                c += 1
            _emit_literal(data, lit_start, pos, out)
            _emit_copy(pos - cand, m - pos, out)
            pos = m
            lit_start = m
        else:
            pos += 1
    _emit_literal(data, lit_start, n, out)


def compress(data: bytes) -> bytes:
    """Snappy block-format encode (greedy hash-table matcher, the same
    strategy class as the C++ reference encoder; any spec-conforming
    stream is valid output)."""
    if not isinstance(data, (bytes, bytearray, memoryview)):
        raise TypeError("snappy.compress expects bytes")
    data = bytes(data)
    out = bytearray()
    _put_varint(len(data), out)
    for off in range(0, len(data), _MAX_BLOCK):
        _compress_block(data[off:off + _MAX_BLOCK], out)
    return bytes(out)


# ------------------------------------------------------------ crc32c

_CRC32C_POLY = 0x82F63B78
_crc_table = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ _CRC32C_POLY if _c & 1 else _c >> 1
    _crc_table.append(_c)


def crc32c(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFF
    tab = _crc_table
    for b in data:
        crc = (crc >> 8) ^ tab[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    c = crc32c(data)
    return ((c >> 15) | (c << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# ---------------------------------------------------------- framing

_STREAM_ID = b"\xff\x06\x00\x00sNaPpY"


def frame_compress(data: bytes) -> bytes:
    """Framing-format encode: stream identifier + compressed chunks."""
    out = bytearray(_STREAM_ID)
    for off in range(0, len(data), _MAX_BLOCK) or [0]:
        block = data[off:off + _MAX_BLOCK]
        body = compress(block)
        crc = _masked_crc(block).to_bytes(4, "little")
        if len(body) < len(block):
            payload = crc + body
            out.append(0x00)
        else:
            payload = crc + block
            out.append(0x01)
        out += len(payload).to_bytes(3, "little")
        out += payload
    return bytes(out)


def frame_decompress(data: bytes) -> bytes:
    """Framing-format decode with CRC-32C verification."""
    pos = 0
    n = len(data)
    out = bytearray()
    seen_id = False
    while pos < n:
        if pos + 4 > n:
            raise SnappyError("truncated frame header")
        ctype = data[pos]
        length = int.from_bytes(data[pos + 1:pos + 4], "little")
        pos += 4
        if pos + length > n:
            raise SnappyError("truncated frame body")
        body = data[pos:pos + length]
        pos += length
        if ctype == 0xFF:
            if body != _STREAM_ID[4:]:
                raise SnappyError("bad stream identifier")
            seen_id = True
        elif ctype in (0x00, 0x01):
            if not seen_id:
                raise SnappyError("data chunk before stream identifier")
            if length < 4:
                raise SnappyError("data chunk too short for CRC")
            crc = int.from_bytes(body[:4], "little")
            block = decompress(body[4:]) if ctype == 0x00 else bytes(body[4:])
            if _masked_crc(block) != crc:
                raise SnappyError("frame CRC mismatch")
            out += block
        elif 0x02 <= ctype <= 0x7F:
            raise SnappyError(f"unskippable chunk type {ctype:#x}")
        # 0x80..0xFE: skippable, ignore
    return bytes(out)
