"""systemd journal file reader — from scratch, per the documented
Journal File Format (systemd.io/JOURNAL_FILE_FORMAT).

Reference: plugins/in_systemd reads journald through libsystemd's
sd_journal API; this image has no libsystemd, but journal files are
just memory-mapped object stores, so the reader walks them directly:
header → entry-array chain → ENTRY objects → DATA objects ("KEY=value"
payloads). Supports regular AND compact layouts, and XZ / LZ4 / ZSTD
compressed payloads (lzma stdlib, liblz4/libzstd via ctypes — the same
codecs journald itself links).

Layout facts used (offsets from the object/file start):
- header: "LPKSHHRH", compatible u32, incompatible u32, state u8,
  7 reserved, 4×16-byte ids, then u64s: header_size, arena_size,
  data_hash_table offset/size, field_hash_table offset/size,
  tail_object_offset, n_objects, n_entries, tail_entry_seqnum,
  head_entry_seqnum, entry_array_offset, head/tail realtime,
  tail monotonic
- object header: type u8, flags u8, 6 reserved, size u64 (incl. hdr)
- ENTRY: seqnum, realtime, monotonic (u64×3), boot_id 16, xor_hash
  u64, then items — regular: (object_offset u64, hash u64) pairs;
  compact: u32 object offsets
- ENTRY_ARRAY: next_entry_array_offset u64, then items — u64
  (regular) or u32 (compact) entry offsets, zero-padded tail
- DATA: hash, next_hash, next_field, entry_offset,
  entry_array_offset, n_entries (u64×6), then — compact only — two
  u32s (tail entry array offset/count), then the payload
"""

from __future__ import annotations

import os
import struct
from typing import Dict, Iterator, List, Optional, Tuple

HEADER_SIGNATURE = b"LPKSHHRH"

# incompatible flags
F_COMPRESSED_XZ = 1
F_COMPRESSED_LZ4 = 2
F_KEYED_HASH = 4
F_COMPRESSED_ZSTD = 8
F_COMPACT = 16
_SUPPORTED = (F_COMPRESSED_XZ | F_COMPRESSED_LZ4 | F_KEYED_HASH
              | F_COMPRESSED_ZSTD | F_COMPACT)

# object types
OBJECT_DATA = 1
OBJECT_ENTRY = 3
OBJECT_ENTRY_ARRAY = 6

# object flags (DATA payload compression)
OBJ_XZ = 1
OBJ_LZ4 = 2
OBJ_ZSTD = 4


class JournalError(ValueError):
    pass


_lz4_lib = None


def _lz4_block_decompress(data: bytes, dst_size: int) -> bytes:
    import ctypes

    global _lz4_lib
    if _lz4_lib is None:
        import ctypes.util

        name = ctypes.util.find_library("lz4") or "liblz4.so.1"
        lib = ctypes.CDLL(name)  # cached: find_library forks ldconfig
        lib.LZ4_decompress_safe.restype = ctypes.c_int
        lib.LZ4_decompress_safe.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_int]
        _lz4_lib = lib
    dst = ctypes.create_string_buffer(dst_size)
    n = _lz4_lib.LZ4_decompress_safe(data, dst, len(data), dst_size)
    if n < 0:
        raise JournalError("corrupt LZ4 payload")
    return dst.raw[:n]


class Entry:
    __slots__ = ("seqnum", "realtime", "monotonic", "boot_id", "fields")

    def __init__(self, seqnum, realtime, monotonic, boot_id, fields):
        self.seqnum = seqnum
        self.realtime = realtime  # usec
        self.monotonic = monotonic
        self.boot_id = boot_id
        self.fields = fields  # list of (key, value) strings


def peek_header(path: str):
    """Cheap header-only read → (file_id_hex, n_entries) without
    loading the (possibly 128MB) file body — the per-poll freshness
    check. file_id survives journald's rotation renames, so it is the
    stable cursor key (the sd_journal cursor role)."""
    with open(path, "rb") as f:
        head = f.read(208)
    if len(head) < 208 or head[:8] != HEADER_SIGNATURE:
        raise JournalError(f"{path}: not a journal file")
    file_id = head[24:40].hex()
    n_entries = struct.unpack_from("<Q", head, 152)[0]
    return file_id, n_entries


class JournalFile:
    """One .journal file; `entries(skip)` iterates in write order."""

    def __init__(self, path: str):
        import mmap

        self.path = path
        # mmap, not read(): an ACTIVE journal is re-opened every
        # collect tick, and a full slurp of a multi-GB file per tick is
        # pure waste — the entry walk touches only the pages it needs
        self._f = open(path, "rb")
        try:
            self.buf = mmap.mmap(self._f.fileno(), 0,
                                 access=mmap.ACCESS_READ)
        except (ValueError, OSError):
            # empty or unmappable file: fall back to a byte snapshot
            self._f.seek(0)
            self.buf = self._f.read()
        if len(self.buf) < 208 or self.buf[:8] != HEADER_SIGNATURE:
            self.close()
            raise JournalError(f"{path}: not a journal file")
        self.incompatible = struct.unpack_from("<I", self.buf, 12)[0]
        if self.incompatible & ~_SUPPORTED:
            self.close()  # raising skips the caller's close
            raise JournalError(
                f"{path}: unsupported incompatible flags "
                f"{self.incompatible:#x}")
        self.compact = bool(self.incompatible & F_COMPACT)
        self.file_id = self.buf[24:40].hex()
        (self.header_size, self.arena_size) = struct.unpack_from(
            "<QQ", self.buf, 88)
        (self.n_objects, self.n_entries, self.tail_seqnum,
         self.head_seqnum, self.entry_array_offset) = \
            struct.unpack_from("<QQQQQ", self.buf, 144)

    # -- object plumbing ----------------------------------------------

    def _object(self, offset: int) -> Tuple[int, int, int, int]:
        """→ (type, flags, payload_start, payload_end)."""
        if offset <= 0 or offset + 16 > len(self.buf):
            raise JournalError(f"{self.path}: object offset out of range")
        otype = self.buf[offset]
        oflags = self.buf[offset + 1]
        size = struct.unpack_from("<Q", self.buf, offset + 8)[0]
        if size < 16 or offset + size > len(self.buf):
            raise JournalError(f"{self.path}: bad object size")
        return otype, oflags, offset + 16, offset + size

    def _data_payload(self, offset: int) -> bytes:
        otype, oflags, start, end = self._object(offset)
        if otype != OBJECT_DATA:
            raise JournalError(f"{self.path}: expected DATA object")
        start += 48  # six u64 bookkeeping fields
        if self.compact:
            start += 8  # two u32 tail-entry-array fields
        raw = self.buf[start:end]
        if oflags & OBJ_ZSTD:
            from . import zstd
            return zstd.decompress(bytes(raw))
        if oflags & OBJ_LZ4:
            if len(raw) < 8:
                raise JournalError("short LZ4 payload")
            dst_size = struct.unpack_from("<Q", raw, 0)[0]
            if dst_size > 256 * 1024 * 1024:
                raise JournalError("LZ4 payload too large")
            return _lz4_block_decompress(bytes(raw[8:]), dst_size)
        if oflags & OBJ_XZ:
            import lzma
            return lzma.decompress(bytes(raw))
        return bytes(raw)

    def _entry(self, offset: int) -> Entry:
        otype, _oflags, start, end = self._object(offset)
        if otype != OBJECT_ENTRY:
            raise JournalError(f"{self.path}: expected ENTRY object")
        seqnum, realtime, monotonic = struct.unpack_from(
            "<QQQ", self.buf, start)
        boot_id = bytes(self.buf[start + 24:start + 40])
        items_at = start + 48  # + xor_hash u64
        fields: List[Tuple[str, str]] = []
        if self.compact:
            count = (end - items_at) // 4
            offs = struct.unpack_from(f"<{count}I", self.buf, items_at)
        else:
            count = (end - items_at) // 16
            offs = [struct.unpack_from("<Q", self.buf,
                                       items_at + 16 * i)[0]
                    for i in range(count)]
        for data_off in offs:
            if not data_off:
                continue
            payload = self._data_payload(data_off)
            key, sep, value = payload.partition(b"=")
            if not sep:
                continue
            fields.append((key.decode("utf-8", "replace"),
                           value.decode("utf-8", "replace")))
        return Entry(seqnum, realtime, monotonic, boot_id, fields)

    def _entry_offsets(self) -> Iterator[int]:
        array = self.entry_array_offset
        seen = set()
        while array:
            if array in seen:
                raise JournalError(f"{self.path}: entry array loop")
            seen.add(array)
            otype, _f, start, end = self._object(array)
            if otype != OBJECT_ENTRY_ARRAY:
                raise JournalError(
                    f"{self.path}: expected ENTRY_ARRAY object")
            next_array = struct.unpack_from("<Q", self.buf, start)[0]
            items_at = start + 8
            if self.compact:
                count = (end - items_at) // 4
                offs = struct.unpack_from(f"<{count}I", self.buf,
                                          items_at)
            else:
                count = (end - items_at) // 8
                offs = struct.unpack_from(f"<{count}Q", self.buf,
                                          items_at)
            for off in offs:
                if off == 0:
                    return  # zero-padded tail of the last array
                yield off
            array = next_array

    def close(self) -> None:
        try:
            if hasattr(self.buf, "close"):
                self.buf.close()
        except (BufferError, ValueError):
            pass
        try:
            self._f.close()
        except (OSError, AttributeError):
            pass

    def entries(self, skip: int = 0,
                max_entries: Optional[int] = None) -> Iterator[Entry]:
        produced = 0
        for i, off in enumerate(self._entry_offsets()):
            if i < skip:
                continue
            if max_entries is not None and produced >= max_entries:
                return
            yield self._entry(off)
            produced += 1


def scan_journal_dir(path: str) -> List[str]:
    """All .journal files under a journald directory tree."""
    out = []
    for base, _dirs, files in os.walk(path):
        for f in files:
            if f.endswith(".journal"):
                out.append(os.path.join(base, f))
    out.sort()
    return out
