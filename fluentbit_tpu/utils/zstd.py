"""zstd codec bound to the system libzstd via ctypes.

Reference: src/flb_zstd.c wraps the vendored lib/zstd with exactly
this surface (flb_zstd_compress / flb_zstd_uncompress use the simple
one-shot ZSTD_compress/ZSTD_decompress API, sizing the destination
with ZSTD_compressBound / ZSTD_getFrameContentSize). This image ships
libzstd.so.1, so the binding replaces the vendored copy; no Python
zstd package is required.
"""

from __future__ import annotations

import ctypes
import ctypes.util
from typing import Optional

_ZSTD_CONTENTSIZE_UNKNOWN = 2 ** 64 - 1
_ZSTD_CONTENTSIZE_ERROR = 2 ** 64 - 2

_lib: Optional[ctypes.CDLL] = None
_load_error: Optional[str] = None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_error
    if _lib is not None or _load_error is not None:
        return _lib
    name = ctypes.util.find_library("zstd") or "libzstd.so.1"
    try:
        lib = ctypes.CDLL(name)
    except OSError as e:
        _load_error = str(e)
        return None
    lib.ZSTD_compressBound.restype = ctypes.c_size_t
    lib.ZSTD_compressBound.argtypes = [ctypes.c_size_t]
    lib.ZSTD_compress.restype = ctypes.c_size_t
    lib.ZSTD_compress.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t,
        ctypes.c_void_p, ctypes.c_size_t, ctypes.c_int]
    lib.ZSTD_decompress.restype = ctypes.c_size_t
    lib.ZSTD_decompress.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t, ctypes.c_void_p,
        ctypes.c_size_t]
    lib.ZSTD_getFrameContentSize.restype = ctypes.c_ulonglong
    lib.ZSTD_getFrameContentSize.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t]
    lib.ZSTD_isError.restype = ctypes.c_uint
    lib.ZSTD_isError.argtypes = [ctypes.c_size_t]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def compress(data: bytes, level: int = 3) -> bytes:
    lib = _load()
    if lib is None:
        raise OSError(f"libzstd unavailable: {_load_error}")
    bound = lib.ZSTD_compressBound(len(data))
    dst = ctypes.create_string_buffer(bound)
    n = lib.ZSTD_compress(dst, bound, data, len(data), level)
    if lib.ZSTD_isError(n):
        raise ValueError("zstd compression failed")
    return dst.raw[:n]


def decompress(data: bytes,
               max_output: int = 256 * 1024 * 1024) -> bytes:
    """One-shot decompress. Frames without a content-size header fall
    back to doubling buffers the way flb_zstd_uncompress retries; the
    expansion is bounded so a hostile frame can't exhaust memory."""
    lib = _load()
    if lib is None:
        raise OSError(f"libzstd unavailable: {_load_error}")
    size = lib.ZSTD_getFrameContentSize(data, len(data))
    if size == _ZSTD_CONTENTSIZE_ERROR:
        raise ValueError("not a zstd frame")
    if size != _ZSTD_CONTENTSIZE_UNKNOWN:
        if size > max_output:
            raise ValueError("zstd content size exceeds limit")
        dst = ctypes.create_string_buffer(max(1, size))
        n = lib.ZSTD_decompress(dst, size, data, len(data))
        if lib.ZSTD_isError(n) or n != size:
            raise ValueError("zstd decompression failed")
        return dst.raw[:n]
    cap = min(max(64 * 1024, 4 * len(data)), max_output)
    while True:
        dst = ctypes.create_string_buffer(cap)
        n = lib.ZSTD_decompress(dst, cap, data, len(data))
        if not lib.ZSTD_isError(n):
            return dst.raw[:n]
        if cap >= max_output:
            break
        cap = min(cap * 2, max_output)  # always try the limit itself
    raise ValueError("zstd decompression failed (or exceeds limit)")
