"""AWS common — credentials providers + Signature Version 4.

Reference: src/aws/ (flb_aws_credentials.c provider chain: env →
credential_process → profile → STS web identity → ECS/HTTP container
creds; flb_aws_credentials_sts.c AssumeRole + AssumeRoleWithWebIdentity;
flb_aws_credentials_process.c; flb_aws_credentials_http.c;
src/flb_signv4.c request signing shared by all AWS outputs +
filter_aws). Implemented from the public SigV4 / STS specifications.
IMDS enrichment lives in filter_aws (stub-tested); expiring credentials
(STS/process/HTTP) refresh automatically 5 minutes before expiry
(FLB_AWS_REFRESH_WINDOW, include/fluent-bit/aws/flb_aws_credentials.h).
"""

from __future__ import annotations

import configparser
import datetime
import hashlib
import hmac
import json
import os
import re
import time
import urllib.parse
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple


@dataclass
class Credentials:
    access_key: str
    secret_key: str
    session_token: Optional[str] = None
    expiration: Optional[float] = field(default=None)  # epoch seconds

    def expired(self, window: float = 300.0) -> bool:
        """True once inside the pre-expiry refresh window."""
        return (self.expiration is not None
                and time.time() >= self.expiration - window)


def env_provider() -> Optional[Credentials]:
    ak = os.environ.get("AWS_ACCESS_KEY_ID")
    sk = os.environ.get("AWS_SECRET_ACCESS_KEY")
    if not ak or not sk:
        return None
    return Credentials(ak, sk, os.environ.get("AWS_SESSION_TOKEN"))


def profile_provider(profile: Optional[str] = None,
                     path: Optional[str] = None) -> Optional[Credentials]:
    path = path or os.environ.get(
        "AWS_SHARED_CREDENTIALS_FILE",
        os.path.expanduser("~/.aws/credentials"),
    )
    profile = profile or os.environ.get("AWS_PROFILE", "default")
    cp = configparser.ConfigParser()
    try:
        cp.read(path)
    except (OSError, configparser.Error):
        return None
    if profile not in cp:
        return None
    sec = cp[profile]
    ak = sec.get("aws_access_key_id")
    sk = sec.get("aws_secret_access_key")
    if not ak or not sk:
        return None
    return Credentials(ak, sk, sec.get("aws_session_token"))


def _parse_iso8601(s: Optional[str]) -> Optional[float]:
    """Lenient ISO-8601 (fractional seconds, Z or numeric offsets) —
    an unparseable expiration must not silently mean 'never expires'
    for common formats."""
    if not s:
        return None
    try:
        dt = datetime.datetime.fromisoformat(s.replace("Z", "+00:00"))
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=datetime.timezone.utc)
        return dt.timestamp()
    except ValueError:
        return None


def _sts_endpoint(region: str) -> Tuple[str, int]:
    ep = (os.environ.get("AWS_STS_ENDPOINT")
          or f"sts.{region}.amazonaws.com")
    ep = ep.replace("https://", "").replace("http://", "")
    host, _, port = ep.partition(":")
    return host, int(port or 80)


def _parse_sts_xml(body: bytes) -> Optional[Credentials]:
    def grab(tag):
        m = re.search(rf"<{tag}>([^<]+)</{tag}>".encode(), body)
        return m.group(1).decode() if m else None

    ak, sk = grab("AccessKeyId"), grab("SecretAccessKey")
    if not ak or not sk:
        return None
    return Credentials(ak, sk, grab("SessionToken"),
                       _parse_iso8601(grab("Expiration")))


def sts_assume_role_provider(role_arn: str, session_name: str = "fluent-bit",
                             region: str = "us-east-1",
                             base: Optional[Credentials] = None,
                             external_id: Optional[str] = None,
                             ) -> Optional[Credentials]:
    """STS AssumeRole signed with the base chain's credentials
    (flb_aws_credentials_sts.c:295-340, flb_sts_uri)."""
    from . import plain_http_request

    base = base or env_provider() or profile_provider()
    if base is None:
        return None
    host, port = _sts_endpoint(region)
    query = ("Version=2011-06-15&Action=AssumeRole"
             f"&RoleArn={urllib.parse.quote(role_arn, safe='')}"
             f"&RoleSessionName={urllib.parse.quote(session_name, safe='')}")
    if external_id:
        query += f"&ExternalId={urllib.parse.quote(external_id, safe='')}"
    path = "/?" + query
    url = f"http://{host}:{port}{path}"
    headers = sigv4_headers("GET", url, region, "sts", b"", base)
    try:
        got = plain_http_request(host, port, "GET", path,
                                 headers=headers)
    except OSError:
        got = None
    if got is None or got[0] != 200:  # None on socket failure
        return None
    return _parse_sts_xml(got[1])


def web_identity_provider(region: str = "us-east-1"
                          ) -> Optional[Credentials]:
    """STS AssumeRoleWithWebIdentity from AWS_ROLE_ARN +
    AWS_WEB_IDENTITY_TOKEN_FILE — unsigned (the token IS the proof;
    flb_aws_credentials_sts.c:642,712-740)."""
    from . import plain_http_request

    role_arn = os.environ.get("AWS_ROLE_ARN")
    token_file = os.environ.get("AWS_WEB_IDENTITY_TOKEN_FILE")
    if not role_arn or not token_file:
        return None
    try:
        with open(token_file) as f:
            token = f.read().strip()
    except OSError:
        return None
    session = os.environ.get("AWS_ROLE_SESSION_NAME", "fluent-bit")
    host, port = _sts_endpoint(region)
    path = ("/?Version=2011-06-15&Action=AssumeRoleWithWebIdentity"
            f"&RoleArn={urllib.parse.quote(role_arn, safe='')}"
            f"&RoleSessionName={urllib.parse.quote(session, safe='')}"
            f"&WebIdentityToken={urllib.parse.quote(token, safe='')}")
    try:
        got = plain_http_request(host, port, "GET", path)
    except OSError:
        got = None
    if got is None or got[0] != 200:
        return None
    return _parse_sts_xml(got[1])


def process_provider(profile: Optional[str] = None) -> Optional[Credentials]:
    """``credential_process`` from the AWS config file: run the command,
    parse the JSON credential document
    (flb_aws_credentials_process.c; the documented external-process
    contract: Version/AccessKeyId/SecretAccessKey/SessionToken/
    Expiration)."""
    import shlex
    import subprocess

    path = os.environ.get("AWS_CONFIG_FILE",
                          os.path.expanduser("~/.aws/config"))
    profile = profile or os.environ.get("AWS_PROFILE", "default")
    cp = configparser.ConfigParser()
    try:
        cp.read(path)
    except (OSError, configparser.Error):
        return None
    section = profile if profile in cp else f"profile {profile}"
    if section not in cp:
        return None
    cmd = cp[section].get("credential_process")
    if not cmd:
        return None
    try:
        proc = subprocess.run(shlex.split(cmd), capture_output=True,
                              timeout=30)
        doc = json.loads(proc.stdout)
        if proc.returncode != 0 or int(doc.get("Version", 0)) != 1:
            return None
        ak, sk = doc.get("AccessKeyId"), doc.get("SecretAccessKey")
        if not ak or not sk:
            return None
        return Credentials(ak, sk, doc.get("SessionToken"),
                           _parse_iso8601(doc.get("Expiration")))
    except (OSError, subprocess.TimeoutExpired, ValueError, TypeError,
            AttributeError):
        # malformed external-process output must fall through the
        # chain, never crash plugin init or an in-flight refresh
        return None


def http_provider() -> Optional[Credentials]:
    """ECS/EKS container credentials over HTTP:
    AWS_CONTAINER_CREDENTIALS_RELATIVE_URI (against 169.254.170.2) or
    AWS_CONTAINER_CREDENTIALS_FULL_URI (flb_aws_credentials_http.c;
    optional bearer token via AWS_CONTAINER_AUTHORIZATION_TOKEN)."""
    from . import plain_http_request

    rel = os.environ.get("AWS_CONTAINER_CREDENTIALS_RELATIVE_URI")
    full = os.environ.get("AWS_CONTAINER_CREDENTIALS_FULL_URI")
    if rel:
        host, port, path = "169.254.170.2", 80, rel
    elif full:
        parsed = urllib.parse.urlsplit(full)
        host = parsed.hostname or ""
        port = parsed.port or 80
        path = parsed.path or "/"
        if parsed.query:
            path += "?" + parsed.query
    else:
        return None
    headers = {}
    token = os.environ.get("AWS_CONTAINER_AUTHORIZATION_TOKEN")
    if token:
        headers["Authorization"] = token
    try:
        got = plain_http_request(host, port, "GET", path,
                                 headers=headers)
        if got is None or got[0] != 200:
            return None
        doc = json.loads(got[1])
        ak, sk = doc.get("AccessKeyId"), doc.get("SecretAccessKey")
        if not ak or not sk:
            return None
        return Credentials(ak, sk, doc.get("Token"),
                           _parse_iso8601(doc.get("Expiration")))
    except (OSError, ValueError, TypeError, AttributeError):
        return None


_refresh_backoff_until = 0.0


def current(creds: Optional[Credentials]) -> Optional[Credentials]:
    """Per-request refresh hook for plugins holding credentials from
    init: hands back the same object until it enters the expiry window,
    then re-resolves the chain. The chain is blocking (subprocess /
    sockets), so a FAILED refresh backs off 60 s — without it every
    request past expiry would re-run the full chain inline."""
    global _refresh_backoff_until
    if creds is not None and not creds.expired():
        return creds
    if time.time() < _refresh_backoff_until:
        return creds
    got = get_credentials(refresh=True)
    if got is None or (creds is not None and got is creds):
        _refresh_backoff_until = time.time() + 60.0
    return got or creds


_cached: Optional[Credentials] = None


def get_credentials(refresh: bool = False) -> Optional[Credentials]:
    """The standard provider chain (flb_aws_credentials.c:
    env → credential_process → profile → STS web identity → ECS/HTTP).
    Expiring credentials re-resolve inside the 5-minute refresh
    window."""
    global _cached
    if not refresh and _cached is not None and not _cached.expired():
        return _cached
    creds = (env_provider() or process_provider() or profile_provider()
             or web_identity_provider() or http_provider())
    _cached = creds if creds is not None and creds.expiration else None
    return creds


# ------------------------------------------------------------------ sigv4

def _canonical_query(qs: str) -> str:
    """Spec-exact canonical query: percent-decode WITHOUT '+'-to-space
    (a literal '+' is data), re-encode with the unreserved-safe set,
    sort by ENCODED key then encoded value."""
    if not qs:
        return ""
    pairs = []
    for part in qs.split("&"):
        if not part:
            continue
        k, _, v = part.partition("=")
        pairs.append((
            urllib.parse.quote(urllib.parse.unquote(k), safe="-_.~"),
            urllib.parse.quote(urllib.parse.unquote(v), safe="-_.~"),
        ))
    return "&".join(f"{k}={v}" for k, v in sorted(pairs))

def _sign(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str, service: str) -> bytes:
    k = _sign(("AWS4" + secret).encode(), date)
    k = _sign(k, region)
    k = _sign(k, service)
    return _sign(k, "aws4_request")


def sigv4_headers(
    method: str,
    url: str,
    region: str,
    service: str,
    payload: bytes,
    credentials: Credentials,
    headers: Optional[Dict[str, str]] = None,
    now: Optional[datetime.datetime] = None,
) -> Dict[str, str]:
    """Sign a request; returns the headers to attach (Authorization,
    X-Amz-Date, X-Amz-Content-Sha256 [, X-Amz-Security-Token])."""
    parsed = urllib.parse.urlsplit(url)
    host = parsed.netloc
    path = parsed.path or "/"
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date = now.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(payload).hexdigest()

    all_headers = {"host": host, "x-amz-date": amz_date,
                   "x-amz-content-sha256": payload_hash}
    if credentials.session_token:
        all_headers["x-amz-security-token"] = credentials.session_token
    for k, v in (headers or {}).items():
        # sequential-whitespace collapse per the canonicalization spec
        all_headers[k.lower()] = " ".join(str(v).split())

    canonical_query = _canonical_query(parsed.query)
    signed_names = sorted(all_headers)
    canonical_headers = "".join(
        f"{k}:{all_headers[k]}\n" for k in signed_names
    )
    signed_headers = ";".join(signed_names)
    canonical_request = "\n".join([
        method.upper(),
        urllib.parse.quote(path, safe="/-_.~"),
        canonical_query,
        canonical_headers,
        signed_headers,
        payload_hash,
    ])
    scope = f"{date}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256",
        amz_date,
        scope,
        hashlib.sha256(canonical_request.encode()).hexdigest(),
    ])
    signature = hmac.new(
        signing_key(credentials.secret_key, date, region, service),
        string_to_sign.encode(), hashlib.sha256,
    ).hexdigest()
    out = {
        "Authorization": (
            f"AWS4-HMAC-SHA256 Credential={credentials.access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}"
        ),
        "X-Amz-Date": amz_date,
        "X-Amz-Content-Sha256": payload_hash,
    }
    if credentials.session_token:
        out["X-Amz-Security-Token"] = credentials.session_token
    return out
