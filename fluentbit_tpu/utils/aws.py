"""AWS common — credentials providers + Signature Version 4.

Reference: src/aws/ (flb_aws_credentials.c: env → profile → STS/IMDS
chain; src/flb_signv4.c request signing shared by all AWS outputs +
filter_aws). Implemented from the public SigV4 specification; the
network-dependent providers (IMDS/STS/HTTP) are gated — env and
profile-file credentials cover the offline build.
"""

from __future__ import annotations

import configparser
import datetime
import hashlib
import hmac
import os
import urllib.parse
from dataclasses import dataclass
from typing import Dict, Optional, Tuple


@dataclass
class Credentials:
    access_key: str
    secret_key: str
    session_token: Optional[str] = None


def env_provider() -> Optional[Credentials]:
    ak = os.environ.get("AWS_ACCESS_KEY_ID")
    sk = os.environ.get("AWS_SECRET_ACCESS_KEY")
    if not ak or not sk:
        return None
    return Credentials(ak, sk, os.environ.get("AWS_SESSION_TOKEN"))


def profile_provider(profile: Optional[str] = None,
                     path: Optional[str] = None) -> Optional[Credentials]:
    path = path or os.environ.get(
        "AWS_SHARED_CREDENTIALS_FILE",
        os.path.expanduser("~/.aws/credentials"),
    )
    profile = profile or os.environ.get("AWS_PROFILE", "default")
    cp = configparser.ConfigParser()
    try:
        cp.read(path)
    except (OSError, configparser.Error):
        return None
    if profile not in cp:
        return None
    sec = cp[profile]
    ak = sec.get("aws_access_key_id")
    sk = sec.get("aws_secret_access_key")
    if not ak or not sk:
        return None
    return Credentials(ak, sk, sec.get("aws_session_token"))


def get_credentials() -> Optional[Credentials]:
    """The provider chain (env → profile; IMDS/STS are gated offline)."""
    return env_provider() or profile_provider()


# ------------------------------------------------------------------ sigv4

def _canonical_query(qs: str) -> str:
    """Spec-exact canonical query: percent-decode WITHOUT '+'-to-space
    (a literal '+' is data), re-encode with the unreserved-safe set,
    sort by ENCODED key then encoded value."""
    if not qs:
        return ""
    pairs = []
    for part in qs.split("&"):
        if not part:
            continue
        k, _, v = part.partition("=")
        pairs.append((
            urllib.parse.quote(urllib.parse.unquote(k), safe="-_.~"),
            urllib.parse.quote(urllib.parse.unquote(v), safe="-_.~"),
        ))
    return "&".join(f"{k}={v}" for k, v in sorted(pairs))

def _sign(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str, service: str) -> bytes:
    k = _sign(("AWS4" + secret).encode(), date)
    k = _sign(k, region)
    k = _sign(k, service)
    return _sign(k, "aws4_request")


def sigv4_headers(
    method: str,
    url: str,
    region: str,
    service: str,
    payload: bytes,
    credentials: Credentials,
    headers: Optional[Dict[str, str]] = None,
    now: Optional[datetime.datetime] = None,
) -> Dict[str, str]:
    """Sign a request; returns the headers to attach (Authorization,
    X-Amz-Date, X-Amz-Content-Sha256 [, X-Amz-Security-Token])."""
    parsed = urllib.parse.urlsplit(url)
    host = parsed.netloc
    path = parsed.path or "/"
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date = now.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(payload).hexdigest()

    all_headers = {"host": host, "x-amz-date": amz_date,
                   "x-amz-content-sha256": payload_hash}
    if credentials.session_token:
        all_headers["x-amz-security-token"] = credentials.session_token
    for k, v in (headers or {}).items():
        # sequential-whitespace collapse per the canonicalization spec
        all_headers[k.lower()] = " ".join(str(v).split())

    canonical_query = _canonical_query(parsed.query)
    signed_names = sorted(all_headers)
    canonical_headers = "".join(
        f"{k}:{all_headers[k]}\n" for k in signed_names
    )
    signed_headers = ";".join(signed_names)
    canonical_request = "\n".join([
        method.upper(),
        urllib.parse.quote(path, safe="/-_.~"),
        canonical_query,
        canonical_headers,
        signed_headers,
        payload_hash,
    ])
    scope = f"{date}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256",
        amz_date,
        scope,
        hashlib.sha256(canonical_request.encode()).hexdigest(),
    ])
    signature = hmac.new(
        signing_key(credentials.secret_key, date, region, service),
        string_to_sign.encode(), hashlib.sha256,
    ).hexdigest()
    out = {
        "Authorization": (
            f"AWS4-HMAC-SHA256 Credential={credentials.access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}"
        ),
        "X-Amz-Date": amz_date,
        "X-Amz-Content-Sha256": payload_hash,
    }
    if credentials.session_token:
        out["X-Amz-Security-Token"] = credentials.session_token
    return out
