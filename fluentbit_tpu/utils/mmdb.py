"""MaxMind DB (MMDB) format reader, from scratch.

Reference: filter_geoip2 links libmaxminddb (plugins/filter_geoip2/
geoip2.c MMDB_open/MMDB_lookup_string/MMDB_aget_value); this module
implements the MaxMind-DB-spec binary format directly: metadata section
located by the \\xab\\xcd\\xefMaxMind.com marker, binary search tree
with 24/28/32-bit records, and the typed data section (pointers,
strings, doubles, uints, maps, arrays).
"""

from __future__ import annotations

import ipaddress
import struct
from typing import Any, List, Optional, Tuple

_METADATA_MARKER = b"\xab\xcd\xefMaxMind.com"
_DATA_SEPARATOR = 16  # bytes of zeros between tree and data section


class MMDBError(ValueError):
    pass


class _Decoder:
    """Data-section decoder (spec 'Data Section Separator' onward)."""

    def __init__(self, buf: bytes, base: int):
        self.buf = buf
        self.base = base  # absolute offset of the data section

    def decode(self, offset: int) -> Tuple[Any, int]:
        """offset is relative to the data section; → (value, next_off)."""
        buf = self.buf
        pos = self.base + offset
        if pos >= len(buf):
            raise MMDBError("data offset out of range")
        ctrl = buf[pos]
        pos += 1
        dtype = ctrl >> 5
        if dtype == 0:  # extended
            dtype = 7 + buf[pos]
            pos += 1
        if dtype == 1:  # pointer
            ss = (ctrl >> 3) & 0x3
            vvv = ctrl & 0x7
            if ss == 0:
                ptr = (vvv << 8) | buf[pos]
                pos += 1
            elif ss == 1:
                ptr = ((vvv << 16) | (buf[pos] << 8) | buf[pos + 1]) + 2048
                pos += 2
            elif ss == 2:
                ptr = ((vvv << 24) | (buf[pos] << 16) | (buf[pos + 1] << 8)
                       | buf[pos + 2]) + 526336
                pos += 3
            else:
                ptr = int.from_bytes(buf[pos:pos + 4], "big")
                pos += 4
            value, _ = self.decode(ptr)
            return value, pos - self.base
        size = ctrl & 0x1F
        if size == 29:
            size = 29 + buf[pos]
            pos += 1
        elif size == 30:
            size = 285 + int.from_bytes(buf[pos:pos + 2], "big")
            pos += 2
        elif size == 31:
            size = 65821 + int.from_bytes(buf[pos:pos + 3], "big")
            pos += 3
        if dtype == 2:  # utf8 string
            v = buf[pos:pos + size].decode("utf-8", "replace")
            return v, pos + size - self.base
        if dtype == 3:  # double
            return struct.unpack(">d", buf[pos:pos + 8])[0], \
                pos + 8 - self.base
        if dtype == 4:  # bytes
            return bytes(buf[pos:pos + size]), pos + size - self.base
        if dtype in (5, 6, 9, 10):  # uint16/32/64/128
            return int.from_bytes(buf[pos:pos + size], "big"), \
                pos + size - self.base
        if dtype == 7:  # map
            out = {}
            off = pos - self.base
            for _ in range(size):
                k, off = self.decode(off)
                v, off = self.decode(off)
                out[k] = v
            return out, off
        if dtype == 8:  # int32
            raw = buf[pos:pos + size]
            return int.from_bytes(raw, "big", signed=True) if size else 0, \
                pos + size - self.base
        if dtype == 11:  # array
            out_l: List[Any] = []
            off = pos - self.base
            for _ in range(size):
                v, off = self.decode(off)
                out_l.append(v)
            return out_l, off
        if dtype == 14:  # boolean (size IS the value)
            return bool(size), pos - self.base
        if dtype == 15:  # float
            return struct.unpack(">f", buf[pos:pos + 4])[0], \
                pos + 4 - self.base
        raise MMDBError(f"unsupported data type {dtype}")


class MMDBReader:
    def __init__(self, path: str):
        with open(path, "rb") as f:
            self.buf = f.read()
        idx = self.buf.rfind(_METADATA_MARKER)
        if idx < 0:
            raise MMDBError("not an MMDB file (metadata marker missing)")
        meta_dec = _Decoder(self.buf, idx + len(_METADATA_MARKER))
        self.metadata, _ = meta_dec.decode(0)
        self.node_count = int(self.metadata["node_count"])
        self.record_size = int(self.metadata["record_size"])
        if self.record_size not in (24, 28, 32):
            raise MMDBError(f"unsupported record size {self.record_size}")
        self.ip_version = int(self.metadata.get("ip_version", 6))
        self.node_bytes = self.record_size * 2 // 8
        self.tree_size = self.node_count * self.node_bytes
        self.data = _Decoder(self.buf, self.tree_size + _DATA_SEPARATOR)

    # ------------------------------------------------------ tree walk

    def _record(self, node: int, side: int) -> int:
        base = node * self.node_bytes
        b = self.buf
        if self.record_size == 24:
            off = base + side * 3
            return int.from_bytes(b[off:off + 3], "big")
        if self.record_size == 28:
            if side == 0:
                return ((b[base + 3] >> 4) << 24) | \
                    int.from_bytes(b[base:base + 3], "big")
            return ((b[base + 3] & 0x0F) << 24) | \
                int.from_bytes(b[base + 4:base + 7], "big")
        off = base + side * 4
        return int.from_bytes(b[off:off + 4], "big")

    def lookup(self, ip: str) -> Optional[dict]:
        try:
            addr = ipaddress.ip_address(ip.strip())
        except ValueError:
            return None
        if addr.version == 6 and self.ip_version == 4:
            return None
        bits = addr.packed
        nbits = len(bits) * 8
        node = 0
        if addr.version == 4 and self.ip_version == 6:
            # v4 entries live under ::/96 — follow 96 zero bits first.
            # A data record met on the way covers the v4-mapped range
            # (e.g. a ::/0 default entry) and must be returned, exactly
            # as the full-width walk below would
            for _ in range(96):
                node = self._record(node, 0)
                if node == self.node_count:
                    return None
                if node > self.node_count:
                    offset = node - self.node_count - _DATA_SEPARATOR
                    value, _ = self.data.decode(offset)
                    return value if isinstance(value, dict) \
                        else {"value": value}
        for i in range(nbits):
            bit = (bits[i >> 3] >> (7 - (i & 7))) & 1
            node = self._record(node, bit)
            if node == self.node_count:
                return None  # no data
            if node > self.node_count:
                offset = node - self.node_count - _DATA_SEPARATOR
                value, _ = self.data.decode(offset)
                return value if isinstance(value, dict) else {"value": value}
        return None

    def get_path(self, ip: str, path: List[str]) -> Any:
        """MMDB_aget_value: walk a dotted path into the looked-up map;
        integer path components index arrays."""
        node = self.lookup(ip)
        if node is None:
            return None
        cur: Any = node
        for part in path:
            if isinstance(cur, dict):
                cur = cur.get(part)
            elif isinstance(cur, list):
                try:
                    cur = cur[int(part)]
                except (ValueError, IndexError):
                    return None
            else:
                return None
            if cur is None:
                return None
        return cur
