"""Minimal protobuf wire-format helpers (proto3 encoding).

The reference links full protobuf-c stacks for remote-write / OTLP
(e.g. plugins/out_prometheus_remote_write uses cmetrics'
cmt_encode_prometheus_remote_write.c, a hand-rolled wire encoder).
This is the same stance: no codegen, just the five wire types —
enough to encode/decode the small fixed schemas the plugins speak
(prometheus.WriteRequest and friends).

Wire types: 0 varint, 1 fixed64, 2 length-delimited, 5 fixed32.
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Tuple


class ProtobufError(ValueError):
    pass


# ----------------------------------------------------------- encode

def write_varint(n: int, out: bytearray) -> None:
    if n < 0:
        n &= 0xFFFFFFFFFFFFFFFF  # two's-complement 64-bit (int64 fields)
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)


def write_tag(field: int, wire_type: int, out: bytearray) -> None:
    write_varint((field << 3) | wire_type, out)


def write_varint_field(field: int, value: int, out: bytearray) -> None:
    if value == 0:
        return
    write_tag(field, 0, out)
    write_varint(value, out)


def write_double_field(field: int, value: float, out: bytearray) -> None:
    if value == 0.0 and not _is_neg_zero(value):
        return
    write_tag(field, 1, out)
    out += struct.pack("<d", value)


def _is_neg_zero(v: float) -> bool:
    return v == 0.0 and struct.pack("<d", v) != struct.pack("<d", 0.0)


def write_bytes_field(field: int, value: bytes, out: bytearray) -> None:
    if not value:
        return
    write_tag(field, 2, out)
    write_varint(len(value), out)
    out += value


def write_string_field(field: int, value: str, out: bytearray) -> None:
    write_bytes_field(field, value.encode("utf-8"), out)


def write_message_field(field: int, body: bytes, out: bytearray) -> None:
    """Submessages are emitted even when empty (presence semantics)."""
    write_tag(field, 2, out)
    write_varint(len(body), out)
    out += body


# ----------------------------------------------------------- decode

def read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    shift = 0
    result = 0
    while True:
        if pos >= len(data):
            raise ProtobufError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ProtobufError("varint too long")


def iter_fields(data: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, value). Length-delimited values
    come back as bytes; varints as int; fixed64/32 as raw bytes."""
    pos = 0
    n = len(data)
    while pos < n:
        key, pos = read_varint(data, pos)
        field = key >> 3
        wt = key & 7
        if wt == 0:
            val, pos = read_varint(data, pos)
        elif wt == 1:
            if pos + 8 > n:
                raise ProtobufError("truncated fixed64")
            val = data[pos:pos + 8]
            pos += 8
        elif wt == 2:
            ln, pos = read_varint(data, pos)
            if pos + ln > n:
                raise ProtobufError("truncated length-delimited field")
            val = data[pos:pos + ln]
            pos += ln
        elif wt == 5:
            if pos + 4 > n:
                raise ProtobufError("truncated fixed32")
            val = data[pos:pos + 4]
            pos += 4
        else:
            raise ProtobufError(f"unsupported wire type {wt}")
        yield field, wt, val


def decode_double(raw: bytes) -> float:
    return struct.unpack("<d", raw)[0]


def to_int64(v: int) -> int:
    """Interpret a decoded varint as a signed int64."""
    return v - (1 << 64) if v >= (1 << 63) else v


def group_fields(data: bytes) -> Dict[int, List[object]]:
    out: Dict[int, List[object]] = {}
    for field, _wt, val in iter_fields(data):
        out.setdefault(field, []).append(val)
    return out
