"""Minimal FlatBuffers reader — just enough of the wire format
(https://flatbuffers.dev/internals) to walk a .tflite model.

Reference: the TF-Lite runtime fluent-bit links (filter_tensorflow,
plugins/filter_tensorflow/tensorflow.c includes tensorflow/lite/c)
parses the same FlatBuffers layout through the generated C API; here
the three structural pieces are implemented directly: root offset →
table, vtable-indirected fields, and vectors/strings.

Wire format facts used:
- root: u32 offset at position 0 to the root table
- table: i32 at table pos = relative offset BACK to its vtable;
  vtable: u16 vtable size, u16 table size, then u16 per field id —
  0 means the field is absent (default applies)
- offsets inside tables are u32 FORWARD offsets from the field slot
- vector: u32 length at the target, elements follow
- string: vector of bytes (NUL-terminated, length excludes the NUL)
"""

from __future__ import annotations

import struct
from typing import List, Optional


class Table:
    """A flatbuffer table view: field(n) accessors by field id."""

    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int):
        self.buf = buf
        self.pos = pos

    def _slot(self, field_id: int) -> int:
        """Absolute position of the field slot, 0 if absent."""
        vtable = self.pos - struct.unpack_from("<i", self.buf,
                                               self.pos)[0]
        vsize = struct.unpack_from("<H", self.buf, vtable)[0]
        entry = 4 + 2 * field_id
        if entry + 2 > vsize:
            return 0
        rel = struct.unpack_from("<H", self.buf, vtable + entry)[0]
        return self.pos + rel if rel else 0

    # -- scalar fields --

    def i8(self, fid: int, default: int = 0) -> int:
        p = self._slot(fid)
        return struct.unpack_from("<b", self.buf, p)[0] if p else default

    def u8(self, fid: int, default: int = 0) -> int:
        p = self._slot(fid)
        return struct.unpack_from("<B", self.buf, p)[0] if p else default

    def i32(self, fid: int, default: int = 0) -> int:
        p = self._slot(fid)
        return struct.unpack_from("<i", self.buf, p)[0] if p else default

    def u32(self, fid: int, default: int = 0) -> int:
        p = self._slot(fid)
        return struct.unpack_from("<I", self.buf, p)[0] if p else default

    def f32(self, fid: int, default: float = 0.0) -> float:
        p = self._slot(fid)
        return struct.unpack_from("<f", self.buf, p)[0] if p else default

    def bool_(self, fid: int, default: bool = False) -> bool:
        p = self._slot(fid)
        return bool(self.buf[p]) if p else default

    # -- offset fields --

    def _indirect(self, p: int) -> int:
        return p + struct.unpack_from("<I", self.buf, p)[0]

    def table(self, fid: int) -> Optional["Table"]:
        p = self._slot(fid)
        return Table(self.buf, self._indirect(p)) if p else None

    def string(self, fid: int) -> Optional[str]:
        p = self._slot(fid)
        if not p:
            return None
        v = self._indirect(p)
        n = struct.unpack_from("<I", self.buf, v)[0]
        return self.buf[v + 4:v + 4 + n].decode("utf-8", "replace")

    def _vector(self, fid: int):
        p = self._slot(fid)
        if not p:
            return None, 0
        v = self._indirect(p)
        n = struct.unpack_from("<I", self.buf, v)[0]
        return v + 4, n

    def vector_len(self, fid: int) -> int:
        _, n = self._vector(fid)
        return n

    def i32_vector(self, fid: int) -> List[int]:
        base, n = self._vector(fid)
        if base is None:
            return []
        return list(struct.unpack_from(f"<{n}i", self.buf, base))

    def bytes_vector(self, fid: int) -> bytes:
        base, n = self._vector(fid)
        if base is None:
            return b""
        return bytes(self.buf[base:base + n])

    def table_vector(self, fid: int) -> List["Table"]:
        base, n = self._vector(fid)
        if base is None:
            return []
        out = []
        for i in range(n):
            slot = base + 4 * i
            out.append(Table(self.buf, self._indirect(slot)))
        return out


def root(buf: bytes) -> Table:
    return Table(buf, struct.unpack_from("<I", buf, 0)[0])
