"""Tag → output routing.

Reference: src/flb_router.c:140 (flb_router_match) — Match patterns support
'*' wildcards (each '*' matches any run of characters, so 'kube.*' matches
'kube.var.log'); Match_Regex uses a full regex instead. Routes are computed
per chunk as a bitmask over outputs (src/flb_routes_mask.c).
"""

from __future__ import annotations

import re
from typing import List, Optional


def tag_match(pattern: str, tag: str) -> bool:
    """Wildcard tag match (flb_router_match equivalent).

    '*' matches any sequence of characters (including '.'), '**' degenerates
    to the same. Comparison is exact otherwise (case sensitive, like the
    reference's strncmp-based loop).
    """
    # fast paths
    if pattern == "*" or pattern == "**":
        return True
    if "*" not in pattern:
        return pattern == tag
    rx = _pattern_cache.get(pattern)
    if rx is None:
        parts = [re.escape(p) for p in pattern.split("*")]
        rx = re.compile("^" + ".*".join(parts) + "$", re.S)
        _pattern_cache[pattern] = rx
    return rx.match(tag) is not None


_pattern_cache: dict = {}


class Route:
    """A match rule binding an instance to tags."""

    def __init__(self, match: Optional[str] = None, match_regex: Optional[str] = None):
        self.match = match
        self.match_regex = re.compile(match_regex) if match_regex else None

    def matches(self, tag: str) -> bool:
        if self.match_regex is not None:
            return self.match_regex.search(tag) is not None
        if self.match is not None:
            return tag_match(self.match, tag)
        return False


def match_outputs(tag: str, outputs: List) -> List:
    """Return output instances whose route matches ``tag``."""
    return [o for o in outputs if o.route.matches(tag)]


def routes_mask(tag: str, outputs: List) -> int:
    """Bitmask over the ordered output list (flb_routes_mask equivalent)."""
    mask = 0
    for i, o in enumerate(outputs):
        if o.route.matches(tag):
            mask |= 1 << i
    return mask
