"""TLS layer — client + server SSL contexts from instance properties.

Reference: src/tls/flb_tls.c + src/tls/openssl.c (OpenSSL-backed TLS
for upstreams/downstreams: ``tls``, ``tls.verify``, ``tls.ca_file``,
``tls.crt_file``, ``tls.key_file``, ``tls.vhost``). Python's ``ssl``
module is the OpenSSL binding here; asyncio integrates the handshake
with the event loop exactly like the reference's coroutine I/O.

``client_context(ins)`` / ``server_context(ins)`` read the shared core
properties off any plugin instance (CORE_INSTANCE_KEYS) and return an
``ssl.SSLContext`` or None when ``tls`` is off.
"""

from __future__ import annotations

import ssl
from typing import Optional

from .config import parse_bool


def _props(ins):
    get = ins.properties.get
    return {
        "on": parse_bool(get("tls", False)),
        "verify": parse_bool(get("tls.verify", True)),
        "ca_file": get("tls.ca_file"),
        "crt_file": get("tls.crt_file"),
        "key_file": get("tls.key_file"),
        "vhost": get("tls.vhost"),
    }


def tls_enabled(ins) -> bool:
    return bool(_props(ins)["on"])


def client_context(ins) -> Optional[ssl.SSLContext]:
    """Upstream TLS (flb_tls_create for outputs)."""
    p = _props(ins)
    if not p["on"]:
        return None
    ctx = ssl.create_default_context(ssl.Purpose.SERVER_AUTH,
                                     cafile=p["ca_file"])
    if not p["verify"]:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    if p["crt_file"]:
        ctx.load_cert_chain(p["crt_file"], p["key_file"])
    # an h2 output must negotiate the protocol via ALPN — without it a
    # TLS server assumes HTTP/1.1 and rejects the binary h2 preamble
    if getattr(ins, "http2", False):
        ctx.set_alpn_protocols(["h2"])
    return ctx


def client_server_hostname(ins) -> Optional[str]:
    """SNI override (tls.vhost)."""
    return _props(ins)["vhost"]


async def open_connection(ins, host: str, port: int, timeout=None):
    """Client connect honoring the instance's TLS properties — the one
    place the ssl/server_hostname dance lives (every TCP client plugin
    uses this instead of repeating it). Name resolution rides the
    TTL-cached resolver (core.upstream.resolve, the c-ares role)."""
    import asyncio

    from .. import failpoints as _fp
    from .upstream import invalidate_dns, resolve

    if _fp.ACTIVE:
        # FailpointError is an OSError: every caller's dial-failure
        # handling (pool drop, node cooloff, RETRY) engages as-is
        _fp.fire("upstream.connect")
    ctx = client_context(ins)
    try:
        addrs = await resolve(host, port)
    except OSError:
        addrs = [host]  # let the connect surface the resolution error
    # dialing resolved ADDRESSES: SNI/verification must still use the
    # original hostname (or the vhost override). Try each address in
    # getaddrinfo order — dual-stack fallback must survive the cache.
    sni = (client_server_hostname(ins) or host) if ctx else None
    last_err: Exception = OSError(f"no addresses for {host}")
    # the timeout bounds the WHOLE connect (all fallback addresses
    # together), like the single wait_for before multi-address dialing
    deadline = None if timeout is None else \
        asyncio.get_event_loop().time() + timeout
    for addr in addrs:
        try:
            if deadline is not None:
                remaining = deadline - asyncio.get_event_loop().time()
                if remaining <= 0:
                    raise asyncio.TimeoutError()
                return await asyncio.wait_for(
                    asyncio.open_connection(addr, port, ssl=ctx,
                                            server_hostname=sni),
                    remaining)
            return await asyncio.open_connection(
                addr, port, ssl=ctx, server_hostname=sni)
        except (OSError, asyncio.TimeoutError) as e:
            last_err = e
    invalidate_dns(host, port)  # every cached address failed
    raise last_err


def server_context(ins) -> Optional[ssl.SSLContext]:
    """Downstream TLS (server-type inputs)."""
    p = _props(ins)
    if not p["on"]:
        return None
    if not p["crt_file"]:
        raise ValueError(f"{ins.display_name}: tls on requires tls.crt_file")
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(p["crt_file"], p["key_file"])
    if p["ca_file"]:
        ctx.load_verify_locations(p["ca_file"])
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx
