"""fbtpu-relay — durable state for the fluent-forward fan-in hop.

Two small persistent structures give the fbtpu→fbtpu network hop its
effectively-once + partition-degrade semantics (FAULTS.md "fbtpu-relay"):

- :class:`DedupLedger` — the aggregator side. The forward client stamps
  every flush with a *stable* chunk-id (a content digest, so a resend of
  the same chunk carries the same id); the ledger records each id the
  FIRST time its chunk is absorbed into engine/flux state, persisted in
  an fstore meta sidecar (the PR-4 S3 multipart ``{digest: staged-at}``
  idempotency pattern) with a retry-window TTL. Ack-lost redelivery,
  mid-backoff interleavings and post-crash-restart redelivery all hit
  :meth:`seen` and are acked WITHOUT re-absorbing — the flux plane's
  HLL/CMS sketches are not idempotent, so "absorbed ≤ once" is the
  whole trust story for the shared analytical plane.

- :class:`ForwardSpool` — the edge side. When every upstream aggregator
  is down (a partition), the forward client degrades gracefully: the
  already-packed entry stream is spooled to an fstore stream together
  with a record-offset sidecar (core/sidecar.py), and on heal the spool
  replays via ``mmap`` — the sidecar supplies the record count, so
  replay never re-walks the msgpack payload. The spooled chunk keeps
  its stable chunk-id in the meta sidecar: a replay that races a
  pre-partition delivery dedups at the ledger like any other resend.

Both structures keep their mutable state under a named ``make_lock``
(core/lockorder.py) and are registered in the guarded-by registry
(analysis/registry.py) — new callers that touch the maps off-lock fail
the fbtpu-locksmith lint gate.
"""

from __future__ import annotations

import json
import mmap
import os
import time
from typing import Dict, List, Optional, Tuple

from .fstore import FStore, FStoreFile
from .lockorder import make_lock
from .sidecar import SIDECAR_SUFFIX, SidecarWriter, read_sidecar

__all__ = ["DedupLedger", "ForwardSpool", "stable_chunk_id"]


def stable_chunk_id(tag: str, blob: bytes) -> str:
    """The forward hop's stable chunk-id: a digest of (tag, entry
    stream) — computed over the UNCOMPRESSED packed entries, so the id
    survives compression settings, reconnects, backoff resends and even
    an edge restart replaying the same storage chunk. Identity follows
    the bytes, which is exactly what the dedup ledger needs."""
    import hashlib

    h = hashlib.sha256()
    h.update(tag.encode("utf-8", "replace"))
    h.update(b"\x00")
    h.update(blob)
    return h.hexdigest()[:32]


class DedupLedger:
    """Durable chunk-id ledger with a retry-window TTL.

    ``meta`` layout (the fstore JSON sidecar)::

        {"absorbed": {"<chunk-id>": [<absorbed-at>, <absorb-count>]}}

    ``absorb-count`` exists for the soak contract: :meth:`record` is
    called only when a chunk's records actually entered engine/flux
    state, so a count above 1 IS a double-absorb — ``verify_contract``'s
    "absorbed ≤ once" clause audits exactly this map. Entries expire
    after ``ttl`` seconds (the retry window: a peer that still resends
    after the window is misconfigured, and unbounded ledgers would leak).

    ``root=None`` keeps the ledger in memory only (no storage path
    configured): in-process redelivery still dedups, crash-restart
    redelivery does not — the same durability the chunks themselves
    have without filesystem storage.
    """

    STREAM = "forward-dedup"

    def __init__(self, root: Optional[str], ttl: float = 300.0,
                 clock=time.time):
        self.ttl = float(ttl)
        self.clock = clock
        self._lock = make_lock("DedupLedger._lock")
        self._file: Optional[FStoreFile] = None
        self._seen: Dict[str, List[float]] = {}  # id -> [ts, count]
        self.dedup_hits = 0
        if root:
            self._file = FStore(root).stream(self.STREAM).create("ledger")
            now = self.clock()
            absorbed = self._file.meta().get("absorbed") or {}
            for cid, rec in absorbed.items():
                try:
                    ts, count = float(rec[0]), int(rec[1])
                except (TypeError, ValueError, IndexError):
                    continue
                if now - ts <= self.ttl:
                    self._seen[str(cid)] = [ts, count]

    @staticmethod
    def _gc(seen: Dict[str, List[float]], now: float,
            ttl: float) -> None:
        # callers pass the map while holding self._lock (the guarded-by
        # registry keys on the attribute access, which stays lexically
        # under the with)
        if not seen:
            return
        dead = [cid for cid, rec in seen.items() if now - rec[0] > ttl]
        for cid in dead:
            del seen[cid]

    def seen(self, chunk_id: str) -> bool:
        """True when this chunk-id was absorbed inside the TTL window —
        the caller acks WITHOUT absorbing (a redelivery)."""
        now = self.clock()
        with self._lock:
            self._gc(self._seen, now, self.ttl)
            hit = chunk_id in self._seen
            if hit:
                self.dedup_hits += 1
        return hit

    def record(self, chunk_id: str) -> None:
        """Record one ABSORB of ``chunk_id`` and persist durably before
        the caller acks: an ack whose absorb-record died with the
        process would turn the next redelivery into a double-absorb."""
        now = self.clock()
        with self._lock:
            self._gc(self._seen, now, self.ttl)
            rec = self._seen.get(chunk_id)
            if rec is None:
                self._seen[chunk_id] = [now, 1]
            else:
                rec[1] += 1  # a double-absorb: kept visible, never hidden
            snap = {cid: [rec[0], rec[1]]
                    for cid, rec in self._seen.items()}
        if self._file is not None:
            self._file.set_meta({"absorbed": snap}, durable=True)

    def size(self) -> int:
        with self._lock:
            return len(self._seen)

    def snapshot(self) -> Dict[str, int]:
        """chunk-id → absorb count (the health block / soak audit)."""
        with self._lock:
            return {cid: rec[1] for cid, rec in self._seen.items()}


class ForwardSpool:
    """Partition-time buffer for the forward client.

    One spooled chunk = one fstore file holding the packed entry stream,
    a ``.offs`` record-offset sidecar (core/sidecar.py) and a JSON meta
    sidecar carrying the wire envelope (tag, stable chunk-id, record
    count, tenant/priority stamps, the engine chunk id whose storage
    quota charge the spool inherits). Files are named by a
    monotonically increasing sequence so replay preserves spool order.
    """

    STREAM = "forward-spool"

    def __init__(self, root: str):
        self._stream = FStore(root).stream(self.STREAM)
        self._lock = make_lock("ForwardSpool._lock")
        seq = 0
        for f in self._stream.files():
            name = f.name.split(".", 1)[0]
            if name.isdigit():
                seq = max(seq, int(name) + 1)
        self._seq = seq

    def put(self, tag: str, blob: bytes, ends: List[int], meta: dict
            ) -> FStoreFile:
        """Spool one packed entry stream + its offset table + envelope.
        The payload is flushed before the sidecars (the torn-file
        contract replay already honors: either file may be ahead)."""
        with self._lock:
            name = "%012d" % self._seq
            self._seq += 1
        f = self._stream.create(name)
        with open(f.path, "wb") as fh:
            fh.write(blob)
            fh.flush()
            os.fsync(fh.fileno())
        w = SidecarWriter(f.path + SIDECAR_SUFFIX)
        w.append_ends(len(blob), ends)
        w.finalize()
        f.set_meta(dict(meta, n=len(ends)), durable=True)
        return f

    def pending(self) -> List[FStoreFile]:
        """Spooled chunks in replay (spool) order."""
        return [f for f in self._stream.files()
                if not f.name.endswith(SIDECAR_SUFFIX)]

    def pending_bytes(self) -> int:
        return sum(f.size for f in self.pending())

    @staticmethod
    def load(f: FStoreFile) -> Optional[Tuple[bytes, int, dict]]:
        """mmap one spooled chunk for replay: ``(payload, n, meta)``.

        The record count comes from the ``.offs`` sidecar table (no
        msgpack re-walk) when it validates, else from the meta envelope;
        a spool file with neither is dropped by the caller (it cannot
        be framed). The payload is materialized only at the socket
        write — the validation path stays on the mapping."""
        meta = f.meta()
        try:
            with open(f.path, "rb") as fh:
                size = os.fstat(fh.fileno()).st_size
                if size == 0:
                    return None
                mm = mmap.mmap(fh.fileno(), 0, access=mmap.ACCESS_READ)
        except (OSError, ValueError):
            return None
        try:
            n = None
            got = read_sidecar(f.path + SIDECAR_SUFFIX, size)
            if got is not None and got[1].size:
                n = int(got[1].size)
            if n is None:
                n = int(meta.get("n") or 0)
            if n <= 0:
                return None
            return bytes(mm), n, meta
        finally:
            mm.close()

    @staticmethod
    def drop(f: FStoreFile) -> None:
        """Delete a delivered (acked) spool chunk + its sidecars."""
        try:
            os.unlink(f.path + SIDECAR_SUFFIX)
        except OSError:
            pass
        f.delete()


def load_ledger_counts(storage_root: str) -> Dict[str, int]:
    """Parse a ledger meta sidecar back into ``{chunk-id: absorbs}`` —
    the soak parent's audit input (no live process required)."""
    path = os.path.join(storage_root, DedupLedger.STREAM, "ledger.meta")
    try:
        with open(path, encoding="utf-8") as fh:
            absorbed = json.load(fh).get("absorbed") or {}
    except (OSError, ValueError):
        return {}
    out: Dict[str, int] = {}
    for cid, rec in absorbed.items():
        try:
            out[str(cid)] = int(rec[1])
        except (TypeError, ValueError, IndexError):
            continue
    return out
