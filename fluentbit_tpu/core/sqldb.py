"""SQLite wrapper — shared state databases.

Reference: src/flb_sqldb.c (the sqlite-amalgamation wrapper behind
in_tail offsets, tail_db.c, and the blob db). One shared connection per
path (the reference shares handles via flb_sqldb_open's db list), with
thread-safe access and a tiny exec/query API.
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

_lock = threading.Lock()
_open_dbs: Dict[str, "SqlDB"] = {}


class SqlDB:
    """flb_sqldb equivalent: one connection, serialized statements."""

    def __init__(self, path: str):
        self.path = path
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        self.users = 1

    def execute(self, sql: str, params: Iterable[Any] = ()) -> None:
        with self._lock:
            self._conn.execute(sql, tuple(params))
            self._conn.commit()

    def executemany(self, sql: str, rows: Iterable[Iterable[Any]]) -> None:
        """One transaction + one commit for a whole batch (the per-file
        checkpoint pattern must not fsync per row)."""
        with self._lock:
            self._conn.executemany(sql, [tuple(r) for r in rows])
            self._conn.commit()

    def query(self, sql: str, params: Iterable[Any] = ()) -> List[Tuple]:
        with self._lock:
            return self._conn.execute(sql, tuple(params)).fetchall()

    def close(self) -> None:
        with _lock:
            self.users -= 1
            if self.users <= 0:
                _open_dbs.pop(self.path, None)
                with self._lock:
                    self._conn.close()


def open_db(path: str) -> SqlDB:
    """Shared-handle open (flb_sqldb_open): same FILE → same DB —
    normalized so spelling variants cannot bypass the shared lock."""
    import os

    path = os.path.abspath(path)
    with _lock:
        db = _open_dbs.get(path)
        if db is not None:
            db.users += 1
            return db
        db = SqlDB(path)
        _open_dbs[path] = db
        return db
