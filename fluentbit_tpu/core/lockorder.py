"""fbtpu-locksmith ground truth: the lock-order witness recorder.

The static lock-acquisition-order graph (analysis/locksmith.py) is a
model; this module keeps it honest the same way the launch counters
keep fbtpu-xray honest and the live spec probe keeps fbtpu-speccheck
honest.  Every named lock in the threaded control plane is constructed
through :func:`make_lock`.  In normal operation that returns a plain
``threading.Lock``/``RLock`` — zero overhead, nothing recorded.  With
``FBTPU_LOCK_WITNESS`` set in the environment *at construction time*,
the lock is wrapped: each acquire records, for the acquiring thread,
one ``(held, acquired)`` edge per lock already held, into a process
-global edge set.

The tier-1 crosscheck (tests/test_locksmith.py) then drives
representative workloads — append/flush/reload/housekeeping/stop —
under the witness and asserts **static ⊇ dynamic**: every edge the
process actually exercised exists in the static graph, and the static
graph is acyclic.  A dynamically observed edge missing from the static
model means the analyzer's call-walk lost a path — the test fails
loudly instead of the model silently rotting.

Names handed to :func:`make_lock` are the analyzer's canonical node
ids (``Engine._ingest_lock``, ``InputInstance.ingest_lock``,
``device._lock`` …) — the two sides join on these strings, so renaming
a lock means updating both the construction site and the analyzer's
``LOCK_HOMES`` table (the crosscheck catches a drift).

Re-entrant re-acquisition of the same named lock records no edge: an
RLock re-entry is not an ordering constraint.
"""

from __future__ import annotations

import os
import threading
from typing import List, Set, Tuple

__all__ = ["make_lock", "witness_enabled", "witness_edges",
           "witness_reset"]

#: (held_name, acquired_name) edges observed since the last reset.
_edges: Set[Tuple[str, str]] = set()
_edges_guard = threading.Lock()
_tls = threading.local()


def witness_enabled() -> bool:
    """True when locks constructed NOW would record edges."""
    return bool(os.environ.get("FBTPU_LOCK_WITNESS"))


def witness_edges() -> List[Tuple[str, str]]:
    """Sorted snapshot of every recorded acquisition edge."""
    with _edges_guard:
        return sorted(_edges)


def witness_reset() -> None:
    with _edges_guard:
        _edges.clear()


def _held_stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


class _WitnessLock:
    """A named threading lock that records acquisition-order edges.

    Mirrors the subset of the ``threading.Lock``/``RLock`` surface the
    engine uses (``with``, ``acquire``/``release``, ``locked``).  The
    held-name stack is thread-local; the edge set is process-global so
    one tier-1 run accumulates every thread family's orderings.
    """

    __slots__ = ("name", "reentrant", "_inner")

    def __init__(self, name: str, reentrant: bool):
        self.name = name
        self.reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            stack = _held_stack()
            if self.name not in stack:
                # re-entry of the same named lock is not an ordering
                # constraint; a FIRST acquire under other held locks is
                new = {(held, self.name) for held in stack
                       if held != self.name}
                if new:
                    with _edges_guard:
                        _edges.update(new)
            stack.append(self.name)
        return got

    def release(self) -> None:
        stack = _held_stack()
        # remove the most recent entry for this name (lock scopes nest)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == self.name:
                del stack[i]
                break
        self._inner.release()

    def locked(self) -> bool:
        inner = self._inner
        return inner.locked() if hasattr(inner, "locked") else False

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<WitnessLock {self.name} reentrant={self.reentrant}>"


def make_lock(name: str, reentrant: bool = False):
    """Construct the named control-plane lock.

    Plain ``threading`` primitive unless ``FBTPU_LOCK_WITNESS`` is set
    in the environment when the lock is CONSTRUCTED (engines built
    before the flag flips stay unwitnessed — tests set the env before
    building their engine).
    """
    if witness_enabled():
        return _WitnessLock(name, reentrant)
    return threading.RLock() if reentrant else threading.Lock()
