"""Output worker thread pools.

Reference: src/flb_output_thread.c — an output configured with
``workers N`` runs its flush callbacks on N dedicated OS threads, each
with its own event loop; tasks are assigned round-robin
(flb_output_thread.c:439-496), and workers get cb_worker_init/exit
hooks (:249, :375). Here each worker thread runs its own asyncio loop
and the engine submits the plugin's flush coroutine to the next worker,
awaiting the result from the engine loop via a wrapped
concurrent.futures future — delivery I/O (and any GIL-releasing work:
socket sends, TLS, compression in C) leaves the engine thread.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import threading
from typing import List, Optional

from .. import failpoints as _fp

log = logging.getLogger("flb.output_thread")


class OutputWorkerPool:
    def __init__(self, name: str, workers: int, plugin=None,
                 start_timeout: float = 10.0):
        self.name = name
        self.plugin = plugin
        #: True when the workers never reached the ready barrier: the
        #: pool's loops are dead or missing, so the engine must fail the
        #: output over to inline flushes instead of letting submit()
        #: silently target a loop that will never run anything
        self.failed = False
        self._start_timeout = start_timeout
        self._loops: List[asyncio.AbstractEventLoop] = []
        self._threads: List[threading.Thread] = []
        self._rr = itertools.cycle(range(workers))
        ready = threading.Barrier(workers + 1)
        for i in range(workers):
            t = threading.Thread(target=self._worker, args=(i, ready),
                                 daemon=True,
                                 name=f"flb-out-{name}-w{i}")
            t.start()
            self._threads.append(t)
        try:
            ready.wait(timeout=start_timeout)
        except threading.BrokenBarrierError:
            self.failed = True
            log.error(
                "output %s: %d worker thread(s) did not start within "
                "%.1fs — pool unusable, caller must fall back to "
                "inline flush", name, workers, start_timeout)

    def _worker(self, index: int, ready: threading.Barrier) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loops.append(loop)
        if _fp.ACTIVE:
            try:
                # models a wedged/failed worker start (a hung
                # worker_init, a thread that dies before serving):
                # delay()/hang() stalls the ready barrier past the
                # startup timeout; return() kills this worker outright
                _fp.fire("output.worker_start")
            except OSError:
                log.error("%s worker %d start failed (injected)",
                          self.name, index)
                ready.abort()  # fail startup NOW, not at the timeout
                return
        # cb_worker_init hook (flb_output_thread.c:249)
        init = getattr(self.plugin, "worker_init", None)
        if init is not None:
            try:
                init(index)
            except Exception:
                log.exception("%s worker_init failed", self.name)
        try:
            # same bound as the constructor's wait: a fast worker must
            # not break the barrier under a slower sibling that the
            # configured guard.worker_start_timeout still allows
            ready.wait(timeout=self._start_timeout)
        except threading.BrokenBarrierError:
            pass
        try:
            loop.run_forever()
        finally:
            # drain callbacks scheduled right before stop
            try:
                loop.run_until_complete(asyncio.sleep(0))
            except RuntimeError:
                pass  # loop already stopped/closed: nothing to drain
            exit_cb = getattr(self.plugin, "worker_exit", None)
            if exit_cb is not None:
                try:
                    exit_cb(index)
                except Exception:
                    log.exception("%s worker_exit failed", self.name)
            loop.close()

    def submit(self, coro) -> "asyncio.Future":
        """Run the coroutine on the next worker loop (round-robin);
        returns an awaitable for the CALLING loop."""
        if self.failed or not self._loops:
            coro.close()  # never leak a never-awaited coroutine
            raise RuntimeError(
                f"output {self.name}: worker pool never started "
                f"(submit would target a dead loop)")
        if _fp.ACTIVE:
            try:
                _fp.fire("output.worker_flush")
            except BaseException:
                coro.close()
                raise
        loop = self._loops[next(self._rr) % len(self._loops)]
        cf = asyncio.run_coroutine_threadsafe(coro, loop)
        return asyncio.wrap_future(cf)

    def stop(self, timeout: float = 5.0) -> None:
        for loop in self._loops:
            try:
                loop.call_soon_threadsafe(loop.stop)
            except RuntimeError:
                pass
        for t in self._threads:
            t.join(timeout=timeout)
        self._loops.clear()
        self._threads.clear()
