"""The engine — event loop, ingest path, dispatch, flush, retries.

Reference: src/flb_engine.c (flb_engine_start event loop),
src/flb_engine_dispatch.c (chunk → task → per-route flush),
src/flb_task.c (task refcounting/retries), src/flb_input_chunk.c
(ingest + synchronous filter chain at append, :3078).

Architecture (TPU-first, not a port): the engine is a host-side asyncio
loop running in its own thread (the reference runs its engine in a pthread
spawned by flb_start, src/flb_lib.c). Inputs append records; the filter
chain runs synchronously at ingest exactly like the reference; chunks
accumulate per (input, tag); a flush timer drains ready chunks into tasks
and one async flush per (task × route) — the coroutine-per-flush model of
include/fluent-bit/flb_output.h:730 mapped onto asyncio. Device (TPU)
work happens inside filters via the ops layer; the engine itself never
blocks on the device.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import threading
import time
from typing import Any, Dict, List, Optional

from .. import failpoints as _fp
from ..codec.chunk import Chunk, EVENT_TYPE_LOGS, EVENT_TYPE_METRICS, EVENT_TYPE_TRACES
from ..codec.events import LogEvent, decode_events, reencode_event
from . import copywitness as _cw
from .config import ServiceConfig
from .lockorder import make_lock
from .metrics import MetricsRegistry
from .plugin import (
    FLUSH_CHUNK,
    FilterInstance,
    FilterResult,
    FlushResult,
    InputInstance,
    OutputInstance,
    registry as default_registry,
)
from .scheduler import backoff_full_jitter

log = logging.getLogger("flb.engine")

# _dispatch_chunk outcomes: PARKED must stay falsy (callers gate the
# park-and-break path on `not rc`)
PARKED = 0      # task map full — chunk goes back to the backlog
DISPATCHED = 1  # task spawned, a task-map slot was consumed
ABSORBED = 2    # handled without a slot (guard-shed / no live routes)

_task_ids = itertools.count(1)


class Task:
    """One flushable chunk + its routes + retry state
    (reference struct flb_task, include/fluent-bit/flb_task.h:82-98)."""

    __slots__ = ("id", "chunk", "routes", "retries", "users", "engine",
                 "processed")

    def __init__(self, chunk: Chunk, routes: List[OutputInstance]):
        self.id = next(_task_ids)
        self.chunk = chunk
        self.routes = routes
        self.retries: Dict[str, int] = {}  # output name → attempts
        self.users = 0
        # output name → processed payload (output-side processors run
        # once per route; retries reuse the cached bytes)
        self.processed: Dict[str, bytes] = {}


class _RawTail:
    """Continuation returned by ``_ingest_raw`` when a filter declines
    mid-chain AFTER an earlier stateful filter's side effects are out.
    The caller finishes the remaining filters per-record via
    ``_finish_raw_tail`` — outside the raw-path lock scope, because the
    tail re-enters the decode path's ``self._ingest_lock`` and taking
    that while still holding ``ins.ingest_lock`` would invert the
    canonical lock order (fbtpu-locksmith)."""

    __slots__ = ("tag", "data", "remaining", "n", "n_records", "deltas",
                 "in_bytes")

    def __init__(self, tag, data, remaining, n, n_records, deltas,
                 in_bytes):
        self.tag = tag
        self.data = data
        self.remaining = remaining  # the declining filter onward
        self.n = n
        self.n_records = n_records
        self.deltas = deltas
        self.in_bytes = in_bytes


class Engine:
    """The pipeline runtime for one configuration context."""

    def __init__(self, service: Optional[ServiceConfig] = None, registry=None):
        self.service = service or ServiceConfig()
        self.registry = registry or default_registry
        self.inputs: List[InputInstance] = []
        self.filters: List[FilterInstance] = []
        self.outputs: List[OutputInstance] = []
        self.customs: List = []
        self.metrics = MetricsRegistry()
        self.storage = None  # set by core.storage when storage_path configured
        self.parsers: Dict[str, Any] = {}  # named parsers (flb_parser registry)
        self.ml_parsers: Dict[str, Any] = {}  # multiline parsers (flb_ml)
        self.sp = None  # stream processor (flb_sp), created on first task
        self.traces: Dict[str, dict] = {}  # chunk-trace "tap" contexts
        self._ingest_src = None  # input currently appending (under lock)

        self._backlog: List[Chunk] = []  # recovered chunks to re-dispatch
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._stopping = False
        self._stop_event = threading.Event()  # wakes threaded collectors
        self._ingest_lock = make_lock("Engine._ingest_lock",
                                      reentrant=True)
        self._pending_flushes: set = set()
        # scheduler-owned retries (flb_engine_dispatch_retry,
        # src/flb_engine_dispatch.c:36-99): a retry is a loop timer +
        # this record, NOT a sleeping coroutine — key (chunk id, output)
        self._pending_retries: Dict[tuple, tuple] = {}
        # priority bucket queue (flb_bucket_queue, 8 priorities): ready
        # engine callbacks drain lowest-priority-number first, so retry
        # fires (scheduler, top) outrun fresh flush spawns (flush, 2)
        from .bucket_queue import BucketQueue

        self._event_queue = BucketQueue()
        self._event_queue_lock = make_lock("Engine._event_queue_lock")
        # task id map, default 2048 slots (flb_task_map, flb_task.c:542
        # + FLB_CONFIG_DEFAULT_TASK_MAP_SIZE): dispatch pauses when full
        self._task_map: Dict[int, Task] = {}
        self._task_map_warned = 0.0
        self._notification_subs: List = []
        self.started_at: float = 0.0
        self.reload_count = 0
        # configuration generation (fbtpu-qos): bumped by every
        # ReloadTxn.commit in the same ingest-lock critical section
        # that swaps the instance lists, so generation / reload_count /
        # list contents always read consistently
        self.generation = 0
        # outputs removed by hot reload: their in-flight tasks hold
        # direct references and finish normally; stop() reaps their
        # worker pools and runs their exit callbacks
        self._retired_outputs: List[OutputInstance] = []
        # canonical names freed by hot-reload removals (and trace-tap
        # teardown), per instance kind: numbering must never hand a
        # fresh instance a dead one's name — a guard-shed chunk's
        # persisted route_names or a dashboard's metric series would
        # silently re-bind to the unrelated newcomer
        self._retired_names: Dict[str, set] = {}
        # serializes whole hot-reload transactions (core/qos.py
        # ReloadTxn.commit): two concurrent commits would each write
        # back instance lists derived from their own pre-build
        # snapshot, silently dropping the other's changes
        self._reload_lock = make_lock("Engine._reload_lock")
        self.admin_server = None
        self.reload_callback = None  # wired by the CLI for /api/v2/reload

        self._init_metrics()
        # fbtpu-guard: flush deadlines, per-output breakers, watchdog +
        # load shedding (core/guard.py). Touches flush paths only —
        # the per-record ingest hot path has no guard code, and the
        # periodic checks ride flush_all's existing timer.
        from .guard import Guard

        self.guard = Guard(self)
        # fbtpu-qos: tenant admission, weighted-fair dispatch, hot
        # reload (core/qos.py). Ingest pays one tenant lookup + counter
        # per append; dispatch order comes from the fair queue.
        from .qos import Qos

        self.qos = Qos(self)

    # ------------------------------------------------------------------
    # metrics (names mirror the reference's fluentbit_* families)
    # ------------------------------------------------------------------

    def _init_metrics(self) -> None:
        m = self.metrics
        self.m_in_records = m.counter("fluentbit", "input", "records_total",
                                      "Input records", ("name",))
        self.m_in_bytes = m.counter("fluentbit", "input", "bytes_total",
                                    "Input bytes", ("name",))
        self.m_filter_add = m.counter("fluentbit", "filter", "add_records_total",
                                      "Records added by filter", ("name",))
        self.m_filter_drop = m.counter("fluentbit", "filter", "drop_records_total",
                                       "Records dropped by filter", ("name",))
        self.m_filter_emit = m.counter("fluentbit", "filter", "emit_records_total",
                                       "Records re-emitted by filter", ("name",))
        # batched fast-path declines (north-star addition): the
        # exactness contract says a decline is invisible in OUTPUT —
        # this counter makes it visible in OPS, so a config change that
        # silently demotes a hot chain to per-record shows up on a dash
        self.m_filter_batch_decline = m.counter(
            "fluentbit", "filter", "batch_declines_total",
            "Batched fast-path declines to the per-record path",
            ("name",))
        self.m_out_proc_records = m.counter("fluentbit", "output", "proc_records_total",
                                            "Records delivered", ("name",))
        self.m_out_proc_bytes = m.counter("fluentbit", "output", "proc_bytes_total",
                                          "Bytes delivered", ("name",))
        self.m_out_errors = m.counter("fluentbit", "output", "errors_total",
                                      "Flush errors", ("name",))
        self.m_out_retries = m.counter("fluentbit", "output", "retries_total",
                                       "Flush retries", ("name",))
        self.m_out_retries_failed = m.counter("fluentbit", "output", "retries_failed_total",
                                              "Retries exhausted", ("name",))
        self.m_out_dropped = m.counter("fluentbit", "output", "dropped_records_total",
                                       "Records dropped at output", ("name",))
        self.m_uptime = m.gauge("fluentbit", "", "uptime", "Uptime seconds")
        # end-to-end latency histogram (reference src/flb_engine.c:400-405)
        self.m_latency = m.histogram("fluentbit", "output", "latency_seconds",
                                     "chunk create → delivered latency", ("name",))
        # memrb ring-buffer eviction (src/flb_input_chunk.c:2936-2966)
        self.m_memrb_dropped_chunks = m.counter(
            "fluentbit", "input", "memrb_dropped_chunks_total",
            "Chunks evicted by memrb ring buffer", ("name",))
        self.m_memrb_dropped_bytes = m.counter(
            "fluentbit", "input", "memrb_dropped_bytes_total",
            "Bytes evicted by memrb ring buffer", ("name",))
        # fault-injection observability: every armed failpoint that
        # actually fires shows up here, so a soak run (or a forgotten
        # armed site in staging) is visible on the same dashboards as
        # the errors it provokes
        self.m_failpoint_triggered = m.counter(
            "fluentbit", "", "failpoint_triggered_total",
            "Faults triggered by the failpoint plane", ("name",))
        # fbtpu-armor device fault domain (ops/fault.py): per-lane
        # failover counters, fed by the fault listener bridge — a mesh
        # lane silently degrading to the CPU fallback is a metric, not
        # a mystery CPU-speed bench number
        self.m_device_fallback = m.counter(
            "fluentbit", "device", "fallback_segments_total",
            "Segments completed on the bit-exact CPU fallback after a "
            "device launch failed, timed out, or was short-circuited",
            ("lane",))
        self.m_device_timeouts = m.counter(
            "fluentbit", "device", "launch_timeouts_total",
            "Device launches soft-killed past the lane deadline",
            ("lane",))
        self.m_device_failures = m.counter(
            "fluentbit", "device", "launch_failures_total",
            "Device launches that raised (XlaRuntimeError, injected "
            "faults, resource exhaustion)", ("lane",))
        self.m_device_lost = m.counter(
            "fluentbit", "device", "device_lost_total",
            "Device-loss events (mesh shrinks to the survivors)",
            ("lane",))
        self.m_device_breaker = m.gauge(
            "fluentbit", "device", "breaker_state",
            "Per-lane device breaker state (0 closed, 1 half-open, "
            "2 open)", ("lane",))
        self.m_device_mesh = m.gauge(
            "fluentbit", "device", "mesh_devices",
            "Devices in the lane's current mesh (shrinks on loss, "
            "regrows on breaker re-close)", ("lane",))
        self.m_device_reattach = m.counter(
            "fluentbit", "device", "reattach_total",
            "Late/re-attach generations (the mesh lane swapped in "
            "live after earlier refusals)")
        # fbtpu-shrink (PERF.md "shrink"): compile-path DFA reduction
        # outcomes plus the approximate first-pass mask's runtime
        # economics — an approx mask that admits nearly everything is
        # pure overhead, and these counters (not a mystery-slow ingest
        # number) are how that reads on a dashboard
        self.m_shrink_states = m.counter(
            "fluentbit", "grep_shrink", "states_eliminated_total",
            "DFA states eliminated by the compile-path minimizer "
            "(Hopcroft + dead-state pruning), summed over compiled "
            "rules", ("name",))
        self.m_shrink_classes = m.counter(
            "fluentbit", "grep_shrink", "classes_eliminated_total",
            "Byte classes eliminated by the post-minimization class "
            "remerge, summed over compiled rules", ("name",))
        self.m_shrink_approx_admits = m.counter(
            "fluentbit", "grep_shrink", "approx_admits_total",
            "Per-(rule, record) admissions by the approximate "
            "first-pass DFA mask (mask selectivity)", ("name",))
        self.m_shrink_approx_rechecks = m.counter(
            "fluentbit", "grep_shrink", "approx_rechecks_total",
            "Records re-walked by the exact DFA (the union of all "
            "rules' admissions — the recheck cost actually paid)",
            ("name",))
        self.m_shrink_approx_fp = m.counter(
            "fluentbit", "grep_shrink", "approx_false_positives_total",
            "Approximate-mask admissions the exact recheck rejected "
            "(the measured FP the budget is enforced against)",
            ("name",))
        self.m_shrink_approx_disabled = m.counter(
            "fluentbit", "grep_shrink", "approx_disabled_total",
            "Approximate mode self-disabled: measured FP rate "
            "exceeded tpu_approx_fp_budget", ("name",))

    # ------------------------------------------------------------------
    # configuration
    # ------------------------------------------------------------------

    def _number_instance(self, ins, peers) -> None:
        # count-of-peers matches the reference's append-only numbering,
        # but a hot reload can REMOVE lib.0 while lib.1 survives — a
        # later add would count one peer and collide on lib.1. Bump
        # past taken names (never reuse a retired name: a fresh
        # instance must not inherit a dead one's metric series)
        n = sum(1 for p in peers if p.plugin.name == ins.plugin.name)
        taken = {p.name for p in peers} \
            | self._retired_names.get(type(ins).__name__, set())
        while f"{ins.plugin.name}.{n}" in taken:
            n += 1
        ins.name = f"{ins.plugin.name}.{n}"
        pool = getattr(ins, "pool", None)
        if pool is not None:
            pool.in_name = ins.name

    def _make_instance(self, create, name: str, props, peers):
        """create + number + set props — shared by the config-time
        builders and the hot-reload build phase (core/qos.py) so the
        construction sequence cannot drift between them."""
        ins = create(name)
        self._number_instance(ins, peers)
        # props is a dict (builder API) or a properties ITEM LIST
        # (hot-reload *_items staging: repeated keys + declared order)
        items = props.items() if hasattr(props, "items") else props
        for k, v in items:
            ins.set(k, v)
        return ins

    def _init_instance(self, ins) -> None:
        """configure + plugin.init + mark initialized — THE live-init
        sequence. start(), hidden_input and hot-reload builds all go
        through here: a future post-init step added in one place
        cannot silently skip the others."""
        ins.configure()
        ins.plugin.init(ins, self)
        ins._initialized = True

    def input(self, name: str, **props) -> InputInstance:
        ins = self._make_instance(self.registry.create_input, name,
                                  props, self.inputs)
        # COW swap: collectors iterate engine.inputs lock-free, so the
        # builder publishes a fresh list instead of mutating the alias
        with self._ingest_lock:
            self.inputs = self.inputs + [ins]
        return ins

    def filter(self, name: str, **props) -> FilterInstance:
        ins = self._make_instance(self.registry.create_filter, name,
                                  props, self.filters)
        # hidden flux-SQL filters stand in for the stream processor,
        # which runs POST-filter at ingest — user filters registered
        # later (config files apply [STREAM_TASK] before [FILTER])
        # must still run BEFORE them or flux would aggregate records
        # the chain was about to drop/rewrite
        pos = len(self.filters)
        while pos > 0 and getattr(self.filters[pos - 1],
                                  "_flux_sql_hidden", False):
            pos -= 1
        # COW swap (see input()): ingest walks engine.filters lock-free
        with self._ingest_lock:
            self.filters = self.filters[:pos] + [ins] + self.filters[pos:]
        return ins

    def output(self, name: str, **props) -> OutputInstance:
        ins = self._make_instance(self.registry.create_output, name,
                                  props, self.outputs)
        # COW swap (see input()): the router reads engine.outputs
        # lock-free while dispatching
        with self._ingest_lock:
            self.outputs = self.outputs + [ins]
        return ins

    def custom(self, name: str, **props):
        """Custom plugin instance (flb_custom_create); initialized
        before the pipeline at start()."""
        ins = self.registry.create_custom(name)
        self._number_instance(ins, self.customs)
        for k, v in props.items():
            ins.set(k, v)
        self.customs.append(ins)
        return ins

    def parser(self, name: str, **props):
        """Create + register a named parser (flb_parser_create)."""
        from ..parsers import create_parser

        p = create_parser(name, **props)
        self.parsers[p.name] = p
        return p

    def ml_parser(self, name: str, rules=None, flush_ms: int = 2000,
                  key_content: str = "log"):
        """Create + register a named multiline parser
        ([MULTILINE_PARSER] section / flb_ml_parser_create)."""
        from ..multiline import MLParser, MLRule

        # from_states may be comma-separated ("start_state,cont" —
        # flb_ml_rule_create splits on comma)
        mlr = [
            MLRule([s.strip() for s in str(r[0]).split(",")], r[1], r[2])
            if not isinstance(r, MLRule) else r
            for r in (rules or [])
        ]
        p = MLParser(name, mlr, flush_ms=flush_ms, key_content=key_content)
        self.ml_parsers[name] = p
        return p

    def sp_task(self, sql: str, allow_flux: bool = True):
        """Register a stream-processor query (flb_sp_create task;
        [STREAM_TASK] Exec). The SP runs synchronously post-filter at
        ingest (src/flb_input_chunk.c:3155) and its window timer rides a
        collector on the SP emitter.

        Sketch-eligible queries transparently resolve against the flux
        plane (fbtpu-flux): a hidden ``flux`` filter maintains the
        aggregation state inside the (batched) filter pass, the task
        reads windows from it, and the raw ingest fast path stays on
        for the query's tag. ``allow_flux=False`` pins the exact
        per-event evaluation (the differential harness's twin), as does
        ``WITH (flux='off')`` per query or FBTPU_FLUX_SQL=off globally.
        """
        import os as _os

        from ..stream_processor import StreamProcessor

        if self.sp is None:
            self.sp = StreamProcessor(self)
        task = self.sp.create_task(sql)
        if allow_flux and _os.environ.get(
                "FBTPU_FLUX_SQL", "on").lower() not in ("0", "off"):
            from ..flux.query import attach_flux

            try:
                attach_flux(self, task)
            except Exception:
                log.exception(
                    "flux attach failed; query %r stays on the exact "
                    "evaluation path", sql)
        # window timer: piggyback a collector on the SP emitter input
        if self.sp._emitter is None:
            ins = self.hidden_input(
                "emitter", alias="emitter_for_stream_processor"
            )
            self.sp._emitter = ins.plugin
            self.sp.emitter_instance = ins
            sp = self.sp

            def _tick(_engine):
                with self._ingest_lock:
                    sp.tick()

            ins.plugin.collect_interval = 0.5
            ins.plugin.collect = _tick
            # tasks may be registered AFTER engine start: _main's
            # startup pass has already run, so schedule the collector
            # ourselves
            self.ensure_collector(ins)
        return task

    def enable_trace(self, input_name: str, output_tag: str = "trace") -> bool:
        """Chunk trace "tap" (src/flb_chunk_trace.c:184-203): stamp each
        append's journey — input + per-filter before/after with timing —
        and re-emit the stamps through a hidden emitter under
        ``output_tag`` so they flow the normal pipeline. Enabled per
        input (CLI -Z / HTTP api/v1/trace equivalent)."""
        target = None
        for ins in self.inputs:
            if input_name in (ins.name, ins.display_name):
                target = ins
                break
        if target is None:
            return False
        if target.name in self.traces:  # canonical key: dedup aliases
            return True
        emitter = self.hidden_input(
            "emitter", owner=target, alias=f"trace_emitter_{target.name}"
        )
        # trace installs race the reap timer / reload commits mutating
        # the same dict from other threads
        with self._ingest_lock:
            self.traces[target.name] = {
                "input": target,
                "output_tag": output_tag,
                "emitter": emitter.plugin,
                "emitter_instance": emitter,
                "count": 0,
            }
        return True

    def disable_trace(self, input_name: str) -> bool:
        key = input_name
        if key not in self.traces:
            for name, ctx in self.traces.items():
                if ctx["input"].display_name == input_name:
                    key = name
                    break
        with self._ingest_lock:
            ctx = self.traces.pop(key, None)
            if ctx is None:
                return False
            # drop the hidden emitter too — repeated enable/disable
            # cycles must not accumulate dead inputs (COW swap:
            # concurrent iterators keep their snapshot)
            self.inputs = [i for i in self.inputs
                           if i is not ctx["emitter_instance"]]
            emitter_ins = ctx["emitter_instance"]
            self._retired_names.setdefault(
                type(emitter_ins).__name__, set()).add(emitter_ins.name)
        return True

    def _trace_ctx(self, ins) -> Optional[dict]:
        if not self.traces:
            return None
        for key in (ins.name, ins.display_name):
            ctx = self.traces.get(key)
            if ctx is not None and ctx["input"] is ins:
                return ctx
        return None

    def _trace_emit(self, ctx: dict, body: dict) -> None:
        from ..codec.events import encode_event, now_event_time

        try:
            ctx["emitter"].add_record(
                ctx["output_tag"], encode_event(body, now_event_time()), 1
            )
        except Exception:
            log.exception("chunk trace emit failed")

    def ensure_collector(self, ins: InputInstance) -> None:
        """Schedule a collector for an input created after start() —
        the SAME dispatch as _main's startup pass: threaded interval
        collectors get their own OS thread (a blocking collect() must
        not stall the flush loop), loop collectors an asyncio task,
        and push servers (server_task_needed) their listener task —
        otherwise a hot-reload-added tcp/http input would never start
        listening."""
        if not self.running or self.loop is None:
            return
        plugin = ins.plugin
        if plugin.collect_interval is not None and ins.threaded:
            if getattr(ins, "collector_thread", None) is None:
                ins.collector_thread = threading.Thread(
                    target=self._collector_thread, args=(ins,),
                    daemon=True,
                    name=f"flb-in-{ins.display_name}",
                )
                ins.collector_thread.start()
            return

        def _create():
            if ins.collector_task is not None:
                return
            if plugin.collect_interval is not None:
                ins.collector_task = asyncio.ensure_future(
                    self._collector(ins))
            elif getattr(plugin, "server_task_needed", False):
                ins.collector_task = asyncio.ensure_future(
                    plugin.start_server(self))

        try:
            self.loop.call_soon_threadsafe(_create)
        except RuntimeError:
            pass

    def hidden_input(self, name: str, owner=None,
                     **props) -> InputInstance:
        """Create + immediately initialize an internal input instance —
        the hidden ``emitter`` pattern used by rewrite_tag /
        log_to_metrics / chunk traces (reference
        plugins/filter_rewrite_tag/rewrite_tag.c:245-260). Safe to call
        from a plugin's init while the engine is starting.

        ``owner`` ties the hidden input's lifecycle to the instance
        whose init created it: when a hot reload removes/replaces that
        owner, the emitter is unlinked with it (core/qos.py ReloadTxn)
        instead of leaking one orphaned input per reload."""
        ins = self._make_instance(self.registry.create_input, name,
                                  props, self.inputs)
        ins._hidden_owner = owner
        # internal replay is never re-metered (core/qos.py admit):
        # these bytes passed tenant admission at their ORIGINAL ingest
        # point, and the re-emit callers (rewrite_tag / multiline /
        # trace taps) are fire-and-forget — a DEFER here would silently
        # drop already-admitted data while counting it "deferred"
        ins.qos_exempt = True
        # COW list swap: hidden inputs appear at RUNTIME (sp emitters,
        # trace taps, rewrite_tag emitters during a hot reload's build
        # phase) while other threads iterate snapshot references
        with self._ingest_lock:
            self.inputs = self.inputs + [ins]
        self._init_instance(ins)
        return ins

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> None:
        """Spawn the engine thread (flb_start → flb_engine_start)."""
        if self._thread is not None:
            raise RuntimeError("engine already started")
        # storage + backlog recovery (flb_storage_create at
        # src/flb_engine.c:979; sb_segregate_chunks at :1129)
        if self.service.storage_path and self.storage is None:
            from .storage import Storage

            self.storage = Storage(self.service.storage_path,
                                   checksum=self.service.storage_checksum)
        if self.storage is not None:
            with self._ingest_lock:  # uniform discipline (fbtpu-lint)
                self._backlog = self.storage.scan_backlog()
        # customs first (flb_custom_init_all, src/flb_engine.c:973):
        # they may create pipeline instances programmatically
        for ins in self.customs:
            if getattr(ins, "_initialized", False):
                continue
            self._init_instance(ins)
        for ins in self.inputs + self.filters + self.outputs:
            if getattr(ins, "_initialized", False):
                continue  # hidden inputs are initialized at creation
            self._init_instance(ins)
        # fbtpu-qos: register every tenant contract EAGERLY, in config
        # order ("last declaration wins") — lazy first-append
        # registration would let input A flood unmetered before
        # sibling input B (carrying the shared tenant's rate) ever
        # ingests
        for ins in self.inputs:
            self.qos.tenant_for_input(ins)
        # output worker thread pools (flb_output_thread_pool_create,
        # src/flb_output_thread.c:472): flush callbacks leave the
        # engine loop when `workers` is set
        for out in self.outputs:
            self._ensure_worker_pool(out)
        self.started_at = time.time()
        self.guard.heartbeat = time.time()
        # failpoint trigger → metric bridge (unarmed plane: the listener
        # list is only walked when a fault actually fires)
        _fp.add_listener(self._on_failpoint_trigger)
        # device fault-domain → metric bridge (fbtpu-armor): healthy
        # lanes emit nothing, so the hot path pays zero here
        from ..ops import fault as _fault

        _fault.add_listener(self._on_device_event)
        self._stopping = False
        self._stop_event.clear()
        self._thread = threading.Thread(target=self._run, name="flb-engine", daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("engine failed to start")

    def _ensure_worker_pool(self, out: OutputInstance) -> None:
        """Build the output's worker pool when configured (start() and
        hot-reload-added outputs share this path)."""
        from .output_thread import OutputWorkerPool

        if out.workers <= 0 or out.worker_pool is not None \
                or out.plugin.synchronous:
            return
        pool = OutputWorkerPool(
            out.display_name, out.workers, out.plugin,
            start_timeout=self.service.guard_worker_start_timeout)
        if pool.failed:
            # a worker that never starts must not leave submit()
            # targeting a dead loop: fail the output over to
            # inline flushes on the engine loop
            log.error(
                "output %s: worker pool startup failed — "
                "failing over to inline flush", out.display_name)
            self.guard.m_worker_start_fail.inc(
                1, (out.display_name,))
            pool.stop()
        else:
            out.worker_pool = pool

    def reload_txn(self):
        """Open a hot-reload transaction (fbtpu-qos, core/qos.py):
        stage add/remove/replace of inputs, filters, outputs and
        parsers, then ``commit()`` swaps the configuration atomically
        behind a generation bump — without dropping in-flight chunks.
        Embedders wire ``self.reload_callback`` to a function that
        builds and commits one of these for POST /api/v2/reload."""
        from .qos import ReloadTxn

        return ReloadTxn(self)

    def _run(self) -> None:
        self.loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self.loop)
        try:
            self.loop.run_until_complete(self._main())
        finally:
            self.loop.close()

    async def _main(self) -> None:
        # start collectors (flb_input_collectors_start, src/flb_engine.c:1090)
        for ins in self.inputs:
            plugin = ins.plugin
            if plugin.collect_interval is not None:
                if ins.threaded:
                    # FLB_INPUT_THREADED equivalent
                    # (src/flb_input_thread.c:225): collection runs on
                    # its own OS thread; append stays thread-safe via
                    # the engine's ingest locking
                    ins.collector_thread = threading.Thread(
                        target=self._collector_thread, args=(ins,),
                        daemon=True,
                        name=f"flb-in-{ins.display_name}",
                    )
                    ins.collector_thread.start()
                else:
                    ins.collector_task = asyncio.ensure_future(
                        self._collector(ins))
            elif getattr(plugin, "server_task_needed", False):
                ins.collector_task = asyncio.ensure_future(plugin.start_server(self))
        # admin HTTP server (flb_hs_create/start, src/flb_engine.c:1074)
        admin_task = None
        if self.service.http_server:
            from .http_server import AdminServer

            self.admin_server = AdminServer(
                self, self.service.http_listen, self.service.http_port
            )
            admin_task = asyncio.ensure_future(self.admin_server.serve())
        self._started.set()
        flush_interval = max(0.02, self.service.flush)
        try:
            while not self._stopping:
                await asyncio.sleep(flush_interval)
                self.flush_all()
            # stop threaded collectors FIRST: anything they append must
            # land before the final flush below, or it would sit in the
            # pool and be lost at shutdown
            self._stop_event.set()
            for ins in self.inputs:
                t = getattr(ins, "collector_thread", None)
                if t is not None and t.is_alive():
                    await asyncio.get_event_loop().run_in_executor(
                        None, t.join, self.service.grace + 2.0)
            # graceful drain (grace period, src/flb_engine.c:1137-1160):
            # let plugins flush held state (pending multiline groups)
            # BEFORE the final chunk drain so nothing is lost at stop
            for ins in self.inputs + self.filters + self.outputs:
                drain = getattr(ins.plugin, "drain", None)
                if drain is not None:
                    try:
                        drain(self)
                    except Exception:
                        log.exception("%s drain failed", ins.display_name)
                # attached processors may hold state too (tail sampler's
                # undecided traces): give them the same drain window
                for proc in getattr(ins, "processors", None) or []:
                    pdrain = getattr(proc.plugin, "drain", None)
                    if pdrain is not None:
                        try:
                            pdrain(self)
                        except Exception:
                            log.exception("%s processor drain failed",
                                          proc.name)
            if self.sp is not None:  # flush open SQL windows
                with self._ingest_lock:
                    try:
                        self.sp.drain()
                    except Exception:
                        log.exception("stream processor drain failed")
            # shed chunks re-enter the backlog so the shutdown drain
            # (and its quarantine accounting) sees them
            self.guard.readmit_all()
            self.flush_all()
            await asyncio.sleep(0.05)  # let queued _create callbacks run
            deadline = time.time() + self.service.grace
            while self._pending_flushes and time.time() < deadline:
                await asyncio.sleep(0.02)
            # cancel stragglers (in-flight flush attempts)
            for fut in list(self._pending_flushes):
                fut.cancel()
            if self._pending_flushes:
                await asyncio.gather(*self._pending_flushes, return_exceptions=True)
            # pending scheduler retries: cancel their timers and
            # quarantine undelivered memory chunks (same semantics as a
            # cancelled in-flight flush)
            for key, (task, out, handle) in list(
                    self._pending_retries.items()):
                handle.cancel()
                self._drop_retry(task, out)
            self._pending_retries.clear()
        finally:
            # an abnormal loop exit (exception above) must still stop
            # collector threads — they check _stopping/_stop_event
            self._stopping = True
            self._stop_event.set()
            pending = []
            for ins in self.inputs:
                if ins.collector_task is not None:
                    ins.collector_task.cancel()
                    pending.append(ins.collector_task)
                t = getattr(ins, "collector_thread", None)
                if t is not None and t.is_alive():
                    t.join(timeout=2.0)
            if admin_task is not None:
                admin_task.cancel()
                pending.append(admin_task)
            if pending:  # let cancellations run their cleanup (finally:)
                await asyncio.gather(*pending, return_exceptions=True)
            self._started.clear()

    def _collector_delay(self, ins: InputInstance,
                         interval: float) -> float:
        """Collector pacing: a DEFER-paused input sleeps for the qos
        bucket's predicted refill time (Qos.defer_hint on the dropped
        append's size) instead of spin-polling every interval while the
        pause flag stays set. Capped at 30s so a starved tenant still
        re-checks (resume_paused may clear the pause for other reasons
        — config reload, quota raise); never below the configured
        interval."""
        if not getattr(ins, "paused_by_qos", False):
            return interval
        try:
            cost = int(getattr(ins, "_qos_defer_cost", 0)) or 1
            hint = float(self.qos.defer_hint(ins, cost))
        except Exception:
            return interval
        return max(interval, min(hint, 30.0))

    async def _collector(self, ins: InputInstance) -> None:
        """Interval collector (flb_input_set_collector_time)."""
        interval = ins.plugin.collect_interval or 1.0
        # hot reload removes inputs mid-run: the flag stops collection
        # even when the cancel races a collect in flight
        while not ins.removed:
            try:
                if not ins.paused:
                    ins.plugin.collect(self)
            except Exception:
                log.exception("input %s collect failed", ins.display_name)
            await asyncio.sleep(self._collector_delay(ins, interval))

    def _collector_thread(self, ins: InputInstance) -> None:
        """Threaded-input collector loop (reference
        input_thread_instance_create, src/flb_input_thread.c:225): the
        plugin's collect — file reads, socket drains, line splitting,
        encoding — runs off the engine loop so slow inputs never stall
        flushes, and independent inputs collect in parallel."""
        interval = ins.plugin.collect_interval or 1.0
        while not self._stopping and not ins.removed:
            try:
                if not ins.paused:
                    ins.plugin.collect(self)
            except Exception:
                log.exception("input %s collect failed", ins.display_name)
            if self._stop_event.wait(  # instant stop wakeup
                    self._collector_delay(ins, interval)):
                break
        if ins.removed:
            # hot reload removed this input: this thread owns the
            # plugin's I/O, so exiting HERE guarantees no collect() is
            # in flight when files/sockets close (ReloadTxn skips the
            # inline exit while this thread is alive or this flag is
            # set — flag BEFORE exit so the reload's liveness check
            # can never observe dead-thread-and-unset-flag after we
            # exited). Engine stop leaves removed=False and keeps the
            # stop()-path exit.
            ins._exited_by_collector = True
            try:
                ins.plugin.exit()
            except Exception:
                log.exception("removed input %s exit failed",
                              ins.display_name)

    def request_stop(self) -> None:
        """Ask the engine loop to shut down gracefully (the in-pipeline
        stop used by out_exit / filter_expect's exit action / in_exec's
        exit_after_oneshot). The loop drains and exits; call stop() to
        join the thread."""
        self._stopping = True

    def stop(self) -> None:
        """Graceful stop with drain (flb_stop)."""
        if self._thread is None:
            return
        self._stopping = True
        # barrier: an in-flight hot-reload commit (HTTP thread) may be
        # about to retire outputs — wait for it to finish so its
        # retired list is visible to the reap below; commits arriving
        # AFTER this point see _stopping under the same lock and
        # refuse (core/qos.py ReloadTxn.commit), so none can slip in
        # behind the reap and leak un-exited pools
        with self._reload_lock:
            pass
        self._thread.join(timeout=self.service.grace + 10)
        if self._thread.is_alive():
            # a silently-swallowed join timeout leaves a wedged engine
            # undiagnosable: say so, and dump every thread's stack
            self._dump_stuck_shutdown()
        self._thread = None
        # hot-reload-retired outputs kept their pools alive for
        # in-flight flushes; the drain above has settled them. Swap
        # under the lock: a reload commit on another thread extends
        # this list under _ingest_lock, and an unlocked swap racing it
        # would strand its outputs on a list nobody reaps
        with self._ingest_lock:
            retired, self._retired_outputs = self._retired_outputs, []
        for out in self.outputs + retired:
            if out.worker_pool is not None:
                out.worker_pool.stop()
                out.worker_pool = None
        for ins in self.inputs + self.filters + self.outputs \
                + retired + self.customs:
            try:
                ins.plugin.exit()
            except Exception:
                log.exception("%s exit failed", ins.display_name)
        try:
            if self.storage is not None:
                self.storage.close()
        finally:
            # always release the module-global listeners: a teardown
            # error must not pin this engine (and its metrics) forever
            _fp.remove_listener(self._on_failpoint_trigger)
            try:
                from ..ops import fault as _fault

                _fault.remove_listener(self._on_device_event)
            except Exception:
                log.exception("device fault listener release failed")

    def _dump_stuck_shutdown(self) -> None:
        """The engine thread outlived grace+10s at stop(): log it and
        dump all thread stacks via faulthandler so a wedged shutdown
        (a flush stuck in C code, a deadlocked lock) is diagnosable
        from the crash report instead of a silent hang."""
        import faulthandler
        import sys

        log.warning(
            "engine thread did not exit within %.1fs at stop() — "
            "shutdown is stuck; dumping all thread stacks to stderr",
            self.service.grace + 10)
        try:
            faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
        except Exception:
            log.exception("thread stack dump failed")

    def _on_failpoint_trigger(self, name: str, _action: str) -> None:
        self.m_failpoint_triggered.inc(1, (name,))

    def _on_device_event(self, lane: str, event: str, value) -> None:
        """fbtpu-armor listener bridge → fluentbit_device_* metrics
        (ops/fault.py event vocabulary)."""
        if event == "fallback" or event == "short_circuit":
            self.m_device_fallback.inc(1, (lane,))
        elif event == "timeout":
            self.m_device_timeouts.inc(1, (lane,))
        elif event == "failure":
            self.m_device_failures.inc(1, (lane,))
        elif event == "device_lost":
            self.m_device_lost.inc(1, (lane,))
        elif event == "breaker":
            code = {"closed": 0, "half-open": 1, "open": 2}.get(value, 0)
            self.m_device_breaker.set(code, (lane,))
        elif event == "mesh_devices":
            self.m_device_mesh.set(float(value), (lane,))
        elif event == "reattach":
            self.m_device_reattach.inc(1)

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # ------------------------------------------------------------------
    # ingest path (reference: flb_input_log_append → input_chunk_append_raw)
    # ------------------------------------------------------------------

    def input_log_append(self, ins: InputInstance, tag: Optional[str],
                         data: bytes, n_records: Optional[int] = None) -> int:
        """Append encoded log events; runs processors then the filter chain
        synchronously (src/flb_input_chunk.c:3078), then writes the chunk.

        Returns number of records written (post-filter), or -1 when the
        append was rejected by backpressure (reference
        flb_input_chunk_append_raw returns -1 on paused/overlimit).
        Thread-safe: the whole ingest path (processors + filters + append)
        runs under the ingest lock, serializing stateful filters exactly
        like the reference's single engine thread does.
        """
        tag = tag or ins.tag or ins.plugin.name

        # backpressure FIRST (mem_buf_limit, src/flb_input.c:157,740-746;
        # storage.pause_on_chunks_overlimit, :169) — pool counters are
        # snapshotted under the input's lock (parallel raw-path appends
        # mutate them concurrently); the pause flip itself is atomic in
        # set_paused. Runs before tenant admission so a rejected append
        # does NOT charge the tenant's token bucket: the caller retries
        # the same bytes, and charging every retry would drain quota on
        # data that was never ingested
        with ins.ingest_lock:
            over = ins.storage_type != "memrb" and ((
                ins.mem_buf_limit
                and ins.pool.pending_bytes >= ins.mem_buf_limit
            ) or (
                getattr(ins, "pause_on_chunks_overlimit", False)
                and ins.pool.pending_chunks
                >= self.service.storage_max_chunks_up
            ))
        if over:
            ins.set_paused(True)
            return -1

        # fbtpu-qos tenant admission (core/qos.py): every ingest entry
        # point meters the append against its tenant's token bucket
        # BEFORE any decode/filter work — over quota, DEFER (1) is the
        # reference's backpressure verdict (-1, caller retries) and
        # SHED (2) drops the append with per-tenant accounting
        verdict = self.qos.admit(ins, len(data))
        if verdict:
            if verdict == 1:
                # DEFER uses the SAME pause contract as mem_buf_limit:
                # collector/server inputs ignore -1 and have already
                # consumed their source, so without the pause every
                # over-quota read would be silently dropped while
                # counted "deferred". Paused collectors stop consuming;
                # housekeeping resumes once the bucket can admit this
                # append's size again (resuming on a single token
                # would churn: consume → defer-drop → re-pause)
                ins._qos_defer_cost = len(data)
                ins.paused_by_qos = True
                ins.set_paused(True)
                return -1
            return 0

        # memrb storage: a ring buffer — over the limit, the OLDEST
        # buffered chunks are evicted with drop metrics instead of
        # pausing the input (src/flb_input_chunk.c:2936-2966)
        if ins.storage_type == "memrb":
            limit = ins.mem_buf_limit or 10 * 1024 * 1024
            # read + evict atomically under the input lock; sized on
            # the incoming raw bytes, matching the reference's
            # pre-filter check (src/flb_input_chunk.c:2936, which runs
            # before flb_filter_do at :3078)
            with ins.ingest_lock:
                need = ins.pool.pending_bytes + len(data) - limit
                evicted = ins.pool.evict_oldest(need) if need > 0 else []
            for c in evicted:
                self.m_memrb_dropped_chunks.inc(
                    1, (ins.display_name,))
                self.m_memrb_dropped_bytes.inc(
                    c.size, (ins.display_name,))

        # ---- raw fast path (VERDICT r1: no decode-per-append) ----
        # When nothing on the chain needs decoded events — no
        # processors, no stream-processor task, and every matching
        # filter can operate on raw chunk bytes (grep's native
        # staging) — records are counted by the native msgpack
        # scanner and appended as raw spans. When additionally every
        # matching filter is stateless (thread_safe_raw), the chain runs
        # under the INPUT's lock only, so independent inputs ingest in
        # parallel (VERDICT r2 #4: the global lock stops serializing
        # independent tags; reference threaded inputs + per-input chunk
        # maps, src/flb_input_thread.c:225).
        matching = [f for f in self.filters if f.route.matches(tag)]
        # flux-backed tasks don't need decoded events — their hidden
        # flux filter (in `matching`) absorbs on the raw chain, so they
        # must not force the decode path (that is the whole point)
        sp_active = (
            self.sp is not None
            and self.sp.tasks
            and ins is not self.sp.emitter_instance
            and any(t.matches(tag) and t.flux is None
                    for t in self.sp.tasks)
        )
        cond_routing = any(
            o.route_condition is not None and o.route.matches(tag)
            for o in self.outputs
        )
        raw_ok = (
            not ins.processors
            and not sp_active
            and not cond_routing  # per-record splits need decoded events
            and self._trace_ctx(ins) is None
            and all(
                getattr(f.plugin, "can_filter_raw", lambda: False)()
                or f.plugin.can_process_batch()
                for f in matching
            )
        )
        if raw_ok:
            # stateful chains are pinned to the global lock even when
            # every filter is thread_safe_raw: a stateful hook's side
            # effects (emitter re-emits) re-enter input_log_append,
            # which takes self._ingest_lock — under ins.ingest_lock
            # that re-entry would invert the canonical
            # Engine._ingest_lock -> InputInstance.ingest_lock order
            # (fbtpu-locksmith lock-order-cycle)
            parallel = all(
                getattr(f.plugin, "thread_safe_raw", False)
                and not getattr(f.plugin, "stateful_batch", False)
                for f in matching
            )
            # two lexical branches, not a lock alias: the locksmith
            # order-graph walk resolves `with self._X:` scopes, not
            # conditionally-bound aliases
            if parallel:
                with ins.ingest_lock:
                    got = self._ingest_raw(ins, tag, data, matching,
                                           n_records)
            else:
                with self._ingest_lock:
                    got = self._ingest_raw(ins, tag, data, matching,
                                           n_records)
            if isinstance(got, _RawTail):
                # a mid-chain decline after committed side effects:
                # finish per-record OUTSIDE the raw-path lock scope —
                # the tail takes self._ingest_lock itself, and taking
                # it while still holding ins.ingest_lock would be the
                # inversion the order graph forbids
                got = self._finish_raw_tail(ins, got)
            if got is not None:
                return got

        with self._ingest_lock:
            # expose the appending input to filters that must recognise
            # their own emitter's records (filter_multiline's and
            # filter_rewrite_tag's i_ins == ctx->ins_emitter checks in
            # the reference). Saved/restored because emitters re-enter
            # input_log_append synchronously mid-chain — without the
            # restore the OUTER chain's remaining filters would see the
            # nested append's source
            prev_src = self._ingest_src
            self._ingest_src = ins
            try:
                return self._log_append_decoded(ins, tag, data,
                                                n_records, cond_routing)
            finally:
                self._ingest_src = prev_src

    def _log_append_decoded(self, ins, tag, data, n_records, cond_routing):
        """The decode branch of input_log_append (runs under the global
        ingest lock, with _ingest_src already pointing at ``ins``)."""
        if ins.removed:
            # hot reload unlinked this input (see _ingest_raw): refuse
            # so the caller never acks into the orphaned pool
            self.qos.refund(ins, len(data))
            return 0
        events = decode_events(data)
        if n_records is None:
            n_records = len(events)
        self.m_in_records.inc(n_records, (ins.display_name,))
        self.m_in_bytes.inc(len(data), (ins.display_name,))

        # input-side processors (flb_processor_run, src/flb_input_log.c:1562)
        events = self._run_log_processors(ins.processors, events, tag)
        if not events:
            return 0

        # chunk trace: input stamp (flb_chunk_trace_do_input,
        # src/flb_input_chunk.c:3049)
        trace_ctx = self._trace_ctx(ins)
        if trace_ctx is not None:
            trace_ctx["count"] += 1
            trace_ctx["trace_id"] = trace_id = \
                f"{ins.name}.{trace_ctx['count']}"
            self._trace_emit(trace_ctx, {
                "type": "input", "trace_id": trace_id,
                "input_instance": ins.display_name, "tag": tag,
                "records": n_records,
            })

        # filter chain — synchronous, pre-storage
        events = self._run_filters(events, tag, trace_ctx)
        if not events:
            return 0

        # stream processor on the filtered records (flb_sp_do,
        # src/flb_input_chunk.c:3155); never on its OWN emitter's
        # records — a task whose TAG pattern matches its output tag
        # must not feed back into itself
        if (
            self.sp is not None
            and self.sp.tasks
            and ins is not self.sp.emitter_instance
        ):
            try:
                self.sp.do(events, tag)
            except Exception:
                log.exception("stream processor failed")

        if cond_routing:
            # split_and_append_route_payloads
            # (src/flb_input_log.c:1495): group records by the set
            # of outputs whose condition admits them; each group
            # lands in its own chunk carrying that route bitmask
            groups: Dict[int, bytearray] = {}
            counts: Dict[int, int] = {}
            ends: Dict[int, list] = {}  # record END offsets per group
            # tag is constant for the append: resolve the matching
            # candidates once, per-record work is condition eval only
            candidates = [
                (1 << i, o.route_condition)
                for i, o in enumerate(self.outputs)
                if o.route.matches(tag)
            ]
            for ev in events:
                mask = 0
                for bit, cond in candidates:
                    if cond is None or cond.eval(ev.body):
                        mask |= bit
                if mask == 0:
                    # no output admits this record (every matching
                    # route's condition failed): nothing to deliver
                    # — parity with dispatch finding zero routes
                    continue
                raw = ev.raw if ev.raw is not None \
                    else reencode_event(ev)
                buf = groups.setdefault(mask, bytearray())
                buf.extend(raw)
                ends.setdefault(mask, []).append(len(buf))
                counts[mask] = counts.get(mask, 0) + 1
            with ins.ingest_lock:
                for mask, buf in groups.items():
                    # ONE materialization per group: the pool append
                    # adopts the same bytes object write_through
                    # persists (this branch used to call bytes(buf)
                    # twice — memscope host-redundant-copy)
                    payload = bytes(buf)
                    if _cw.witness_enabled():
                        _cw.count("engine.cond.materialize",
                                  len(payload))
                    chunk = ins.pool.append(
                        tag, payload, counts[mask],
                        routes_mask=mask)
                    if chunk.route_names is None:
                        # persisted form: NAMES, not bit positions
                        # — conditional routing must survive a
                        # restart with reordered outputs
                        chunk.route_names = tuple(
                            o.display_name
                            for i, o in enumerate(self.outputs)
                            if (mask >> i) & 1
                        )
                    self._persist(ins, chunk, payload,
                                  offsets=ends[mask])
            return len(events)

        out = bytearray()
        rec_ends = []  # per-event END offsets: the sidecar gets them free
        for ev in events:
            out += ev.raw if ev.raw is not None else reencode_event(ev)
            rec_ends.append(len(out))
        # ONE materialization: pool append + write-through share the
        # same bytes object (this used to be two full bytes(out) copies
        # of every decoded append — memscope host-redundant-copy)
        payload = bytes(out)
        if _cw.witness_enabled():
            _cw.count("engine.decoded.materialize", len(payload))
        with ins.ingest_lock:
            chunk = ins.pool.append(tag, payload, len(events))
            self._persist(ins, chunk, payload, offsets=rec_ends)
        return len(events)

    def input_event_append(self, ins: InputInstance, tag: Optional[str],
                           data: bytes, event_type: str, n_records: int = 1) -> int:
        """Non-log telemetry append (metrics/traces/profiles): no filter
        chain (reference typed appends, src/flb_input_metric.c etc.)."""
        tag = tag or ins.tag or ins.plugin.name
        in_bytes = len(data)  # pre-processor size: what admit charged
        # same tenant admission contract as input_log_append
        verdict = self.qos.admit(ins, in_bytes)
        if verdict:
            if verdict == 1:
                # DEFER pauses (see input_log_append): fire-and-forget
                # typed appenders must stop consuming until refill
                ins._qos_defer_cost = in_bytes
                ins.paused_by_qos = True
                ins.set_paused(True)
                return -1
            return 0
        with self._ingest_lock:
            # input-side metrics/traces processors (flb_processor_run on
            # the typed append path)
            if ins.processors and event_type == EVENT_TYPE_METRICS:
                data = self._run_metrics_processors(ins.processors, data, tag)
            elif ins.processors and event_type == EVENT_TYPE_TRACES:
                data, n_records = self._run_traces_processors(
                    ins.processors, data, tag, n_records)
                if not data:
                    # all spans buffered (tail sampling) or dropped —
                    # consumed, so counted as ingested
                    self.m_in_records.inc(n_records, (ins.display_name,))
                    self.m_in_bytes.inc(in_bytes, (ins.display_name,))
                    return n_records
            with ins.ingest_lock:
                if ins.removed:
                    # hot reload unlinked this input: its pool was
                    # drained and will never be visited again — refuse
                    # (un-acked) instead of appending into the orphan
                    self.qos.refund(ins, in_bytes)
                    return 0
                # counted only once the append actually lands (a
                # removed-input refusal retried by the caller must not
                # double-count)
                self.m_in_records.inc(n_records, (ins.display_name,))
                self.m_in_bytes.inc(in_bytes, (ins.display_name,))
                chunk = ins.pool.append(tag, data, n_records, event_type)
                self._persist(ins, chunk, data)
        return n_records

    def _ingest_raw(self, ins, tag: str, data: bytes, matching,
                    n_records: Optional[int]):
        """Append without Python decode. Returns the appended record
        count, None (caller falls back to the decode path: native
        unavailable / a pure-prefix filter decline), or a ``_RawTail``
        continuation (decline AFTER committed side effects — the caller
        runs it via ``_finish_raw_tail`` once the raw-path lock is
        released)."""
        from ..codec import events as _events

        from .chunk_batch import RawChunk

        if ins.removed:
            # hot reload unlinked this input while we waited on the
            # ingest lock: its pool is drained and orphaned — refuse
            # (0 ingested, so the caller never acks). ReloadTxn sets
            # the flag under BOTH locks, so whichever lock this path
            # holds serializes against the swap.
            self.qos.refund(ins, len(data))
            return 0
        in_bytes = len(data)
        # n may stay None until the FIRST raw filter discovers it (the
        # fused grep walk returns the record count as a third element),
        # skipping the counting pre-pass on the hot path entirely
        n = n_records
        # one chunk view travels the whole chain: the record count one
        # filter discovers is reused as the next one's n_hint
        chunk = RawChunk(data, tag, n, src=ins, engine=self)
        deltas = []  # metric updates deferred until the chain commits:
        committed = False  # True once a stateful hook's effects are out
        for fi, f in enumerate(matching):
            prev = data     # a later decline re-runs the decode path,
            got = None      # which must not double-count earlier drops
            plugin = f.plugin
            try:
                if plugin.can_process_batch():
                    if chunk.data is not data:
                        chunk.replace(data, n)
                    else:
                        chunk.n = n
                    if getattr(plugin, "stateful_batch", False):
                        # marked BEFORE the call: a hook raising after
                        # partial emits must not trigger a full decode
                        # re-run (the tail continuation re-runs only
                        # THIS filter onward — strictly fewer doubled
                        # effects than restarting the chain; a clean
                        # decline costs nothing extra since the tail
                        # is bit-exact with the decode path)
                        committed = True
                    got = plugin.process_batch(chunk)
                if got is None and getattr(
                        plugin, "can_filter_raw", None) is not None \
                        and plugin.can_filter_raw():
                    got = plugin.filter_raw(data, tag, self, n_records=n)
            except Exception:
                log.exception("filter %s raw path failed", f.display_name)
                got = None
            if got is None:
                self.m_filter_batch_decline.inc(1, (f.display_name,))
                if not committed:
                    return None  # pure prefix: decode path re-runs it
                # an upstream stateful filter already emitted records /
                # bumped metrics — re-running the whole chain on the
                # decode path would double those side effects. Hand the
                # caller a continuation: the REMAINING filters finish
                # per-record (same code the decode path runs:
                # bit-exact) via _finish_raw_tail, AFTER the raw-path
                # lock is released — the tail takes self._ingest_lock
                # itself, and nesting that under ins.ingest_lock would
                # invert the canonical order (fbtpu-locksmith)
                return _RawTail(tag, data, matching[fi:], n, n_records,
                                deltas, in_bytes)
            if len(got) == 3:
                n2, data, n_in = got
                if n is None:
                    n = n_in
            else:
                n2, data = got
                if n is None:  # filter didn't count: count its input
                    n = _events.fast_count_records(prev)
                    if n is None:
                        if not committed:
                            return None
                        # committed effects forbid a decode re-run and
                        # the input count is unrecoverable: skip this
                        # filter's drop/add delta (its output count n2
                        # is still exact)
                        log.warning(
                            "filter %s output uncountable after a "
                            "committed batch stage; its filter metrics "
                            "delta is skipped", f.display_name)
                        n = n2
            deltas.append((f.display_name, n, n2))
            n = n2
            if n == 0:
                break
        if n is None:  # no filter matched: count natively
            n = _events.fast_count_records(data)
            if n is None:
                return None
        return self._finish_raw_append(ins, tag, data, n, n_records,
                                       deltas, in_bytes)

    def _persist(self, ins, chunk, data, offsets=None) -> None:
        """Write-through behind the tenant storage quota
        (``Qos.admit_storage``): over ``tenant.storage_limit`` the
        append's persistence is SHED — the chunk stays memory-buffered
        and delivery proceeds, only crash durability for the shed bytes
        is given up (``fluentbit_storage_quota_shed_bytes_total``)."""
        if self.storage is None or ins.storage_type != "filesystem":
            return
        from .qos import SHED

        if self.qos.admit_storage(ins, chunk, len(data)) == SHED:
            return
        self.storage.write_through(chunk, data, offsets=offsets)

    def _finish_raw_append(self, ins, tag: str, data, n, n_records,
                           deltas, in_bytes: int) -> int:
        """The raw path's commit epilogue: deferred filter metric
        deltas, ingest accounting, pool append. Shared by the straight
        -through chain and the decline-after-commit tail continuation."""
        if n_records is None:
            n_records = deltas[0][1] if deltas else n
        for name, before, after in deltas:
            if after < before:
                self.m_filter_drop.inc(before - after, (name,))
            elif after > before:
                self.m_filter_add.inc(after - before, (name,))
        self.m_in_records.inc(n_records, (ins.display_name,))
        self.m_in_bytes.inc(in_bytes, (ins.display_name,))
        if n == 0:
            return 0
        with ins.ingest_lock:  # no-op re-entry on the parallel path
            chunk = ins.pool.append(tag, data, n)
            self._persist(ins, chunk, data)
        return n

    def _finish_raw_tail(self, ins, cont: "_RawTail") -> int:
        """Run a _RawTail continuation: decode-path finish of the
        remaining filters, then the shared commit epilogue. MUST be
        called with no raw-path lock held (see _RawTail)."""
        tail = self._raw_tail_decoded(cont.data, cont.tag,
                                      cont.remaining, ins)
        n, data, n_records = cont.n, cont.data, cont.n_records
        if tail is not None:
            n2, data, n_in = tail
            if n_records is None and not cont.deltas:
                # the first matching filter declined before any count
                # was discovered: the tail's decode IS the append's
                # input count (m_in_records accounting)
                n_records = n_in
            # the tail's per-filter drop/add metrics were counted
            # inside _run_filters — no deltas entry here
            n = n2
        # tail None → undecodable mid-chain output (a filter contract
        # violation): append the current bytes as-is rather than losing
        # the chunk
        if tail is None and n is None:
            from ..codec import events as _events
            n = _events.fast_count_records(data)
            if n is None:
                return None  # decode-path fallback (pre-split parity)
        return self._finish_raw_append(ins, cont.tag, data, n,
                                       n_records, cont.deltas,
                                       cont.in_bytes)

    def _raw_tail_decoded(self, data, tag: str, remaining, ins):
        """Finish a raw chain per-record after a mid-chain decline once
        an earlier stateful filter's side effects (emitter re-emits,
        metric bumps) are already visible — re-running the whole chain
        on the decode path would double them. Runs exactly the decode
        path's filter code on the remaining filters only, with
        ``_ingest_src`` pointing at the appending input so own-emitter
        re-entry guards (rewrite_tag, multiline) fire exactly as they
        do on the decode path. Returns (n_out, data_out, n_in) or None
        when the current bytes do not decode (a filter contract
        violation: the append then lands as-is rather than losing the
        chunk)."""
        try:
            events = decode_events(bytes(data))
        except Exception:
            log.exception("raw-chain tail decode failed; remaining "
                          "filters skipped for this append")
            return None
        n_in = len(events)
        # runs via _finish_raw_tail with NO raw-path lock held (a
        # stateful chain's raw pass released self._ingest_lock before
        # the continuation fired); the save/restore mirrors
        # input_log_append's
        with self._ingest_lock:
            prev_src = self._ingest_src
            self._ingest_src = ins
            try:
                events = self._run_filters(events, tag, None,
                                           filters=remaining)
            finally:
                self._ingest_src = prev_src
        out = bytearray()
        for ev in events:
            out += ev.raw if ev.raw is not None else reencode_event(ev)
        return (len(events), bytes(out), n_in)

    def _run_log_processors(self, procs, events, tag: str):
        """Processor pipeline with per-unit conditions
        (flb_processor.h:69-90: a unit may carry a condition; events
        that fail it pass through the unit untouched)."""
        for proc in procs:
            if not events:
                break
            cond = getattr(proc, "condition", None)
            if cond is None:
                events = proc.plugin.process_logs(events, tag, self)
                continue
            out = []
            for ev in events:
                if cond.eval(ev.body):
                    out.extend(proc.plugin.process_logs([ev], tag, self))
                else:
                    out.append(ev)
            events = out
        return events

    def _run_payload_processors(self, procs, data: bytes, tag: str,
                                method: str) -> Optional[bytes]:
        """Shared unpack → per-plugin pipeline → repack shape for the
        typed (metrics/traces) processor paths. Returns the re-encoded
        payloads, b"" when a stage consumed everything, or None on
        pipeline failure (caller keeps the original bytes)."""
        from ..codec.msgpack import Unpacker, packb

        try:
            payloads = list(Unpacker(data))
            for proc in procs:
                payloads = getattr(proc.plugin, method)(payloads, tag, self)
                if not payloads:
                    return b""
            return b"".join(packb(p) for p in payloads)
        except Exception:
            log.exception("%s processor pipeline failed", method)
            return None

    def _run_metrics_processors(self, procs, data: bytes, tag: str) -> bytes:
        """Run a metrics processor pipeline over encoded payloads."""
        out = self._run_payload_processors(procs, data, tag,
                                           "process_metrics")
        return data if out is None else out

    def _run_traces_processors(self, procs, data: bytes, tag: str,
                               n_records: int):
        """Run a traces processor pipeline over encoded typed payloads
        (flb_processor_run on the trace append path,
        src/flb_input_trace.c). Returns (data, n_spans); b"" data means
        every span was consumed (dropped, or buffered by a tail sampler
        that re-injects later via its emitter)."""
        from ..codec.msgpack import Unpacker
        from ..codec.telemetry import count_spans

        out = self._run_payload_processors(procs, data, tag,
                                           "process_traces")
        if out is None:
            return data, n_records
        if not out:
            return b"", 0
        return out, sum(count_spans(p) for p in Unpacker(out))

    def _run_filters(self, events: List[LogEvent], tag: str,
                     trace_ctx: Optional[dict] = None,
                     filters: Optional[List[FilterInstance]] = None
                     ) -> List[LogEvent]:
        """flb_filter_do equivalent (src/flb_filter.c:119-330), with the
        chunk-trace per-filter stamps (flb_chunk_trace_filter hooks,
        src/flb_filter.c:248,312) when a tap is active. ``filters``
        restricts the pass to a sub-chain (the raw path's decoded-tail
        continuation)."""
        for f in (self.filters if filters is None else filters):
            if not events:
                break
            if not f.route.matches(tag):
                continue
            before = len(events)
            t0 = time.perf_counter_ns() if trace_ctx is not None else 0
            try:
                result, new_events = f.plugin.filter(events, tag, self)
            except Exception:
                log.exception("filter %s failed", f.display_name)
                continue
            if trace_ctx is not None:
                after = (len(new_events) if new_events is not None else 0) \
                    if result == FilterResult.MODIFIED else before
                self._trace_emit(trace_ctx, {
                    "type": "filter",
                    "trace_id": trace_ctx.get("trace_id", ""),
                    "filter_instance": f.display_name,
                    "records_in": before,
                    "records_out": after,
                    "elapsed_ns": time.perf_counter_ns() - t0,
                })
            if result == FilterResult.MODIFIED:
                events = new_events if new_events is not None else []
                # modified events lose raw identity unless the filter kept it
                after = len(events)
                if after > before:
                    self.m_filter_add.inc(after - before, (f.display_name,))
                elif after < before:
                    self.m_filter_drop.inc(before - after, (f.display_name,))
        return events

    # ------------------------------------------------------------------
    # dispatch + flush (reference: flb_engine_flush → flb_engine_dispatch)
    # ------------------------------------------------------------------

    def flush_all(self) -> None:
        """Drain ready chunks into tasks and start per-route flushes."""
        if self.started_at:
            self.m_uptime.set(time.time() - self.started_at)
        # guard watchdog rides this (the housekeeping timer): heartbeat,
        # flush-deadline scan, occupancy gauges, shed/readmit — never a
        # per-record cost (core/guard.py); qos queue gauges ride the
        # same timer
        self.guard.housekeeping()
        self.qos.update_gauges()
        self.qos.resume_paused(self.inputs)
        self._reap_retired_outputs()
        with self._ingest_lock:
            chunks: List[tuple] = []
            if self._backlog:  # recovered chunks re-dispatch first
                chunks.extend((None, c) for c in self._backlog)
                self._backlog = []
            for ins in self.inputs:
                with ins.ingest_lock:  # parallel raw ingest appends
                    drained = ins.pool.drain()
                for chunk in drained:
                    if (
                        self.storage is not None
                        and ins.storage_type == "filesystem"
                    ):
                        self.storage.finalize(chunk)
                    chunks.append((ins, chunk))
                # resume paused inputs once the buffer drains (pool
                # counters read under the input's lock; flip is atomic)
                # — but NOT quota pauses: the pool draining says
                # nothing about the token bucket, and resuming early
                # would let the collector consume reads the very next
                # DEFER drops (Qos.resume_paused owns that resume)
                if ins.paused and not getattr(ins, "paused_by_qos",
                                              False):
                    with ins.ingest_lock:
                        drained_ok = (
                            not ins.mem_buf_limit
                            or ins.pool.pending_bytes < ins.mem_buf_limit
                        ) and (
                            not getattr(ins, "pause_on_chunks_overlimit",
                                        False)
                            or ins.pool.pending_chunks
                            < self.service.storage_max_chunks_up
                        )
                    if drained_ok:
                        ins.set_paused(False)
        if _fp.ACTIVE and chunks:
            # between finalize and task spawn: a crash here leaves every
            # drained chunk finalized-but-undelivered on disk — the
            # strictest recovery case (all bytes + CRCs present, zero
            # delivery acks)
            try:
                _fp.fire("engine.flush_dispatch")
            except _fp.FailpointError:
                # injected non-crash dispatch failure: this cycle is
                # aborted, but the chunks were already drained from
                # their pools — park them for the next cycle instead of
                # letting the error kill the engine loop (panic keeps
                # its bug semantics and propagates)
                log.warning("flush dispatch failed (injected); %d "
                            "chunk(s) re-queued", len(chunks))
                with self._ingest_lock:
                    self._backlog.extend(c for _i, c in chunks)
                return
        # fbtpu-qos weighted-fair dispatch (core/qos.py): ready chunks
        # drain through per-tenant bucket queues — strict priority
        # across classes, deficit-weighted round robin across tenants
        # within a class — instead of input configuration order. When
        # dispatch capacity is scarce (task map near full, or a
        # qos.cycle_budget set), the scarce slots are allocated by
        # weight, so one flooding tenant saturates only its own share.
        qos = self.qos
        for ins, chunk in chunks:
            qos.enqueue(ins, chunk)
        budget = self.service.qos_cycle_budget
        spent = 0
        while True:
            chunk = qos.pop_ready()
            if chunk is None:
                break
            rc = self._dispatch_chunk(chunk)
            if not rc:
                # task map full: park this chunk and everything still
                # queued on the backlog for the next cycle (drain pops
                # in scheduler order, so fairness order is preserved)
                leftovers = [chunk] + qos.drain_pending()
                with self._ingest_lock:
                    self._backlog.extend(leftovers)
                break
            if rc != DISPATCHED:
                # absorbed without a task slot (guard-shed / no live
                # routes): neither a "dispatch" for the metrics/lag
                # histogram nor a charge against the cycle budget —
                # a burst of shed chunks must not exhaust the budget
                # healthy chunks were going to use
                continue
            qos.note_dispatched(chunk)
            spent += chunk.size or 1
            if budget and spent >= budget:
                # per-cycle dispatch budget exhausted: the remainder
                # waits its fair turn next cycle
                leftovers = qos.drain_pending()
                if leftovers:
                    with self._ingest_lock:
                        self._backlog.extend(leftovers)
                break

    def _reap_retired_outputs(self) -> None:
        """Free hot-reload-removed outputs once their in-flight
        flushes settle (rides the housekeeping timer). A retired
        output no task routes to will never be flushed again — the
        reload cleared it from every route — so its worker-pool
        threads and plugin state can go NOW: a long-running daemon
        doing periodic reloads must not accumulate one idle pool per
        removal until engine.stop()."""
        if not self._retired_outputs:
            return
        with self._ingest_lock:
            busy = {id(o) for task in self._task_map.values()
                    for o in task.routes}
            ready = [o for o in self._retired_outputs
                     if id(o) not in busy]
            if not ready:
                return
            gone = {id(o) for o in ready}
            self._retired_outputs = [o for o in self._retired_outputs
                                     if id(o) not in gone]
        for out in ready:
            if out.worker_pool is not None:
                out.worker_pool.stop()
                out.worker_pool = None
            try:
                out.plugin.exit()
            except Exception:
                log.exception("retired output %s exit failed",
                              out.display_name)

    def _dispatch_chunk(self, chunk) -> int:
        """Resolve routes and spawn one task for a ready chunk (the
        per-chunk tail of the reference's flb_engine_dispatch).
        Returns PARKED (falsy) only when the task map is full — the
        caller then parks the chunk (and the rest of the fair queue)
        for the next cycle; DISPATCHED when a task slot was consumed;
        ABSORBED when the chunk was handled without a slot (guard-shed
        spill or no live routes), which must count against neither the
        qos dispatch metrics nor the cycle budget."""
        if chunk.route_names is not None:
            # resolve by output NAME whenever names exist (stamped at
            # conditional-split ingest, on shed, and on disk recovery):
            # bit positions index a SPECIFIC outputs list, and a hot
            # reload can swap that list while this chunk sits in
            # flush_all's in-flight window — after the pool/backlog
            # mask-clearing pass can no longer reach it. Names survive
            # any reorder; the mask is only a fast path for chunks
            # that never got names
            routes = [
                o for o in self.outputs
                if o.display_name in chunk.route_names
                and chunk.event_type in o.plugin.event_types
            ]
        elif chunk.routes_mask:
            # conditionally-split chunk: the ingest-time bitmask IS
            # the route set (tag matching already folded in)
            routes = [
                o for i, o in enumerate(self.outputs)
                if (chunk.routes_mask >> i) & 1
                and chunk.event_type in o.plugin.event_types
            ]
        else:
            routes = [
                o for o in self.outputs
                if o.route.matches(chunk.tag)
                and chunk.event_type in o.plugin.event_types
            ]
        if not routes:
            if self.storage is not None:
                self.storage.delete(chunk)
                self.qos.release_storage(chunk)
            return ABSORBED
        # load shedding (fbtpu-guard): above the occupancy watermark,
        # chunks spill to filesystem storage in priority order — the
        # lowest class first — and chunks whose EVERY route is behind
        # an open breaker spill regardless of class
        if self.guard.maybe_shed(chunk, routes):
            return ABSORBED
        # bounded task id map (flb_task_map_get_task_id,
        # src/flb_task.c:542): when every slot is in use the chunk
        # stays parked and is re-dispatched next flush cycle — the
        # reference's "task_id exhausted" stance. The map is mutated
        # here (engine loop or flush_now's caller thread) and in
        # _task_unref (loop callbacks, sync-fallback flush on any
        # thread) — both hold the ingest lock.
        task = None
        with self._ingest_lock:
            if len(self._task_map) >= self.service.task_map_size:
                now = time.time()
                if now - self._task_map_warned > 5.0:
                    self._task_map_warned = now
                    log.warning(
                        "task map full (%d tasks in flight) — chunk "
                        "dispatch paused until slots free",
                        len(self._task_map))
            else:
                task = Task(chunk, routes)
                # fully referenced BEFORE the first spawn: a route
                # completing synchronously must not see users hit 0
                # (and free the slot / delete the chunk) while its
                # siblings are still being spawned
                task.users = len(routes)
                self._task_map[task.id] = task
        if task is None:
            return PARKED
        for out in routes:
            self._spawn_flush(task, out)
        return DISPATCHED

    def _task_unref(self, task: Task) -> bool:
        """flb_task_users_dec: the id-map slot frees when the last
        route finishes (flb_task_destroy). Returns True when this was
        the last reference (callers gate storage cleanup on it instead
        of re-reading task.users unlocked)."""
        with self._ingest_lock:
            task.users -= 1
            done = task.users == 0
            if done:
                self._task_map.pop(task.id, None)
        return done

    def _enqueue_event(self, priority: int, fn) -> None:
        """Queue a ready callback through the 8-priority bucket queue
        (flb_engine_handle_event demux order): drains run lowest
        priority number first on the engine loop."""
        with self._event_queue_lock:
            self._event_queue.add(priority, fn)
        self.loop.call_soon_threadsafe(self._drain_event_queue)

    def _drain_event_queue(self) -> None:
        while True:
            with self._event_queue_lock:
                if not self._event_queue:
                    return
                fn = self._event_queue.pop()
            try:
                fn()
            except Exception:
                log.exception("engine event callback failed")

    def _spawn_flush(self, task: Task, out: OutputInstance,
                     priority: Optional[int] = None) -> None:
        from .bucket_queue import PRIORITY_FLUSH

        if self.loop is not None and self.running:
            # per-output circuit breaker (fbtpu-guard): while open,
            # dispatch short-circuits to an immediately scheduled retry
            # — no coroutine, no connection, no flush-semaphore slot.
            # Deliberately NOT counted against retry_limit: the breaker
            # is suppressing attempts, not failing them, and must never
            # turn a sick-but-recoverable route into dropped chunks.
            delay = self.guard.short_circuit_delay(out)
            if delay is not None:
                self.guard.m_short_circuit.inc(1, (out.display_name,))
                self._schedule_retry(task, out, delay)
                return
        coro = self._flush_one(task, out)
        if self.loop is None or not self.running:
            # synchronous fallback (engine not started: unit tests)
            asyncio.run(coro)
            return
        def _create():
            fut = asyncio.ensure_future(coro)
            self.guard.track(task, out, fut)
            self._pending_flushes.add(fut)
            fut.add_done_callback(self._pending_flushes.discard)
        try:
            self._enqueue_event(
                PRIORITY_FLUSH if priority is None else priority, _create)
        except RuntimeError:
            # loop shut down mid-stop: account the chunk as dropped
            coro.close()
            self.m_out_errors.inc(1, (out.display_name,))
            self.m_out_dropped.inc(task.chunk.records, (out.display_name,))
            self._task_unref(task)

    async def _flush_one(self, task: Task, out: OutputInstance) -> None:
        """One (task × output) flush ATTEMPT
        (flb_output_flush_create/output_pre_cb_flush). A RETRY result
        does not sleep here: it registers a scheduler timer that
        re-spawns a fresh attempt (flb_engine_dispatch_retry,
        src/flb_engine_dispatch.c:36-99), so a chunk backing off for
        minutes holds no coroutine and no concurrency slot. Concurrency
        honors the reference's dispatch flags
        (src/flb_engine_dispatch.c:193-207 + flb_output_thread.c):
        FLB_OUTPUT_SYNCHRONOUS / no_multiplex serialize to one in-flight
        flush per output; ``workers N`` bounds concurrency to N."""
        try:
            await self._flush_body(task, out)
        except asyncio.CancelledError:
            if self.guard.consume_timeout(task, out):
                # guard soft-kill (flush deadline expired), NOT a
                # shutdown cancel: the slot's attempt is reclaimed and
                # the chunk re-enters the retry scheduler as a normal
                # RETRY (it counts against retry_limit, so a
                # permanently hung route still drains to the DLQ)
                delay = self._handle_flush_result(task, out,
                                                  FlushResult.RETRY)
                if delay is not None:
                    self._schedule_retry(task, out, delay)
                return
            # engine stopping with this route undelivered (parked on the
            # semaphore, mid-flush, or in backoff): a memory chunk would
            # be silently lost — quarantine when storage is on.
            # Filesystem chunks are on disk and recover as backlog.
            if self.storage is not None and \
                    not self.storage.is_tracked(task.chunk):
                try:
                    if _fp.ACTIVE:
                        _fp.fire("engine.shutdown_quarantine")
                    self.storage.quarantine(task.chunk)
                except Exception:
                    log.exception("shutdown quarantine failed")
            raise

    def _flush_payload(self, task: Task, out: OutputInstance) -> bytes:
        """The bytes this output delivers for the chunk — output-side
        processors (flb_processor_run at flush-create,
        include/fluent-bit/flb_output.h:794) run ONCE per (chunk,
        output); retries reuse the cached result so non-idempotent
        processors never repeat side effects."""
        chunk = task.chunk
        cached = task.processed.get(out.name)
        if cached is not None:
            return cached
        data = chunk.get_bytes()
        if out.processors and chunk.event_type == EVENT_TYPE_LOGS:
            events = self._run_log_processors(
                out.processors, decode_events(data), chunk.tag
            )
            data = b"".join(
                ev.raw if ev.raw is not None else reencode_event(ev)
                for ev in events
            )
        elif out.processors and chunk.event_type == EVENT_TYPE_METRICS:
            data = self._run_metrics_processors(out.processors, data,
                                                chunk.tag)
        elif out.processors and chunk.event_type == EVENT_TYPE_TRACES:
            data, _ = self._run_traces_processors(out.processors, data,
                                                  chunk.tag, chunk.records)
        if out.processors:
            task.processed[out.name] = data
        return data

    async def _flush_body(self, task: Task, out: OutputInstance) -> None:
        chunk = task.chunk
        data = self._flush_payload(task, out)

        async def attempt() -> Optional[float]:
            sem = out.flush_semaphore
            if sem is not None:
                await sem.acquire()
            # fbtpu-qos tenant.flush_concurrency: cap the tenant's
            # concurrent flushes ACROSS outputs, acquired after the
            # output slot (uniform order, no cross-wait cycle). Held
            # by reference: a reload that swaps the tenant's semaphore
            # never strands this release.
            tsem = self.qos.flush_slot(chunk)
            if tsem is not None:
                await tsem.acquire()
            # the deadline clock starts HERE, once the attempt actually
            # executes: time parked in the flush-semaphore queue behind
            # a saturated-but-healthy output must not count (the slot
            # HOLDER's deadline runs, so a hung holder still frees the
            # queue), and the guard-tracked record is exposed to the
            # flush via the cooperative-cancel contextvar
            rec = self.guard.flight(task, out)
            if rec is not None:
                from . import guard as _guard

                rec.started = time.time()
                rec.begun = True
                _guard.CANCEL_EVENT.set(rec.cancel_event)
            # expose the chunk to the plugin the same way the cancel
            # event is exposed: outputs that relay pipeline metadata
            # (out_forward's tenant/priority wire stamps) read it here
            FLUSH_CHUNK.set(chunk)
            try:
                # test formatter hook (src/flb_engine_dispatch.c:101-137)
                if out.test_formatter is not None:
                    try:
                        out.test_formatter(data, chunk.tag)
                        result = FlushResult.OK
                    except Exception:
                        log.exception("test formatter failed")
                        result = FlushResult.ERROR
                else:
                    try:
                        if _fp.ACTIVE:
                            # hung/failing-destination faults: an ASYNC
                            # site, so delay()/hang() suspends only this
                            # flush (cancellable by the guard deadline),
                            # never the engine loop. The instance-scoped
                            # name lets one output hang while siblings
                            # flow (FAULTS.md).
                            await _fp.fire_async("output.flush")
                            await _fp.fire_async(
                                "output.flush." + out.display_name)
                        if out.worker_pool is not None:
                            # run the plugin's flush on a worker thread
                            # loop (flb_output_thread.c round-robin);
                            # result/retry handling stays here
                            if rec is not None:
                                rec.worker = True
                            result = await out.worker_pool.submit(
                                self._worker_flush(out.plugin, data,
                                                   chunk.tag, rec,
                                                   chunk))
                        else:
                            result = await out.plugin.flush(
                                data, chunk.tag, self)
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        log.exception("output %s flush raised",
                                      out.display_name)
                        result = FlushResult.ERROR
            finally:
                if tsem is not None:
                    tsem.release()
                if sem is not None:
                    sem.release()
            return self._handle_flush_result(task, out, result)

        delay = await attempt()
        if delay is None:
            return
        if self.loop is not None and self.running:
            self._schedule_retry(task, out, delay)
            return
        # synchronous fallback (engine not started: unit tests/lib mode
        # without a loop): retry inside this coroutine like the
        # pre-scheduler design — asyncio.run() can't be nested
        while delay is not None:
            await asyncio.sleep(delay)
            delay = await attempt()

    async def _worker_flush(self, plugin, data: bytes, tag: str, rec,
                            chunk=None):
        """Worker-pool submission wrapper: re-exposes the guard's
        cooperative cancel flag AND the flush-chunk contextvar on the
        worker loop (contextvars do not cross
        ``run_coroutine_threadsafe``) and marks completion, so the
        watchdog can tell a soft-kill that landed late from a worker
        thread wedged in sync code (the leaked-thread counter)."""
        if rec is not None:
            from . import guard as _guard

            _guard.CANCEL_EVENT.set(rec.cancel_event)
        FLUSH_CHUNK.set(chunk)
        try:
            return await plugin.flush(data, tag, self)
        finally:
            if rec is not None:
                rec.worker_done = True

    def _schedule_retry(self, task: Task, out: OutputInstance,
                        delay: float) -> None:
        """Timer-driven retry re-dispatch: the backoff lives in the
        event loop's timer wheel (flb_sched_request_create →
        flb_engine_dispatch_retry), not in a parked coroutine. At stop,
        pending retry records are quarantined like any undelivered
        route."""
        key = (task.chunk.id, out.name)
        if _fp.ACTIVE:
            try:
                # retry infrastructure failure: the chunk's retry cannot
                # be scheduled — account it like a shutdown-dropped
                # retry (quarantine + drop metrics), never silently leak
                # the task-map slot
                _fp.fire("engine.retry_schedule")
            except _fp.FailpointError:
                log.warning("retry scheduling failed (injected); "
                            "dropping retry for %s", out.display_name)
                self._drop_retry(task, out)
                return

        def _fire():
            from .bucket_queue import PRIORITY_TOP

            self._pending_retries.pop(key, None)
            # fire even while stopping: a retry coming due inside the
            # grace window gets its attempt (the reference services
            # retries until grace expires); if it RETRYs again,
            # _register drops it, and the stop-sequence cleanup handles
            # whatever is still pending when grace runs out.
            # Scheduler events outrank flush spawns
            # (FLB_ENGINE_PRIORITY_CB_SCHED = top)
            self._spawn_flush(task, out, priority=PRIORITY_TOP)

        def _register():
            if self._stopping:
                self._drop_retry(task, out)
                return
            handle = self.loop.call_later(delay, _fire)
            self._pending_retries[key] = (task, out, handle)

        try:
            self.loop.call_soon_threadsafe(_register)
        except RuntimeError:
            self._drop_retry(task, out)

    def _drop_retry(self, task: Task, out: OutputInstance) -> None:
        """Account a retry dropped at shutdown: quarantine the chunk
        unless its bytes are already on disk, and count the drop like
        every other drop path."""
        self.m_out_errors.inc(1, (out.display_name,))
        self.m_out_dropped.inc(task.chunk.records, (out.display_name,))
        if self.storage is not None and \
                not self.storage.is_tracked(task.chunk):
            try:
                if _fp.ACTIVE:
                    _fp.fire("engine.shutdown_quarantine")
                self.storage.quarantine(task.chunk)
            except Exception:
                log.exception("retry quarantine failed")
        self._task_unref(task)

    def _handle_flush_result(self, task: Task, out: OutputInstance,
                             result: FlushResult) -> Optional[float]:
        """handle_output_event equivalent (src/flb_engine.c:302-540).
        Returns the backoff delay when the flush must be retried, else None."""
        name = out.display_name
        chunk = task.chunk
        if result == FlushResult.OK:
            self.guard.on_result(out, ok=True)  # breaker: close/hold
            self.m_out_proc_records.inc(chunk.records, (name,))
            self.m_out_proc_bytes.inc(chunk.size, (name,))
            self.m_latency.observe(time.time() - chunk.created, (name,))
            if self._task_unref(task) and self.storage is not None:
                self.storage.delete(chunk)  # every route delivered
                self.qos.release_storage(chunk)
            return None
        if result == FlushResult.RETRY:
            attempts = task.retries.get(out.name, 0) + 1
            task.retries[out.name] = attempts
            limit = out.retry_limit if out.retry_limit is not None else self.service.retry_limit
            if limit == -1 or attempts <= limit:
                self.guard.on_result(out, ok=False)
                self.m_out_retries.inc(1, (name,))
                return backoff_full_jitter(
                    self.service.scheduler_base, self.service.scheduler_cap, attempts
                )
            self.m_out_retries_failed.inc(1, (name,))
        # ERROR or retries exhausted → drop (+ DLQ quarantine when storage on)
        self.guard.on_result(out, ok=False)  # breaker: count the failure
        self.m_out_errors.inc(1, (name,))
        self.m_out_dropped.inc(chunk.records, (name,))
        if self.storage is not None:
            try:
                self.storage.quarantine(chunk)
            except Exception:
                log.exception("DLQ quarantine failed")
        if self._task_unref(task) and self.storage is not None:
            self.storage.delete(chunk)  # dlq copy (if any) is separate
            self.qos.release_storage(chunk)
        return None

    # ------------------------------------------------------------------
    # notifications (src/flb_notification.c)
    # ------------------------------------------------------------------

    def notify(self, event: dict) -> None:
        for cb in self._notification_subs:
            try:
                cb(event)
            except Exception:
                log.exception("notification callback failed")

    def subscribe(self, cb) -> None:
        self._notification_subs.append(cb)

    # convenience for tests / lib mode
    def flush_now(self) -> None:
        """Force a flush cycle and wait for pending flushes to settle."""
        self.flush_all()
        if self.loop is None or not self.running:
            return
        # call_soon_threadsafe callbacks run FIFO: once this sentinel fires,
        # every _create queued by flush_all has populated _pending_flushes.
        settled = threading.Event()
        try:
            self.loop.call_soon_threadsafe(settled.set)
        except RuntimeError:
            return
        settled.wait(timeout=2)
        deadline = time.time() + 5
        # retried chunks park as scheduler timers, not coroutines —
        # settle on both so callers still observe final delivery
        while (self._pending_flushes or self._pending_retries) \
                and time.time() < deadline:
            time.sleep(0.01)
