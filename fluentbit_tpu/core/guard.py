"""fbtpu-guard — flush deadlines, per-output circuit breakers, engine
watchdog, graceful load shedding.

The pipeline is only as available as its slowest output: a hung flush
coroutine holds its task-map slot forever (``core/engine.py`` task map,
2048 slots), so one stuck destination eventually stalls dispatch for
*every* route — the head-of-line failure the failpoint plane (FAULTS.md)
can inject but nothing previously survived. This module is the survival
layer; the engine owns one :class:`Guard` and calls into it from the
flush paths only — the per-record ingest hot path has ZERO guard code,
and every periodic check rides the existing flush/housekeeping timer.

Three mechanisms (FAULTS.md "fbtpu-guard" section has the contract):

- **flush deadlines** — every tracked flush attempt (inline coroutine
  or worker-pool submission) carries a deadline: per-output
  ``flush_timeout``, else service ``guard.flush_timeout``, else
  ``2 × grace``. The watchdog soft-kills expired attempts: the asyncio
  future is cancelled (worker submissions additionally get a
  cooperative cancel flag — :func:`cancel_requested` — and are hard
  abandoned if the worker thread is wedged in sync code, counted in
  ``fluentbit_guard_abandoned_flushes_total``), the task slot's attempt
  is reclaimed, and the chunk re-enters the retry scheduler as a normal
  RETRY.

- **per-output circuit breakers** — a closed → open → half-open state
  machine fed by flush outcomes (OK closes/holds, ERROR/RETRY/timeout
  counts against consecutive-failure and windowed error-rate
  thresholds). While open, dispatch short-circuits to an immediately
  scheduled retry: no coroutine, no connection, no flush-semaphore
  slot is burned. After the cooldown, half-open admits exactly ONE
  probe flush; its outcome closes the breaker or re-opens it with a
  fresh cooldown (hysteresis). The same :class:`CircuitBreaker` backs
  ``UpstreamHA`` node health in ``core/upstream.py`` (`mark_down` =
  record_failure, `mark_up` = reset, `pick()` filters on
  ``available()``).

- **watchdog + graded load shedding** — the housekeeping pass (rides
  ``flush_all``'s timer) stamps a heartbeat, exports
  ``fluentbit_guard_*`` gauges (task-map occupancy + high-water,
  retry backlog, in-flight flushes, heartbeat age), scans deadlines,
  and spills chunks off the dispatch path by **priority class**
  (fbtpu-qos, QOS.md): each of the 8 classes has its own occupancy
  watermark — the lowest class sheds right at ``guard.shed_watermark``
  and each higher class only at proportionally higher occupancy, so
  the highest class effectively never sheds and its flush latency is
  unaffected by pressure. Chunks whose every route sits behind an
  open breaker additionally shed at the base watermark regardless of
  class (the original fbtpu-guard rule). Spilled memory chunks are
  written through to filesystem storage first when configured.
  Readmission is graded too: breaker-shed chunks return when any
  route can take a probe, pressure-shed chunks when occupancy falls
  back below ``qos.shed_hysteresis ×`` their class watermark — and
  the readmit batch re-enters the backlog **highest priority first**
  (it previously re-entered in FIFO shed order), so recovery
  bandwidth goes to the classes that matter. Delivery stays
  at-least-once; shedding resets the chunk's retry count (it
  re-enters as a fresh dispatch).

``/api/v1/health`` surfaces the verdict (``ok|degraded|stalled``; see
``core/http_server.py``).
"""

from __future__ import annotations

import asyncio
import contextvars
import logging
import threading
import time
from collections import deque

from .lockorder import make_lock
from typing import Callable, Dict, List, Optional, Tuple

log = logging.getLogger("flb.guard")

# ---------------------------------------------------------------------------
# cooperative cancellation + bounded I/O awaits
# ---------------------------------------------------------------------------

#: Set for the duration of a guarded flush (task-local): plugins doing
#: long synchronous work on a worker loop can poll
#: :func:`cancel_requested` to honor a soft-kill the event loop cannot
#: deliver as a CancelledError.
CANCEL_EVENT: "contextvars.ContextVar[Optional[threading.Event]]" = \
    contextvars.ContextVar("fbtpu_guard_cancel", default=None)


def cancel_requested() -> bool:
    """True when the guard has soft-killed the current flush attempt
    (cooperative worker-thread cancellation; see Guard.housekeeping)."""
    ev = CANCEL_EVENT.get()
    return ev is not None and ev.is_set()


#: Default bound for one socket await inside a flush path (the
#: ``await-no-deadline`` lint's escape hatch — ANALYSIS.md).
DEFAULT_IO_TIMEOUT = 30.0


async def io_deadline(awaitable, timeout: float = DEFAULT_IO_TIMEOUT):
    """Bound one I/O await with a deadline, raising the *builtin*
    ``TimeoutError`` — an ``OSError`` subclass, so the caller's existing
    socket error handling (reconnect, RETRY, pool drop) engages without
    failpoint/guard-aware except clauses. (``asyncio.TimeoutError`` is
    NOT an ``OSError`` before Python 3.11, hence the translation.)"""
    try:
        return await asyncio.wait_for(awaitable, timeout)
    except asyncio.TimeoutError:
        raise TimeoutError(
            f"I/O deadline ({timeout:g}s) expired") from None


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

#: Gauge encoding, severity-ordered for dashboards.
STATE_CLOSED, STATE_HALF_OPEN, STATE_OPEN = 0, 1, 2
_STATE_NAMES = {STATE_CLOSED: "closed", STATE_HALF_OPEN: "half-open",
                STATE_OPEN: "open"}


class CircuitBreaker:
    """Closed → open → half-open state machine over flush outcomes.

    - CLOSED: everything flows; ``failures`` consecutive failures OR a
      full ``window`` of outcomes at ≥ ``error_rate`` opens it.
    - OPEN: :meth:`allow` refuses (callers short-circuit) until
      ``cooldown`` elapses, then transitions to HALF_OPEN and admits
      the caller as the probe.
    - HALF_OPEN: exactly one probe in flight; ``probes`` successes
      close, any failure re-opens with a fresh cooldown (hysteresis).

    ``available()`` is the non-consuming view used by HA ``pick()`` and
    the shedding pass: True whenever a request COULD be admitted.
    Thread-safe; transition callbacks fire outside the lock.
    """

    def __init__(self, name: str, failures: int = 5,
                 error_rate: float = 0.5, window: int = 20,
                 cooldown: float = 5.0, probes: int = 1,
                 on_transition: Optional[Callable] = None,
                 clock=time.monotonic):
        self.name = name
        self.failures = max(1, int(failures))
        self.error_rate = float(error_rate)
        self.window = max(1, int(window))
        self.cooldown = float(cooldown)
        self.probes = max(1, int(probes))
        self.on_transition = on_transition
        self.clock = clock
        self._lock = make_lock("CircuitBreaker._lock")
        self._state = STATE_CLOSED
        self._consecutive = 0
        self._outcomes: deque = deque(maxlen=self.window)
        self._opened_at = 0.0
        self._probe_inflight = False
        self._probe_started = 0.0
        self._probe_ok = 0

    # -- internal (call with self._lock held) --------------------------

    def _transition(self, new: int) -> Optional[Tuple[str, str]]:
        old = self._state
        if old == new:
            return None
        self._state = new
        if new == STATE_OPEN:
            self._opened_at = self.clock()
            self._probe_inflight = False
            self._probe_ok = 0
        elif new == STATE_CLOSED:
            self._consecutive = 0
            self._outcomes.clear()
            self._probe_inflight = False
            self._probe_ok = 0
        return (_STATE_NAMES[old], _STATE_NAMES[new])

    def _notify(self, change: Optional[Tuple[str, str]]) -> None:
        if change is None or self.on_transition is None:
            return
        try:
            self.on_transition(self.name, change[0], change[1])
        except Exception:
            log.exception("breaker transition hook failed")

    def _probe_ttl(self) -> float:
        # a probe whose flush vanished (loop torn down mid-spawn) must
        # not wedge recovery forever; the flush-deadline guard resolves
        # probes long before this in a running engine
        return max(60.0, 4.0 * self.cooldown)

    def _trip_check(self) -> Optional[Tuple[str, str]]:
        if self._consecutive >= self.failures:
            return self._transition(STATE_OPEN)
        if len(self._outcomes) == self.window:
            rate = self._outcomes.count(False) / self.window
            if rate >= self.error_rate:
                return self._transition(STATE_OPEN)
        return None

    # -- state ----------------------------------------------------------

    def state_name(self) -> str:
        with self._lock:
            return _STATE_NAMES[self._state]

    def state_code(self) -> int:
        with self._lock:
            return self._state

    def is_closed(self) -> bool:
        with self._lock:
            return self._state == STATE_CLOSED

    # -- admission -------------------------------------------------------

    def allow(self) -> bool:
        """Admit one request. In HALF_OPEN this CONSUMES the probe slot:
        the first caller after cooldown proceeds, everyone else keeps
        short-circuiting until the probe's outcome is recorded."""
        change = None
        with self._lock:
            now = self.clock()
            if self._state == STATE_OPEN:
                if now - self._opened_at < self.cooldown:
                    return False
                change = self._transition(STATE_HALF_OPEN)
            if self._state == STATE_HALF_OPEN:
                if self._probe_inflight and \
                        now - self._probe_started > self._probe_ttl():
                    self._probe_inflight = False  # lost probe: re-admit
                if self._probe_inflight:
                    admitted = False
                else:
                    self._probe_inflight = True
                    self._probe_started = now
                    admitted = True
            else:
                admitted = True
        self._notify(change)
        return admitted

    def available(self) -> bool:
        """Non-consuming admission view (HA ``pick``, shedding): True
        when a request could be admitted right now."""
        with self._lock:
            if self._state == STATE_CLOSED:
                return True
            if self._state == STATE_HALF_OPEN:
                return True
            return self.clock() - self._opened_at >= self.cooldown

    def retry_delay(self) -> float:
        """Seconds until the next admission opportunity (the breaker
        short-circuit's scheduled-retry delay), floored so retry timers
        never busy-spin."""
        with self._lock:
            if self._state == STATE_OPEN:
                remaining = self.cooldown - (self.clock() - self._opened_at)
            else:
                remaining = min(1.0, self.cooldown / 4.0)
            return max(0.05, remaining)

    # -- outcomes -------------------------------------------------------

    def record_ok(self) -> None:
        change = None
        with self._lock:
            if self._state == STATE_HALF_OPEN:
                self._probe_inflight = False
                self._probe_ok += 1
                if self._probe_ok >= self.probes:
                    change = self._transition(STATE_CLOSED)
            elif self._state == STATE_CLOSED:
                self._consecutive = 0
                self._outcomes.append(True)
            else:
                # late success of a flush that was in flight when the
                # breaker opened: evidence, not recovery — the probe
                # path owns the close decision
                self._outcomes.append(True)
        self._notify(change)

    def record_failure(self) -> None:
        change = None
        with self._lock:
            if self._state == STATE_HALF_OPEN:
                change = self._transition(STATE_OPEN)
            elif self._state == STATE_CLOSED:
                self._consecutive += 1
                self._outcomes.append(False)
                change = self._trip_check()
            else:
                # already OPEN: a failure re-arms the cooldown — a
                # cooled-down-but-still-sick destination (an HA node
                # re-picked via available(), a straggler flush) must
                # not be re-admitted on a lapsed timer
                self._opened_at = self.clock()
                self._outcomes.append(False)
        self._notify(change)

    def reset(self) -> None:
        """Force CLOSED (HA ``mark_up``: the caller has independent
        evidence the destination is healthy)."""
        with self._lock:
            change = self._transition(STATE_CLOSED)
        self._notify(change)


# ---------------------------------------------------------------------------
# the engine-side guard
# ---------------------------------------------------------------------------


class FlightRecord:
    """One in-flight flush attempt under deadline watch."""

    __slots__ = ("key", "task", "out_name", "started", "begun",
                 "deadline", "fut", "cancel_event", "worker",
                 "worker_done", "timed_out", "consumed", "abandoned_at")

    def __init__(self, key, task, out_name: str, deadline: float, fut):
        self.key = key
        self.task = task
        self.out_name = out_name
        self.started = time.time()
        # the deadline clock only runs once the attempt actually
        # executes (the engine re-stamps `started` and sets `begun`
        # after the flush-semaphore acquire): an attempt parked in the
        # queue behind a saturated-but-healthy output is not hung —
        # the slot HOLDER's deadline is what frees the queue
        self.begun = False
        self.deadline = deadline
        self.fut = fut
        self.cancel_event = threading.Event()
        self.worker = False
        self.worker_done = False
        self.timed_out = False
        self.consumed = False
        self.abandoned_at = 0.0


class Guard:
    """Per-engine guard plane. Created with the engine; inert (cheap
    early-outs, no threads, no timers of its own) until flushes flow.

    Concurrency: ``_flights``/``_abandoned``/``_shed``/``_breakers``
    are touched from the engine loop (housekeeping, flush results),
    ``flush_now`` callers, and — for results — sync-fallback flushes on
    arbitrary threads; all access holds ``_lock``. Task-map reads hold
    the engine's ``_ingest_lock`` (same discipline as the engine
    itself); the pending-retry reclaim pass runs only on the engine
    loop, where those records live.
    """

    def __init__(self, engine):
        self.engine = engine
        self._lock = make_lock("Guard._lock")
        self._flights: Dict[tuple, FlightRecord] = {}
        self._abandoned: List[FlightRecord] = []
        self._shed: List = []  # chunks parked off the dispatch path
        self._breakers: Dict[str, CircuitBreaker] = {}
        # count of breakers not in CLOSED (maintained on transitions):
        # the dispatch loop's shed check reads it lock-free, so the
        # all-healthy steady state pays zero lock round-trips per chunk
        self._unhealthy = 0
        self.heartbeat = time.time()

        m = engine.metrics
        self.m_timeouts = m.counter(
            "fluentbit", "guard", "flush_timeouts_total",
            "Flush attempts soft-killed past their deadline", ("name",))
        self.m_abandoned = m.counter(
            "fluentbit", "guard", "abandoned_flushes_total",
            "Worker-thread flushes hard-abandoned (leaked) after a "
            "soft-kill could not land", ("name",))
        self.m_short_circuit = m.counter(
            "fluentbit", "guard", "short_circuits_total",
            "Dispatches short-circuited to a scheduled retry by an "
            "open breaker", ("name",))
        self.m_shed = m.counter(
            "fluentbit", "guard", "shed_chunks_total",
            "Chunks spilled off the dispatch path for open-breaker "
            "routes", ("name",))
        self.m_breaker_state = m.gauge(
            "fluentbit", "guard", "breaker_state",
            "Per-output breaker state (0 closed, 1 half-open, 2 open)",
            ("name",))
        self.m_transitions = m.counter(
            "fluentbit", "guard", "breaker_transitions_total",
            "Breaker state transitions", ("name", "state"))
        self.m_occupancy = m.gauge(
            "fluentbit", "guard", "task_map_occupancy",
            "Task-map slots in use")
        self.m_highwater = m.gauge(
            "fluentbit", "guard", "task_map_highwater",
            "Task-map occupancy high-water mark")
        self.m_retry_backlog = m.gauge(
            "fluentbit", "guard", "retry_backlog",
            "Pending retry timers")
        self.m_inflight = m.gauge(
            "fluentbit", "guard", "inflight_flushes",
            "Flush attempts currently tracked by the guard")
        self.m_heartbeat_age = m.gauge(
            "fluentbit", "guard", "heartbeat_age_seconds",
            "Age of the last housekeeping pass at the time it ran")
        self.m_worker_start_fail = m.counter(
            "fluentbit", "guard", "worker_start_failures_total",
            "Output worker pools that failed to start (failed over to "
            "inline flush)", ("name",))

    # -- config (read live: service keys may be set up to start()) -----

    @property
    def enabled(self) -> bool:
        return bool(self.engine.service.guard_enable)

    def deadline_for(self, out) -> float:
        ft = getattr(out, "flush_timeout", None)
        if ft:
            return ft
        svc = self.engine.service
        if svc.guard_flush_timeout:
            return svc.guard_flush_timeout
        return 2.0 * svc.grace

    # -- breakers -------------------------------------------------------

    def breaker(self, name: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(name)
            if br is None:
                svc = self.engine.service
                br = CircuitBreaker(
                    name,
                    failures=svc.guard_breaker_failures,
                    error_rate=svc.guard_breaker_error_rate,
                    window=svc.guard_breaker_window,
                    cooldown=svc.guard_breaker_cooldown,
                    probes=svc.guard_breaker_probes,
                    on_transition=self._on_transition,
                )
                self._breakers[name] = br
        return br

    def _on_transition(self, name: str, old: str, new: str) -> None:
        code = {v: k for k, v in _STATE_NAMES.items()}[new]
        self.m_breaker_state.set(code, (name,))
        self.m_transitions.inc(1, (name, new))
        with self._lock:
            if old == "closed" and new != "closed":
                self._unhealthy += 1
            elif old != "closed" and new == "closed":
                self._unhealthy -= 1
        level = logging.WARNING if new != "closed" else logging.INFO
        log.log(level, "guard: breaker %s: %s -> %s", name, old, new)

    def short_circuit_delay(self, out) -> Optional[float]:
        """None → dispatch may proceed (closed, or this caller IS the
        half-open probe). A delay → the breaker is open: schedule a
        retry for then instead of flushing."""
        if not self.enabled:
            return None
        br = self.breaker(out.display_name)
        if br.allow():
            return None
        return br.retry_delay()

    def on_result(self, out, ok: bool) -> None:
        """Feed one flush outcome (OK vs ERROR/RETRY/timeout) to the
        output's breaker."""
        if not self.enabled:
            return
        br = self.breaker(out.display_name)
        if ok:
            br.record_ok()
        else:
            br.record_failure()

    # -- flight tracking ------------------------------------------------

    def track(self, task, out, fut) -> Optional[FlightRecord]:
        if not self.enabled:
            return None
        key = (task.id, out.name)
        rec = FlightRecord(key, task, out.display_name,
                           self.deadline_for(out), fut)
        with self._lock:
            self._flights[key] = rec
        fut.add_done_callback(lambda _f, k=key: self._untrack(k))
        return rec

    def _untrack(self, key) -> None:
        with self._lock:
            self._flights.pop(key, None)

    def flight(self, task, out) -> Optional[FlightRecord]:
        with self._lock:
            return self._flights.get((task.id, out.name))

    def consume_timeout(self, task, out) -> bool:
        """True exactly once for a flush the watchdog soft-killed: the
        engine's CancelledError handler uses this to tell a guard
        deadline from a shutdown cancel."""
        with self._lock:
            rec = self._flights.get((task.id, out.name))
            if rec is not None and rec.timed_out and not rec.consumed:
                rec.consumed = True
                return True
        return False

    # -- watchdog (rides flush_all's timer) -----------------------------

    def housekeeping(self) -> None:
        if not self.enabled:
            return
        now = time.time()
        engine = self.engine
        try:
            on_loop = asyncio.get_running_loop() is engine.loop
        except RuntimeError:
            on_loop = False
        if on_loop:
            # the heartbeat certifies the ENGINE LOOP is alive — a
            # flush_now() caller thread running this pass must not
            # stamp it, or a wedged loop would never read "stalled"
            self.m_heartbeat_age.set(now - self.heartbeat)
            self.heartbeat = now
        with engine._ingest_lock:
            occupancy = len(engine._task_map)
        self.m_occupancy.set(occupancy)
        self.m_highwater.set_max(occupancy)
        self.m_retry_backlog.set(len(engine._pending_retries))

        # deadline scan: soft-kill expired attempts
        expired: List[FlightRecord] = []
        with self._lock:
            self.m_inflight.set(len(self._flights))
            for rec in self._flights.values():
                if rec.begun and not rec.timed_out \
                        and now - rec.started >= rec.deadline:
                    rec.timed_out = True
                    expired.append(rec)
        for rec in expired:
            self.m_timeouts.inc(1, (rec.out_name,))
            log.warning(
                "guard: flush to %s exceeded its %.1fs deadline — "
                "soft-killing; chunk re-enters the retry scheduler",
                rec.out_name, rec.deadline)
            rec.cancel_event.set()  # cooperative worker-side flag
            loop = engine.loop
            if loop is not None:
                try:
                    loop.call_soon_threadsafe(rec.fut.cancel)
                except RuntimeError:
                    pass  # loop torn down: stop-path accounting owns it
            if rec.worker:
                rec.abandoned_at = now
                with self._lock:
                    self._abandoned.append(rec)

        # leaked-thread scan: worker flushes whose soft-kill never
        # landed (thread wedged in sync code) are counted once
        leaked: List[FlightRecord] = []
        grace = engine.service.guard_leak_grace
        with self._lock:
            keep = []
            for rec in self._abandoned:
                if rec.worker_done:
                    continue  # cancel landed late: recovered
                if now - rec.abandoned_at >= grace:
                    leaked.append(rec)
                else:
                    keep.append(rec)
            self._abandoned = keep
        for rec in leaked:
            self.m_abandoned.inc(1, (rec.out_name,))
            log.error(
                "guard: worker flush to %s ignored its soft-kill for "
                "%.1fs — hard-abandoning (thread leaked until it "
                "returns)", rec.out_name, grace)

        self._shed_pass(now, occupancy, on_loop)

    # -- load shedding --------------------------------------------------

    def _watermark_slots(self) -> int:
        svc = self.engine.service
        return int(svc.guard_shed_watermark * svc.task_map_size)

    def _class_watermark_slots(self, priority) -> int:
        """Shed-by-priority (fbtpu-qos): each of the 8 classes gets its
        own occupancy watermark, graded linearly from the base
        watermark (lowest class: sheds first) up toward a full task
        map (class 0: effectively never sheds), so pressure spills the
        classes that hurt least and the highest class's flush latency
        stays flat."""
        from .bucket_queue import QOS_CLASS_COUNT

        svc = self.engine.service
        if priority is None:
            priority = svc.qos_default_priority
        priority = min(max(int(priority), 0), QOS_CLASS_COUNT - 1)
        base = svc.guard_shed_watermark
        frac = base + (1.0 - base) * (
            QOS_CLASS_COUNT - 1 - priority) / QOS_CLASS_COUNT
        # floor of one slot: a degenerate task map must never compute a
        # zero watermark and shed everything at occupancy zero
        return max(1, int(frac * svc.task_map_size))

    def _route_breakers(self, names) -> List[Optional[CircuitBreaker]]:
        with self._lock:
            return [self._breakers.get(n) for n in names]

    def maybe_shed(self, chunk, routes) -> bool:
        """Dispatch-path shedding, graded by priority class. Above the
        chunk's CLASS watermark it spills regardless of route health
        (shed-by-priority); above the BASE watermark a chunk whose
        EVERY route sits behind an open (and not yet probe-ready)
        breaker spills regardless of class (the original rule)."""
        if not self.enabled or not routes:
            return False
        engine = self.engine
        if not self._unhealthy and not engine.qos.graded():
            # lock-free health probe: with every breaker closed and a
            # single priority class nothing can shed — the all-healthy
            # dispatch loop pays zero lock round-trips here
            return False
        # relaxed read: len() of a dict is atomic in CPython and the
        # value is stale the instant any lock is released anyway — a
        # per-chunk engine-lock round-trip here would put dispatch in
        # contention with every ingest thread just to move the shed
        # threshold by at most one in-flight chunk
        # fbtpu-lint: allow(guarded-by) atomic len() threshold probe
        occupancy = len(engine._task_map)
        if occupancy < self._watermark_slots():
            return False  # below the base watermark nothing ever sheds
        names = [o.display_name for o in routes]
        # shed-by-priority only engages when tenants actually span
        # several classes — a single-class pipeline keeps the original
        # park-on-backlog backpressure (shedding a class below itself
        # would just add spill churn)
        if engine.qos.graded() and \
                occupancy >= self._class_watermark_slots(chunk.priority):
            self._shed_chunk(chunk, names, reason="pressure")
            return True
        if not self._unhealthy:
            # lock-free health probe: breaker-shedding needs every
            # route's breaker open, impossible while all are closed
            return False
        brs = self._route_breakers(names)
        if any(br is None or br.available() for br in brs):
            return False
        self._shed_chunk(chunk, names, reason="breaker")
        return True

    def _shed_chunk(self, chunk, route_names,
                    reason: str = "breaker") -> None:
        # persisted route restriction: on readmission the chunk must
        # only go to the routes it was shed FROM (a sibling route that
        # already delivered must not see duplicates). Dispatch resolves
        # route NAMES first, so the restricted set wins; the stale
        # bitmask (which still indexes the delivered siblings) is
        # cleared for hygiene
        chunk.route_names = tuple(route_names)
        chunk.routes_mask = 0
        storage = self.engine.storage
        if storage is not None and not storage.is_tracked(chunk):
            try:  # durability: a memory chunk spills to disk — the
                # tenant storage quota applies here too (an over-quota
                # tenant's shed chunks park in memory only)
                from .qos import SHED

                data = chunk.get_bytes()
                if self.engine.qos.admit_storage(
                        None, chunk, len(data)) != SHED:
                    storage.write_through(chunk, data)
                    storage.finalize(chunk)
            except Exception:
                log.exception("guard: shed write-through failed; chunk "
                              "parked in memory only")
        with self._lock:
            self._shed.append((chunk, reason))
        for name in route_names:
            self.m_shed.inc(1, (name,))
        if reason == "pressure":
            self.engine.qos.m_priority_shed.inc(
                1, (chunk.qos_tenant or "default",))
        log.warning(
            "guard: shed chunk %s class=%s (routes %s) — %s",
            chunk.tag, chunk.priority, ",".join(route_names),
            "task-map pressure (shed-by-priority)"
            if reason == "pressure" else "open breaker + task-map "
            "pressure")

    def _shed_pass(self, now: float, occupancy: int,
                   on_loop: bool) -> None:
        """Readmit recovered shed chunks — HIGHEST priority first;
        above the watermark, reclaim task slots held by retry timers
        for open-breaker routes."""
        engine = self.engine
        svc = engine.service
        with self._lock:
            shed = list(self._shed)
        if shed:
            readmit = []
            for entry in shed:
                chunk, reason = entry
                if reason == "pressure":
                    # hysteresis: only readmit once occupancy fell
                    # comfortably below the chunk's class watermark —
                    # and count the chunks already being readmitted
                    # this pass, so one pass cannot blow back through
                    # the watermark it is honoring
                    thr = self._class_watermark_slots(chunk.priority) \
                        * svc.qos_shed_hysteresis
                    if occupancy + len(readmit) < thr:
                        readmit.append(entry)
                    continue
                brs = self._route_breakers(chunk.route_names or ())
                if any(br is None or br.available() for br in brs):
                    readmit.append(entry)
            if readmit:
                # probe-ready chunks re-enter HIGHEST class first (the
                # previous FIFO readmission handed recovery bandwidth
                # to whatever happened to shed first, regardless of
                # route priority); ties keep shed order (stable sort)
                readmit.sort(
                    key=lambda e: e[0].priority
                    if e[0].priority is not None
                    else svc.qos_default_priority)
                with self._lock:
                    gone = {id(e) for e in readmit}
                    self._shed = [e for e in self._shed
                                  if id(e) not in gone]
                with engine._ingest_lock:
                    engine._backlog.extend(c for c, _r in readmit)
                log.info("guard: readmitted %d shed chunk(s) in "
                         "priority order", len(readmit))
        # retry-slot reclaim: engine-loop only (pending-retry records
        # are loop-owned)
        if not on_loop or occupancy < self._watermark_slots():
            return
        for key, (task, out, handle) in list(
                engine._pending_retries.items()):
            if task.users != 1:
                continue  # sibling routes still own the slot
            brs = self._route_breakers([out.display_name])
            if brs[0] is None or brs[0].available():
                continue
            handle.cancel()
            engine._pending_retries.pop(key, None)
            self._shed_chunk(task.chunk, [out.display_name])
            engine._task_unref(task)

    def readmit_all(self) -> None:
        """Stop path: everything shed re-enters the backlog so the
        shutdown drain (and its quarantine accounting) sees it —
        highest priority first, same contract as the live readmission
        pass."""
        with self._lock:
            shed, self._shed = self._shed, []
        if shed:
            dflt = self.engine.service.qos_default_priority
            shed.sort(key=lambda e: e[0].priority
                      if e[0].priority is not None else dflt)
            with self.engine._ingest_lock:
                self.engine._backlog.extend(c for c, _r in shed)

    def shed_count(self) -> int:
        with self._lock:
            return len(self._shed)

    # -- health ---------------------------------------------------------

    def health(self) -> dict:
        """The ``/api/v1/health`` readiness verdict: ``ok`` (everything
        closed, loop beating), ``degraded`` (any breaker not closed,
        chunks shed, or task-map pressure — healthy routes still flow),
        ``stalled`` (the housekeeping heartbeat is older than
        ``guard.stall_after``: the engine loop is wedged or starved).
        Heartbeat age is computed at call time, so a wedged flush timer
        is visible even while the admin server still answers."""
        engine = self.engine
        if not self.enabled:
            return {"status": "ok", "guard": "disabled"}
        now = time.time()
        with self._lock:
            breakers = {name: _STATE_NAMES[br.state_code()]
                        for name, br in self._breakers.items()}
            shed = len(self._shed)
            inflight = len(self._flights)
        with engine._ingest_lock:
            occupancy = len(engine._task_map)
        svc = engine.service
        running = engine.running
        hb_age = max(0.0, now - self.heartbeat) if running else 0.0
        # fbtpu-armor device fault domain (ops/fault.py): attach
        # lifecycle + per-lane breaker/failover state. A lane breaker
        # not closed means the device path is degraded to its bit-exact
        # CPU fallback — records flow, throughput doesn't, and the
        # health verdict must say so
        try:
            from ..ops import fault as _fault

            device_block = _fault.health_block()
        except Exception:
            log.exception("device health block failed")
            device_block = {"error": "unavailable"}
        lane_breakers = [
            ln.get("breaker") for ln in device_block.get("lanes",
                                                         {}).values()]
        verdict = "ok"
        if (any(s != "closed" for s in breakers.values()) or shed
                or occupancy >= self._watermark_slots()
                or any(b not in (None, "closed") for b in lane_breakers)):
            verdict = "degraded"
        if running and hb_age > max(svc.guard_stall_after,
                                    5.0 * svc.flush):
            verdict = "stalled"
        # fbtpu-relay forward fan-in state: ack/dedup/backpressure
        # counters per forward plugin instance (FAULTS.md "fbtpu-relay")
        forward_block = {}
        for inst in list(engine.inputs) + list(engine.outputs):
            plugin = getattr(inst, "plugin", None)
            hb = getattr(plugin, "health_block", None)
            if getattr(plugin, "name", "") != "forward" or hb is None:
                continue
            try:
                forward_block[inst.display_name] = hb()
            except Exception:
                log.exception("forward health block failed")
                forward_block[inst.display_name] = {
                    "error": "unavailable"}
        return {
            "status": verdict,
            "heartbeat_age": round(hb_age, 3),
            "task_map": {"occupancy": occupancy,
                         "size": svc.task_map_size},
            "inflight_flushes": inflight,
            "shed_chunks": shed,
            "breakers": breakers,
            # fbtpu-armor: attach retry state + device-lane failover
            "device": device_block,
            # fbtpu-relay: forward hop ack/dedup/backpressure state
            "forward": forward_block,
            # fbtpu-qos per-tenant state (QOS.md): generation + each
            # tenant's contract, admission counters and queue depth
            "qos": engine.qos.snapshot(),
        }
