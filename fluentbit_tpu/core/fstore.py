"""fstore — file-backed object store on top of the chunk file format.

Reference: src/flb_fstore.c (chunkio-backed KV staging used by out_s3
multipart uploads and blob delivery). Streams are directories; files
are named objects with byte content + a small JSON metadata sidecar.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional


class FStoreFile:
    __slots__ = ("name", "path", "meta_path")

    def __init__(self, name: str, path: str):
        self.name = name
        self.path = path
        self.meta_path = path + ".meta"

    def append(self, data: bytes) -> None:
        with open(self.path, "ab") as f:
            f.write(data)

    def content(self) -> bytes:
        with open(self.path, "rb") as f:
            return f.read()

    def set_meta(self, meta: dict, durable: bool = False) -> None:
        """Write the JSON metadata sidecar.

        ``durable=True`` takes the tmp + fsync + rename path: the meta
        file is then either the old version or the new one, never a
        torn half-write. The forward dedup ledger requires this — a
        SIGKILL mid-write would otherwise void the whole absorbed-set
        and turn every in-flight redelivery into a double-absorb.
        """
        if not durable:
            with open(self.meta_path, "w", encoding="utf-8") as f:
                json.dump(meta, f)
            return
        tmp = self.meta_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.meta_path)

    def meta(self) -> dict:
        try:
            with open(self.meta_path, "r", encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    @property
    def size(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def delete(self) -> None:
        for p in (self.path, self.meta_path):
            try:
                os.unlink(p)
            except OSError:
                pass


class FStoreStream:
    def __init__(self, root: str, name: str):
        self.name = name
        self.dir = os.path.join(root, name)
        os.makedirs(self.dir, exist_ok=True)

    def create(self, name: str) -> FStoreFile:
        f = FStoreFile(name, os.path.join(self.dir, name))
        open(f.path, "ab").close()  # meta-only files must still exist
        return f

    def get(self, name: str) -> Optional[FStoreFile]:
        path = os.path.join(self.dir, name)
        return FStoreFile(name, path) if os.path.exists(path) else None

    def files(self) -> List[FStoreFile]:
        out = []
        for name in sorted(os.listdir(self.dir)):
            if name.endswith(".meta"):
                continue
            out.append(FStoreFile(name, os.path.join(self.dir, name)))
        return out


class FStore:
    """flb_fstore_create: a root of named streams."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def stream(self, name: str) -> FStoreStream:
        return FStoreStream(self.root, name)

    def streams(self) -> List[str]:
        return sorted(
            d for d in os.listdir(self.root)
            if os.path.isdir(os.path.join(self.root, d))
        )
