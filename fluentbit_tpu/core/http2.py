"""HTTP/2 (h2c, prior knowledge) — frames + HPACK + client/server.

Reference: src/flb_http_client_http2.c (nghttp2-based client used by
~30 outputs) and the HTTP/2 side of plugins/in_http. This build
implements the protocol directly (no nghttp2 to vendor): RFC 7540
framing (SETTINGS/HEADERS/CONTINUATION/DATA/WINDOW_UPDATE/PING/
RST_STREAM/GOAWAY) and RFC 7541 HPACK — full static table, dynamic
table with eviction, integer/string primitives, and Huffman DECODING
(clients like curl Huffman-encode header values; our encoder emits
plain literals, which is always spec-valid).

Scope: cleartext prior-knowledge h2c as the reference uses it for
backend links — one request per stream, client streams odd-numbered,
flow-control windows kept open with generous WINDOW_UPDATEs. TLS ALPN
h2 works with the same engine when the caller supplies a TLS transport.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Dict, List, Optional, Tuple

PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

# frame types (RFC 7540 §6)
DATA, HEADERS, PRIORITY, RST_STREAM, SETTINGS, PUSH_PROMISE, PING, \
    GOAWAY, WINDOW_UPDATE, CONTINUATION = range(10)

FLAG_END_STREAM = 0x1
FLAG_END_HEADERS = 0x4
FLAG_PADDED = 0x8
FLAG_PRIORITY = 0x20
FLAG_ACK = 0x1

# ---------------------------------------------------------------- HPACK

STATIC_TABLE: List[Tuple[str, str]] = [
    (":authority", ""), (":method", "GET"), (":method", "POST"),
    (":path", "/"), (":path", "/index.html"), (":scheme", "http"),
    (":scheme", "https"), (":status", "200"), (":status", "204"),
    (":status", "206"), (":status", "304"), (":status", "400"),
    (":status", "404"), (":status", "500"), ("accept-charset", ""),
    ("accept-encoding", "gzip, deflate"), ("accept-language", ""),
    ("accept-ranges", ""), ("accept", ""), ("access-control-allow-origin",
    ""), ("age", ""), ("allow", ""), ("authorization", ""),
    ("cache-control", ""), ("content-disposition", ""),
    ("content-encoding", ""), ("content-language", ""),
    ("content-length", ""), ("content-location", ""),
    ("content-range", ""), ("content-type", ""), ("cookie", ""),
    ("date", ""), ("etag", ""), ("expect", ""), ("expires", ""),
    ("from", ""), ("host", ""), ("if-match", ""),
    ("if-modified-since", ""), ("if-none-match", ""), ("if-range", ""),
    ("if-unmodified-since", ""), ("last-modified", ""), ("link", ""),
    ("location", ""), ("max-forwards", ""), ("proxy-authenticate", ""),
    ("proxy-authorization", ""), ("range", ""), ("referer", ""),
    ("refresh", ""), ("retry-after", ""), ("server", ""),
    ("set-cookie", ""), ("strict-transport-security", ""),
    ("transfer-encoding", ""), ("user-agent", ""), ("vary", ""),
    ("via", ""), ("www-authenticate", ""),
]

# RFC 7541 appendix B: (code, bit length) for symbols 0..256 (256 = EOS)
_HUFF = [
    (0x1ff8, 13), (0x7fffd8, 23), (0xfffffe2, 28), (0xfffffe3, 28),
    (0xfffffe4, 28), (0xfffffe5, 28), (0xfffffe6, 28), (0xfffffe7, 28),
    (0xfffffe8, 28), (0xffffea, 24), (0x3ffffffc, 30), (0xfffffe9, 28),
    (0xfffffea, 28), (0x3ffffffd, 30), (0xfffffeb, 28), (0xfffffec, 28),
    (0xfffffed, 28), (0xfffffee, 28), (0xfffffef, 28), (0xffffff0, 28),
    (0xffffff1, 28), (0xffffff2, 28), (0x3ffffffe, 30), (0xffffff3, 28),
    (0xffffff4, 28), (0xffffff5, 28), (0xffffff6, 28), (0xffffff7, 28),
    (0xffffff8, 28), (0xffffff9, 28), (0xffffffa, 28), (0xffffffb, 28),
    (0x14, 6), (0x3f8, 10), (0x3f9, 10), (0xffa, 12), (0x1ff9, 13),
    (0x15, 6), (0xf8, 8), (0x7fa, 11), (0x3fa, 10), (0x3fb, 10),
    (0xf9, 8), (0x7fb, 11), (0xfa, 8), (0x16, 6), (0x17, 6), (0x18, 6),
    (0x0, 5), (0x1, 5), (0x2, 5), (0x19, 6), (0x1a, 6), (0x1b, 6),
    (0x1c, 6), (0x1d, 6), (0x1e, 6), (0x1f, 6), (0x5c, 7), (0xfb, 8),
    (0x7ffc, 15), (0x20, 6), (0xffb, 12), (0x3fc, 10), (0x1ffa, 13),
    (0x21, 6), (0x5d, 7), (0x5e, 7), (0x5f, 7), (0x60, 7), (0x61, 7),
    (0x62, 7), (0x63, 7), (0x64, 7), (0x65, 7), (0x66, 7), (0x67, 7),
    (0x68, 7), (0x69, 7), (0x6a, 7), (0x6b, 7), (0x6c, 7), (0x6d, 7),
    (0x6e, 7), (0x6f, 7), (0x70, 7), (0x71, 7), (0x72, 7), (0xfc, 8),
    (0x73, 7), (0xfd, 8), (0x1ffb, 13), (0x7fff0, 19), (0x1ffc, 13),
    (0x3ffc, 14), (0x22, 6), (0x7ffd, 15), (0x3, 5), (0x23, 6),
    (0x4, 5), (0x24, 6), (0x5, 5), (0x25, 6), (0x26, 6), (0x27, 6),
    (0x6, 5), (0x74, 7), (0x75, 7), (0x28, 6), (0x29, 6), (0x2a, 6),
    (0x7, 5), (0x2b, 6), (0x76, 7), (0x2c, 6), (0x8, 5), (0x9, 5),
    (0x2d, 6), (0x77, 7), (0x78, 7), (0x79, 7), (0x7a, 7), (0x7b, 7),
    (0x7ffe, 15), (0x7fc, 11), (0x3ffd, 14), (0x1ffd, 13),
    (0xffffffc, 28), (0xfffe6, 20), (0x3fffd2, 22), (0xfffe7, 20),
    (0xfffe8, 20), (0x3fffd3, 22), (0x3fffd4, 22), (0x3fffd5, 22),
    (0x7fffd9, 23), (0x3fffd6, 22), (0x7fffda, 23), (0x7fffdb, 23),
    (0x7fffdc, 23), (0x7fffdd, 23), (0x7fffde, 23), (0xffffeb, 24),
    (0x7fffdf, 23), (0xffffec, 24), (0xffffed, 24), (0x3fffd7, 22),
    (0x7fffe0, 23), (0xffffee, 24), (0x7fffe1, 23), (0x7fffe2, 23),
    (0x7fffe3, 23), (0x7fffe4, 23), (0x1fffdc, 21), (0x3fffd8, 22),
    (0x7fffe5, 23), (0x3fffd9, 22), (0x7fffe6, 23), (0x7fffe7, 23),
    (0xffffef, 24), (0x3fffda, 22), (0x1fffdd, 21), (0xfffe9, 20),
    (0x3fffdb, 22), (0x3fffdc, 22), (0x7fffe8, 23), (0x7fffe9, 23),
    (0x1fffde, 21), (0x7fffea, 23), (0x3fffdd, 22), (0x3fffde, 22),
    (0xfffff0, 24), (0x1fffdf, 21), (0x3fffdf, 22), (0x7fffeb, 23),
    (0x7fffec, 23), (0x1fffe0, 21), (0x1fffe1, 21), (0x3fffe0, 22),
    (0x1fffe2, 21), (0x7fffed, 23), (0x3fffe1, 22), (0x7fffee, 23),
    (0x7fffef, 23), (0xfffea, 20), (0x3fffe2, 22), (0x3fffe3, 22),
    (0x3fffe4, 22), (0x7ffff0, 23), (0x3fffe5, 22), (0x3fffe6, 22),
    (0x7ffff1, 23), (0x3ffffe0, 26), (0x3ffffe1, 26), (0xfffeb, 20),
    (0x7fff1, 19), (0x3fffe7, 22), (0x7ffff2, 23), (0x3fffe8, 22),
    (0x1ffffec, 25), (0x3ffffe2, 26), (0x3ffffe3, 26), (0x3ffffe4, 26),
    (0x7ffffde, 27), (0x7ffffdf, 27), (0x3ffffe5, 26), (0xfffff1, 24),
    (0x1ffffed, 25), (0x7fff2, 19), (0x1fffe3, 21), (0x3ffffe6, 26),
    (0x7ffffe0, 27), (0x7ffffe1, 27), (0x3ffffe7, 26), (0x7ffffe2, 27),
    (0xfffff2, 24), (0x1fffe4, 21), (0x1fffe5, 21), (0x3ffffe8, 26),
    (0x3ffffe9, 26), (0xffffffd, 28), (0x7ffffe3, 27), (0x7ffffe4, 27),
    (0x7ffffe5, 27), (0xfffec, 20), (0xfffff3, 24), (0xfffed, 20),
    (0x1fffe6, 21), (0x3fffe9, 22), (0x1fffe7, 21), (0x1fffe8, 21),
    (0x7ffff3, 23), (0x3fffea, 22), (0x3fffeb, 22), (0x1ffffee, 25),
    (0x1ffffef, 25), (0xfffff4, 24), (0xfffff5, 24), (0x3ffffea, 26),
    (0x7ffff4, 23), (0x3ffffeb, 26), (0x7ffffe6, 27), (0x3ffffec, 26),
    (0x3ffffed, 26), (0x7ffffe7, 27), (0x7ffffe8, 27), (0x7ffffe9, 27),
    (0x7ffffea, 27), (0x7ffffeb, 27), (0xffffffe, 28), (0x7ffffec, 27),
    (0x7ffffed, 27), (0x7ffffee, 27), (0x7ffffef, 27), (0x7fffff0, 27),
    (0x3ffffee, 26), (0x3fffffff, 30),
]

_huff_decode_map: Dict[Tuple[int, int], int] = {
    (code, bits): sym for sym, (code, bits) in enumerate(_HUFF)
}


def huffman_decode(data: bytes) -> bytes:
    out = bytearray()
    code = 0
    bits = 0
    for byte in data:
        for i in range(7, -1, -1):
            code = (code << 1) | ((byte >> i) & 1)
            bits += 1
            sym = _huff_decode_map.get((code, bits))
            if sym is not None:
                if sym == 256:
                    raise ValueError("EOS in huffman stream")
                out.append(sym)
                code = 0
                bits = 0
    # trailing bits must be a prefix of EOS (all ones), <= 7 bits
    if bits > 7 or code != (1 << bits) - 1:
        raise ValueError("bad huffman padding")
    return bytes(out)


def _int_encode(value: int, prefix_bits: int, first_byte: int = 0) -> bytes:
    limit = (1 << prefix_bits) - 1
    if value < limit:
        return bytes([first_byte | value])
    out = bytearray([first_byte | limit])
    value -= limit
    while value >= 128:
        out.append((value % 128) + 128)
        value //= 128
    out.append(value)
    return bytes(out)


def _int_decode(data: bytes, pos: int, prefix_bits: int) -> Tuple[int, int]:
    limit = (1 << prefix_bits) - 1
    value = data[pos] & limit
    pos += 1
    if value < limit:
        return value, pos
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated hpack integer")
        b = data[pos]
        pos += 1
        value += (b & 0x7F) << shift
        shift += 7
        if not (b & 0x80):
            return value, pos
        if shift > 63:
            raise ValueError("hpack integer overflow")


def _str_decode(data: bytes, pos: int) -> Tuple[str, int]:
    huff = bool(data[pos] & 0x80)
    length, pos = _int_decode(data, pos, 7)
    raw = data[pos:pos + length]
    if len(raw) != length:
        raise ValueError("truncated hpack string")
    pos += length
    if huff:
        raw = huffman_decode(raw)
    return raw.decode("utf-8", "replace"), pos


def _str_encode(s: str) -> bytes:
    raw = s.encode("utf-8")
    return _int_encode(len(raw), 7) + raw


class HpackCodec:
    """One direction's HPACK context (decoder or encoder dynamic table)."""

    def __init__(self, max_size: int = 4096):
        self.max_size = max_size
        self.dynamic: List[Tuple[str, str]] = []
        self.size = 0

    def _entry_size(self, name: str, value: str) -> int:
        return len(name.encode()) + len(value.encode()) + 32

    def _add(self, name: str, value: str) -> None:
        self.dynamic.insert(0, (name, value))
        self.size += self._entry_size(name, value)
        while self.size > self.max_size and self.dynamic:
            n, v = self.dynamic.pop()
            self.size -= self._entry_size(n, v)

    def _lookup(self, index: int) -> Tuple[str, str]:
        if index <= 0:
            raise ValueError("hpack index 0")
        if index <= len(STATIC_TABLE):
            return STATIC_TABLE[index - 1]
        d = index - len(STATIC_TABLE) - 1
        if d >= len(self.dynamic):
            raise ValueError("hpack index out of range")
        return self.dynamic[d]

    def decode(self, data: bytes) -> List[Tuple[str, str]]:
        out: List[Tuple[str, str]] = []
        pos = 0
        while pos < len(data):
            b = data[pos]
            if b & 0x80:  # indexed
                index, pos = _int_decode(data, pos, 7)
                out.append(self._lookup(index))
            elif b & 0x40:  # literal with incremental indexing
                index, pos = _int_decode(data, pos, 6)
                name = self._lookup(index)[0] if index else None
                if name is None:
                    name, pos = _str_decode(data, pos)
                value, pos = _str_decode(data, pos)
                self._add(name, value)
                out.append((name, value))
            elif b & 0x20:  # dynamic table size update
                size, pos = _int_decode(data, pos, 5)
                self.max_size = size
                while self.size > self.max_size and self.dynamic:
                    n, v = self.dynamic.pop()
                    self.size -= self._entry_size(n, v)
            else:  # literal without indexing / never indexed (4-bit)
                index, pos = _int_decode(data, pos, 4)
                name = self._lookup(index)[0] if index else None
                if name is None:
                    name, pos = _str_decode(data, pos)
                value, pos = _str_decode(data, pos)
                out.append((name, value))
        return out

    def encode(self, headers: List[Tuple[str, str]]) -> bytes:
        out = bytearray()
        for name, value in headers:
            name = name.lower()
            idx = None
            name_idx = None
            for i, (n, v) in enumerate(STATIC_TABLE, 1):
                if n == name:
                    if v == value:
                        idx = i
                        break
                    if name_idx is None:
                        name_idx = i
            if idx is not None:
                out += _int_encode(idx, 7, 0x80)
            elif name_idx is not None:
                # literal without indexing, indexed name
                out += _int_encode(name_idx, 4, 0x00)
                out += _str_encode(value)
            else:
                out += _int_encode(0, 4, 0x00)
                out += _str_encode(name)
                out += _str_encode(value)
        return bytes(out)


# ---------------------------------------------------------------- frames

def frame(ftype: int, flags: int, stream_id: int, payload: bytes) -> bytes:
    return struct.pack("!I", len(payload))[1:] + bytes(
        [ftype, flags]) + struct.pack("!I", stream_id & 0x7FFFFFFF) + payload


async def read_frame(reader) -> Tuple[int, int, int, bytes]:
    head = await reader.readexactly(9)
    length = (head[0] << 16) | (head[1] << 8) | head[2]
    ftype, flags = head[3], head[4]
    stream_id = struct.unpack("!I", head[5:9])[0] & 0x7FFFFFFF
    payload = await reader.readexactly(length) if length else b""
    return ftype, flags, stream_id, payload


def settings_frame(ack: bool = False, initial_window: int = 1 << 24,
                   max_frame: int = 16384) -> bytes:
    if ack:
        return frame(SETTINGS, FLAG_ACK, 0, b"")
    payload = struct.pack("!HI", 0x4, initial_window)  # INITIAL_WINDOW_SIZE
    payload += struct.pack("!HI", 0x5, max_frame)      # MAX_FRAME_SIZE
    return frame(SETTINGS, 0, 0, payload)


def strip_padding(flags: int, payload: bytes) -> bytes:
    if flags & FLAG_PADDED:
        if not payload:
            raise ValueError("padded frame with empty payload")
        pad = payload[0]
        payload = payload[1:]
        if pad:
            if pad > len(payload):
                raise ValueError("padding exceeds payload")
            payload = payload[:-pad]
    return payload


def parse_settings(payload: bytes) -> Dict[int, int]:
    out = {}
    for off in range(0, len(payload) - 5, 6):
        ident, value = struct.unpack("!HI", payload[off:off + 6])
        out[ident] = value
    return out


# ---------------------------------------------------------------- client

class Http2Client:
    """Prior-knowledge h2c client over an asyncio transport; one
    request at a time (streams 1, 3, 5, ... on one connection).
    Respects the peer's send windows (RFC 7540 §5.2): DATA waits for
    WINDOW_UPDATE when the 65535-byte default (or whatever the server's
    SETTINGS granted) is exhausted — compliant servers GOAWAY on
    overflow."""

    def __init__(self, reader, writer, scheme: str = "http"):
        self.reader = reader
        self.writer = writer
        self.scheme = scheme
        self.encoder = HpackCodec()
        self.decoder = HpackCodec()
        self.next_stream = 1
        self._started = False
        self.conn_window = 65535
        self.peer_initial_window = 65535
        self.peer_max_frame = 16384

    async def _start(self) -> None:
        self.writer.write(PREFACE + settings_frame())
        await self.writer.drain()
        self._started = True

    async def request(self, method: str, authority: str, path: str,
                      headers: List[Tuple[str, str]],
                      body: bytes = b"",
                      timeout: float = 30.0) -> Tuple[int, bytes]:
        """Send one request, wait for the full response:
        (status, body)."""
        if not self._started:
            await self._start()
        sid = self.next_stream
        self.next_stream += 2
        hdrs = [(":method", method), (":scheme", self.scheme),
                (":authority", authority), (":path", path)] + \
            [(k.lower(), v) for k, v in headers]
        block = self.encoder.encode(hdrs)
        flags = FLAG_END_HEADERS | (0 if body else FLAG_END_STREAM)
        self.writer.write(frame(HEADERS, flags, sid, block))
        await self.writer.drain()

        state = {
            "status": 0, "resp": bytearray(), "hdr": bytearray(),
            "got_headers": False, "done": False,
            "stream_window": self.peer_initial_window,
            "off": 0,
        }

        async def _pump():
            # interleave window-bounded sends with frame processing
            # until the response completes
            while not state["done"]:
                while (state["off"] < len(body)
                       and min(state["stream_window"],
                               self.conn_window) > 0):
                    n = min(self.peer_max_frame,
                            len(body) - state["off"],
                            state["stream_window"], self.conn_window)
                    chunk = body[state["off"]:state["off"] + n]
                    state["off"] += n
                    state["stream_window"] -= n
                    self.conn_window -= n
                    end = state["off"] >= len(body)
                    self.writer.write(frame(
                        DATA, FLAG_END_STREAM if end else 0, sid, chunk))
                    await self.writer.drain()
                await self._process_one(sid, state)

        await asyncio.wait_for(_pump(), timeout)
        if not state["got_headers"]:
            raise ConnectionError("no response headers")
        return state["status"], bytes(state["resp"])

    async def _process_one(self, sid: int, state: dict) -> None:
        ftype, fl, rsid, payload = await read_frame(self.reader)
        if ftype == SETTINGS:
            if not (fl & FLAG_ACK):
                settings = parse_settings(payload)
                if 0x4 in settings:  # INITIAL_WINDOW_SIZE
                    delta = settings[0x4] - self.peer_initial_window
                    self.peer_initial_window = settings[0x4]
                    state["stream_window"] += delta
                if 0x5 in settings:  # MAX_FRAME_SIZE
                    self.peer_max_frame = max(16384, settings[0x5])
                self.writer.write(settings_frame(ack=True))
                await self.writer.drain()
        elif ftype == PING and not (fl & FLAG_ACK):
            self.writer.write(frame(PING, FLAG_ACK, 0, payload))
            await self.writer.drain()
        elif ftype == WINDOW_UPDATE:
            incr = struct.unpack("!I", payload[:4])[0] & 0x7FFFFFFF
            if rsid == 0:
                self.conn_window += incr
            elif rsid == sid:
                state["stream_window"] += incr
        elif ftype in (HEADERS, CONTINUATION) and rsid == sid:
            state["hdr"].extend(strip_padding(fl, payload)
                                if ftype == HEADERS else payload)
            if fl & FLAG_END_HEADERS:
                for k, v in self.decoder.decode(bytes(state["hdr"])):
                    if k == ":status":
                        try:
                            state["status"] = int(v)
                        except ValueError:
                            raise ConnectionError(
                                f"bad :status {v!r}")
                state["got_headers"] = True
            if fl & FLAG_END_STREAM:
                state["done"] = True
        elif ftype == DATA and rsid == sid:
            state["resp"].extend(strip_padding(fl, payload))
            # keep receive windows open
            upd = struct.pack("!I", 1 << 20)
            self.writer.write(frame(WINDOW_UPDATE, 0, 0, upd)
                              + frame(WINDOW_UPDATE, 0, sid, upd))
            await self.writer.drain()
            if fl & FLAG_END_STREAM:
                state["done"] = True
        elif ftype == RST_STREAM and rsid == sid:
            raise ConnectionError("stream reset")
        elif ftype == GOAWAY:
            raise ConnectionError("goaway")

    def close(self) -> None:
        try:
            self.writer.write(frame(GOAWAY, 0, 0, struct.pack("!II", 0, 0)))
            self.writer.close()
        except (OSError, RuntimeError):
            pass  # peer gone / loop closed: nothing left to say goodbye to


# ---------------------------------------------------------------- server

async def serve_h2c(reader, writer, handler, preface_consumed=False):
    """Serve one h2c connection: for each request stream, call
    ``await handler(method, path, headers_dict, body) -> (status,
    body_bytes, content_type)``. The caller detects the connection
    preface (``PREFACE``) and hands the socket over."""
    if not preface_consumed:
        got = await reader.readexactly(len(PREFACE))
        if got != PREFACE:
            raise ConnectionError("bad h2c preface")
    decoder = HpackCodec()
    encoder = HpackCodec()
    writer.write(settings_frame())
    await writer.drain()
    streams: Dict[int, dict] = {}

    async def finish(sid: int) -> None:
        st = streams.pop(sid, None)
        if st is None:
            return
        headers = dict(st["headers"])
        method = headers.get(":method", "GET")
        path = headers.get(":path", "/")
        try:
            status, body, ctype = await handler(
                method, path, headers, bytes(st["body"]))
        except Exception:
            status, body, ctype = 500, b"", "text/plain"
        hdrs = [(":status", str(status)),
                ("content-type", ctype),
                ("content-length", str(len(body)))]
        block = encoder.encode(hdrs)
        writer.write(frame(HEADERS, FLAG_END_HEADERS
                           | (0 if body else FLAG_END_STREAM), sid, block))
        if body:
            writer.write(frame(DATA, FLAG_END_STREAM, sid, body))
        await writer.drain()

    while True:
        try:
            ftype, flags, sid, payload = await read_frame(reader)
        except (asyncio.IncompleteReadError, ConnectionError):
            return
        if ftype == SETTINGS:
            if not (flags & FLAG_ACK):
                writer.write(settings_frame(ack=True))
                await writer.drain()
        elif ftype == PING:
            if not (flags & FLAG_ACK):
                writer.write(frame(PING, FLAG_ACK, 0, payload))
                await writer.drain()
        elif ftype == HEADERS:
            data = strip_padding(flags, payload)
            if flags & FLAG_PRIORITY:
                data = data[5:]
            st = streams.setdefault(sid, {"headers": [], "body":
                                          bytearray(), "hdr": bytearray()})
            st["hdr"].extend(data)
            if flags & FLAG_END_HEADERS:
                st["headers"] = decoder.decode(bytes(st["hdr"]))
                st["hdr"].clear()
            if flags & FLAG_END_STREAM:
                await finish(sid)
        elif ftype == CONTINUATION:
            st = streams.get(sid)
            if st is not None:
                st["hdr"].extend(payload)
                if flags & FLAG_END_HEADERS:
                    st["headers"] = decoder.decode(bytes(st["hdr"]))
                    st["hdr"].clear()
                if flags & FLAG_END_STREAM:
                    await finish(sid)
        elif ftype == DATA:
            st = streams.get(sid)
            if st is not None:
                st["body"].extend(strip_padding(flags, payload))
                upd = struct.pack("!I", 1 << 20)
                writer.write(frame(WINDOW_UPDATE, 0, 0, upd)
                             + frame(WINDOW_UPDATE, 0, sid, upd))
                await writer.drain()
                if flags & FLAG_END_STREAM:
                    await finish(sid)
        elif ftype == RST_STREAM:
            streams.pop(sid, None)
        elif ftype == GOAWAY:
            return
        # PRIORITY / PUSH_PROMISE / unknown types: ignored (spec allows)


def grpc_wrap(message: bytes, compressed: bool = False) -> bytes:
    """gRPC length-prefixed message framing (the transport layer of
    OTLP/gRPC; the protobuf message encoding itself is gated — no
    protoc schemas are vendored, see plugins/gated.py rationale)."""
    return bytes([1 if compressed else 0]) + struct.pack(
        "!I", len(message)) + message


def grpc_unwrap(data: bytes) -> List[bytes]:
    out = []
    pos = 0
    while pos + 5 <= len(data):
        length = struct.unpack("!I", data[pos + 1:pos + 5])[0]
        out.append(data[pos + 5:pos + 5 + length])
        pos += 5 + length
    return out
