"""Filesystem chunk storage — persistence, backlog, DLQ.

Reference: lib/chunkio (file chunks with CRC32 integrity,
src/cio_file.c:49-104) wrapped by src/flb_storage.c (memory/filesystem
mapping per input :530-556, quarantine
flb_storage_quarantine_chunk), and plugins/in_storage_backlog (re-ingest
of filesystem chunks found at startup after sb_segregate_chunks,
src/flb_engine.c:1129).

Design (TPU build, not a port of chunkio): a chunk file is
``header + concatenated msgpack events``; appends are write-through
(append + flush so a crash loses at most the last partial write), the
CRC is stamped when the chunk is finalized at drain time. Layout::

    <root>/streams/<input_name>/<chunk_id>.flb      in-flight chunks
    <root>/dlq/<chunk_id>.flb                       quarantined chunks

Header (v2): ``FBTC | ver u8 | type u8 | state u8 | pad u8 | crc32 u32le |
tag_len u16le | routes_len u16le | route_names | tag`` (v1 files — no
routes field — still load with tag routing; route NAMES, not bit
positions, so conditional routing survives output reordering). state
0 = open (crc not yet valid, a crash left
it un-finalized — payload is still recovered), 1 = finalized (crc32 of
the payload must match; mismatch → the file is quarantined into
``dlq/<name>.corrupt`` and skipped, so operators find every rejected
payload — hard-errored chunks and corruption alike — in one place).
"""

from __future__ import annotations

import logging
import mmap as _mmap
import os
import struct
import zlib
from typing import Dict, List, Optional, Tuple

from ..codec.chunk import (
    Chunk,
    EVENT_TYPE_BLOBS,
    EVENT_TYPE_LOGS,
    EVENT_TYPE_METRICS,
    EVENT_TYPE_PROFILES,
    EVENT_TYPE_TRACES,
)
from .. import failpoints as _fp
from . import copywitness as _cw
from . import sidecar as _sidecar

log = logging.getLogger("flb.storage")

MAGIC = b"FBTC"
VERSION = 2
STATE_OPEN = 0
STATE_FINAL = 1

_TYPE_CODES = {
    EVENT_TYPE_LOGS: 0,
    EVENT_TYPE_METRICS: 1,
    EVENT_TYPE_TRACES: 2,
    EVENT_TYPE_PROFILES: 3,
    EVENT_TYPE_BLOBS: 4,
}
_TYPE_NAMES = {v: k for k, v in _TYPE_CODES.items()}

_HEAD = struct.Struct("<4sBBBBIH")  # magic, ver, type, state, pad, crc, tag_len
_RLEN = struct.Struct("<H")  # v2: route-names blob length


def _mask_bytes(chunk) -> bytes:
    """v2 route-names blob: conditionally-split chunks persist their
    route OUTPUT NAMES (bit positions are meaningless after a config
    reorder); empty blob = tag routing."""
    names = getattr(chunk, "route_names", None) or ()
    blob = "\n".join(names).encode("utf-8")[:65535]
    return _RLEN.pack(len(blob)) + blob


def _prio_byte(chunk) -> int:
    """QoS priority class in the v2 header's (previously unused) pad
    byte — 0 = unstamped, n+1 = class n — so a spilled/recovered chunk
    keeps its shed-by-priority class across a restart (old files read
    back as unstamped; old readers ignore the byte)."""
    prio = getattr(chunk, "priority", None)
    if prio is None:
        return 0
    return (int(prio) + 1) & 0xFF


class Storage:
    """Filesystem backend for chunk persistence + DLQ."""

    # class-level defaults: tests (and tooling) build bare readers via
    # Storage.__new__ to call _read_chunk_file directly — they replay
    # on the decode walk with zeroed counters instead of crashing
    sidecars = False
    replay_sidecar_hits = 0
    replay_sidecar_trusted = 0
    replay_decode_walks = 0

    def __init__(self, path: str, checksum: bool = True):
        self.root = os.path.abspath(path)
        self.checksum = checksum
        self.streams_dir = os.path.join(self.root, "streams")
        self.dlq_dir = os.path.join(self.root, "dlq")
        os.makedirs(self.streams_dir, exist_ok=True)
        os.makedirs(self.dlq_dir, exist_ok=True)
        # chunk id → (open file handle or None, path)
        self._files: Dict[int, Tuple[Optional[object], str]] = {}
        self._quarantined: set = set()  # chunk ids already in the DLQ
        # fbtpu-memscope offset sidecars: chunk id → incremental writer
        # (None = the table went incomplete and was abandoned)
        self.sidecars = not os.environ.get("FBTPU_NO_SIDECAR")
        self._sidecars: Dict[int, Optional[_sidecar.SidecarWriter]] = {}
        # replay accounting (bench memscope stage reads these)
        self.replay_sidecar_hits = 0     # mmap fast-path replays
        self.replay_sidecar_trusted = 0  # ... of which skipped ALL walks
        self.replay_decode_walks = 0     # Python decode-walk replays

    # -- write path --

    def _chunk_path(self, chunk: Chunk) -> str:
        d = os.path.join(self.streams_dir, chunk.in_name or "default")
        os.makedirs(d, exist_ok=True)
        # the in-process chunk id counter resets on restart; a random
        # suffix keeps new files from colliding with recovered ones
        return os.path.join(d, f"{chunk.id}-{os.urandom(4).hex()}.flb")

    def write_through(self, chunk: Chunk, data,
                      offsets=None) -> None:
        """Persist an append immediately (crash-safe up to this write).

        ``offsets``: the appended span's record END offsets (relative
        to the span) when the caller already knows them — the decode
        path tracks them while joining re-encoded events, so the
        sidecar costs no extra walk there. Without them the native
        scanner discovers the table in C; if neither is possible the
        chunk's sidecar is abandoned and replay falls back to the
        decode walk (bit-exact either way)."""
        if _fp.ACTIVE:
            # partial(n): torn write — persist only the first n bytes of
            # this append (recovery truncates at the last full record)
            d = _fp.fire("storage.append")
            if d is not None and d[0] == "partial":
                data = data[: d[1]]
        entry = self._files.get(chunk.id)
        if entry is None:
            path = self._chunk_path(chunk)
            f = open(path, "wb")
            tag = chunk.tag.encode("utf-8")
            f.write(_HEAD.pack(MAGIC, VERSION,
                               _TYPE_CODES.get(chunk.event_type, 0),
                               STATE_OPEN, _prio_byte(chunk), 0,
                               len(tag)))
            f.write(_mask_bytes(chunk))
            f.write(tag)
            self._files[chunk.id] = (f, path)
            entry = self._files[chunk.id]
            if self.sidecars:
                self._sidecars[chunk.id] = _sidecar.SidecarWriter(
                    _sidecar.sidecar_path(path))
        f = entry[0]
        f.write(data)
        if _fp.ACTIVE:
            # a crash here loses the buffered (written-but-unflushed)
            # append — the exact window write-through exists to bound
            _fp.fire("storage.flush")
        f.flush()
        # sidecar AFTER the data flush: replay tolerates the table
        # being behind the payload (tail walk) or ahead of it (entries
        # past the flushed bytes are dropped), so either crash window
        # between the two flushes recovers bit-exactly
        writer = self._sidecars.get(chunk.id)
        if writer is not None and not writer.dead:
            writer.append_ends(len(data), self._span_ends(data, offsets))

    @staticmethod
    def _span_ends(data, offsets):
        """Record END offsets of one appended span: the caller's table
        when known, else the native scanner's (None abandons the
        sidecar — an unscannable span means the table can never again
        be complete)."""
        if offsets is not None:
            return offsets
        from .. import native

        offs = native.scan_offsets(data)
        if offs is None:
            return None
        if _cw.witness_enabled():
            _cw.count("storage.write.offset_scan", len(data))
        return offs[1:]

    def finalize(self, chunk: Chunk) -> None:
        """Stamp the CRC + finalized state (called at drain time)."""
        entry = self._files.get(chunk.id)
        if entry is None or entry[0] is None:
            return
        if _fp.ACTIVE:
            # a crash here leaves the chunk state=open on disk: recovery
            # must still replay the full payload (un-finalized contract)
            _fp.fire("storage.finalize")
        f, path = entry
        crc = zlib.crc32(chunk.get_bytes()) & 0xFFFFFFFF if self.checksum else 0
        f.flush()
        f.seek(0)
        tag = chunk.tag.encode("utf-8")
        f.write(_HEAD.pack(MAGIC, VERSION,
                           _TYPE_CODES.get(chunk.event_type, 0),
                           STATE_FINAL, _prio_byte(chunk), crc,
                           len(tag)))
        f.write(_mask_bytes(chunk))
        f.close()
        self._files[chunk.id] = (None, path)
        writer = self._sidecars.pop(chunk.id, None)
        if writer is not None:
            # stamped together with the chunk CRC: a FINAL pair with
            # matching CRCs is what replay may trust outright
            writer.finalize()

    def is_tracked(self, chunk: Chunk) -> bool:
        """True when the chunk has a backing stream file (it will be
        recovered as backlog after a crash/stop)."""
        return chunk.id in self._files

    def delete(self, chunk: Chunk) -> None:
        """Drop the backing file once every route delivered the chunk."""
        entry = self._files.pop(chunk.id, None)
        if entry is None:
            return
        f, path = entry
        if f is not None:
            try:
                f.close()
            except OSError:
                pass
        writer = self._sidecars.pop(chunk.id, None)
        if writer is not None:
            writer.close()
        try:
            os.unlink(path)
        except OSError:
            pass
        try:
            os.unlink(_sidecar.sidecar_path(path))
        except OSError:
            pass

    def quarantine(self, chunk: Chunk) -> str:
        """DLQ: persist a rejected chunk (exhausted retries / hard error)
        under dlq/ (flb_storage_quarantine_chunk equivalent)."""
        if chunk.id in self._quarantined:  # one DLQ copy per chunk even
            return ""                      # when several routes fail
        self._quarantined.add(chunk.id)
        path = os.path.join(self.dlq_dir,
                            f"{chunk.id}-{os.urandom(4).hex()}.flb")
        tag = chunk.tag.encode("utf-8")
        payload = chunk.get_bytes()
        crc = zlib.crc32(payload) & 0xFFFFFFFF if self.checksum else 0
        with open(path, "wb") as f:
            f.write(_HEAD.pack(MAGIC, VERSION,
                               _TYPE_CODES.get(chunk.event_type, 0),
                               STATE_FINAL, _prio_byte(chunk), crc,
                               len(tag)))
            f.write(_mask_bytes(chunk))
            f.write(tag)
            f.write(payload)
        if self.sidecars:
            # DLQ files are read back by dlq_chunks / re-ingest
            # tooling: give them a finalized sidecar so inspection of
            # a large quarantine does not pay the decode walk
            writer = _sidecar.SidecarWriter(_sidecar.sidecar_path(path))
            writer.append_ends(len(payload),
                               self._span_ends(payload, None))
            writer.finalize()
        return path

    # -- read path (backlog) --

    def _read_chunk_file(self, path: str) -> Optional[Chunk]:
        with open(path, "rb") as f:
            head = f.read(_HEAD.size)
            if len(head) < _HEAD.size:
                raise ValueError("truncated header")
            magic, ver, tcode, state, prio, crc, tag_len = \
                _HEAD.unpack(head)
            if magic != MAGIC or ver not in (1, VERSION):
                raise ValueError("bad magic/version")
            route_names = None
            if ver >= 2:
                (rlen,) = _RLEN.unpack(f.read(_RLEN.size))
                if rlen:
                    route_names = tuple(
                        f.read(rlen).decode("utf-8").split("\n"))
            tag = f.read(tag_len).decode("utf-8")
            got = self._replay_mmap(f, path, state, crc)
            if got is not None:
                payload, records = got
                self.replay_sidecar_hits += 1
            else:
                payload, records = self._replay_decode(f, state, crc)
                self.replay_decode_walks += 1
        chunk = Chunk(tag, _TYPE_NAMES.get(tcode, EVENT_TYPE_LOGS),
                      os.path.basename(os.path.dirname(path)))
        # payload is already an immutable bytes object: the buf setter
        # adopts it without re-materializing (the replay path used to
        # copy every recovered byte twice more here — bytearray(payload)
        # through the bytes() in the setter)
        chunk.buf = payload
        chunk.records = records
        chunk.locked = True
        chunk.route_names = route_names
        # QoS class survives a restart (shed-by-priority + readmission
        # order stay correct for recovered spill); 0 = unstamped
        chunk.priority = prio - 1 if prio else None
        return chunk

    def _replay_decode(self, f, state: int, crc: int):
        """The decode-walk replay: read the payload, CRC-check, walk
        every record in Python to count + find the torn tail. The
        semantic reference the mmap fast path must match bit-exactly."""
        payload = f.read()
        if state == STATE_FINAL and self.checksum and crc:
            if _fp.ACTIVE:
                # return(err) forces the corrupt-chunk path for a chunk
                # whose bytes are actually fine (quarantine plumbing
                # can be exercised without hand-flipping file bytes)
                _fp.fire("storage.crc_verify")
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                raise ValueError("crc mismatch")
        from ..codec.msgpack import Unpacker

        # a crash mid-write can leave a partial trailing event in an
        # un-finalized file: truncate at the last complete boundary so
        # raw-passthrough outputs never transmit a corrupt fragment
        u = Unpacker(payload)
        records = 0
        for _ in u:
            records += 1
        if _cw.witness_enabled():
            _cw.count("storage.replay.decode_walk", len(payload))
        if u.tell() != len(payload):
            # slice ONLY the torn case: clean recoveries keep the one
            # f.read() materialization (memscope host-redundant-copy)
            payload = payload[: u.tell()]
        return payload, records

    def _replay_mmap(self, f, path: str, state: int, crc: int):
        """Offset-sidecar fast path: map the chunk file read-only and
        take the record table from the sidecar instead of walking the
        payload in Python. Returns (payload bytes, records) or None to
        fall back to the decode walk.

        Trust ladder: a FINAL chunk + FINAL sidecar with both CRCs
        valid is believed outright (no walk at all). Anything torn or
        un-finalized is VALIDATED: the covered region must re-count in
        C to exactly the sidecar's record count (the C walk rejects
        everything the Python walk rejects, so a validated prefix
        decodes identically), and the uncovered tail — normally empty
        or one partial append — is walked in Python. Any disagreement
        abandons the fast path entirely; corruption that the decode
        walk would surface as an error (CRC mismatch) raises the same
        error here, so quarantine behaviour is preserved."""
        if not self.sidecars:
            return None
        payload_off = f.tell()
        plen = os.fstat(f.fileno()).st_size - payload_off
        if plen <= 0:
            return None
        sc = _sidecar.read_sidecar(_sidecar.sidecar_path(path), plen)
        if sc is None:
            return None
        _sstate, ends, trusted = sc
        if not len(ends):
            return None
        try:
            mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
        except (ValueError, OSError):
            return None
        view = memoryview(mm)[payload_off:]
        try:
            if state == STATE_FINAL and self.checksum and crc:
                if _fp.ACTIVE:
                    _fp.fire("storage.crc_verify")
                if zlib.crc32(view) & 0xFFFFFFFF != crc:
                    raise ValueError("crc mismatch")
            else:
                trusted = False  # an open payload may be torn anywhere
            covered = int(ends[-1])
            records = int(len(ends))
            if trusted and covered == plen:
                # both CRCs vouch for both files: no walk of any kind
                self.replay_sidecar_trusted += 1
                end = covered
            else:
                from .. import native

                n = native.count_records(view[:covered])
                if n is None or n != records:
                    return None  # table lies → decode walk decides
                if _cw.witness_enabled():
                    _cw.count("storage.replay.validate_walk", covered)
                end = covered
                if covered < plen:
                    # the data flush outran the sidecar flush: the tail
                    # holds whole appends the table never saw — walk
                    # just those bytes (usually one append, not 2MB)
                    from ..codec.msgpack import Unpacker

                    tail = bytes(view[covered:])
                    u = Unpacker(tail)
                    for _ in u:
                        records += 1
                    end = covered + u.tell()
            payload = bytes(view[:end])
            if _cw.witness_enabled():
                _cw.count("storage.replay.materialize", end)
            return payload, records
        finally:
            view.release()
            mm.close()

    def scan_backlog(self) -> List[Chunk]:
        """Recover chunks left on disk by a previous run; corrupt files
        are quarantined into the DLQ directory (``<name>.corrupt``) so
        operators find every rejected payload in one place."""
        if _fp.ACTIVE:
            # crash here = dying mid-recovery: the NEXT restart must
            # still recover everything (recovery is idempotent)
            _fp.fire("storage.backlog_load")
        out: List[Chunk] = []
        for dirpath, _dirs, files in os.walk(self.streams_dir):
            for name in sorted(files):
                if not name.endswith(".flb"):
                    continue
                path = os.path.join(dirpath, name)
                try:
                    chunk = self._read_chunk_file(path)
                except Exception as e:
                    log.warning("storage: corrupt chunk %s (%s) "
                                "quarantined to DLQ", path, e)
                    try:
                        os.rename(path, os.path.join(
                            self.dlq_dir, name + ".corrupt"))
                    except OSError:
                        log.exception("storage: cannot quarantine %s",
                                      path)
                    self._drop_sidecar(path)
                    continue
                if chunk.records == 0:
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                    self._drop_sidecar(path)
                    continue
                # track so delivery deletes the file
                self._files[chunk.id] = (None, path)
                out.append(chunk)
        return out

    def dlq_chunks(self) -> List[Chunk]:
        """Read quarantined chunks (inspection / re-ingestion tooling)."""
        out = []
        for name in sorted(os.listdir(self.dlq_dir)):
            if name.endswith(".flb"):
                try:
                    out.append(
                        self._read_chunk_file(os.path.join(self.dlq_dir, name))
                    )
                except Exception:
                    # a corrupt DLQ file must not hide silently — the
                    # quarantine exists so operators can inspect it
                    log.warning("unreadable DLQ chunk %s skipped",
                                name, exc_info=True)
                    continue
        return out

    @staticmethod
    def _drop_sidecar(path: str) -> None:
        """Remove the offset table of a chunk file that is gone (empty
        recovery / quarantine rename): an orphaned table next to
        nothing would be adopted by no replay and confuse operators."""
        try:
            os.unlink(_sidecar.sidecar_path(path))
        except OSError:
            pass

    def close(self) -> None:
        for f, _ in list(self._files.values()):
            if f is not None:
                try:
                    f.close()
                except OSError:
                    pass
        for writer in list(self._sidecars.values()):
            if writer is not None:
                writer.close()
        self._sidecars.clear()
