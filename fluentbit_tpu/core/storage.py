"""Filesystem chunk storage — persistence, backlog, DLQ.

Reference: lib/chunkio (file chunks with CRC32 integrity,
src/cio_file.c:49-104) wrapped by src/flb_storage.c (memory/filesystem
mapping per input :530-556, quarantine
flb_storage_quarantine_chunk), and plugins/in_storage_backlog (re-ingest
of filesystem chunks found at startup after sb_segregate_chunks,
src/flb_engine.c:1129).

Design (TPU build, not a port of chunkio): a chunk file is
``header + concatenated msgpack events``; appends are write-through
(append + flush so a crash loses at most the last partial write), the
CRC is stamped when the chunk is finalized at drain time. Layout::

    <root>/streams/<input_name>/<chunk_id>.flb      in-flight chunks
    <root>/dlq/<chunk_id>.flb                       quarantined chunks

Header (v2): ``FBTC | ver u8 | type u8 | state u8 | pad u8 | crc32 u32le |
tag_len u16le | routes_len u16le | route_names | tag`` (v1 files — no
routes field — still load with tag routing; route NAMES, not bit
positions, so conditional routing survives output reordering). state
0 = open (crc not yet valid, a crash left
it un-finalized — payload is still recovered), 1 = finalized (crc32 of
the payload must match; mismatch → the file is quarantined into
``dlq/<name>.corrupt`` and skipped, so operators find every rejected
payload — hard-errored chunks and corruption alike — in one place).
"""

from __future__ import annotations

import logging
import os
import struct
import zlib
from typing import Dict, List, Optional, Tuple

from ..codec.chunk import (
    Chunk,
    EVENT_TYPE_BLOBS,
    EVENT_TYPE_LOGS,
    EVENT_TYPE_METRICS,
    EVENT_TYPE_PROFILES,
    EVENT_TYPE_TRACES,
)
from .. import failpoints as _fp

log = logging.getLogger("flb.storage")

MAGIC = b"FBTC"
VERSION = 2
STATE_OPEN = 0
STATE_FINAL = 1

_TYPE_CODES = {
    EVENT_TYPE_LOGS: 0,
    EVENT_TYPE_METRICS: 1,
    EVENT_TYPE_TRACES: 2,
    EVENT_TYPE_PROFILES: 3,
    EVENT_TYPE_BLOBS: 4,
}
_TYPE_NAMES = {v: k for k, v in _TYPE_CODES.items()}

_HEAD = struct.Struct("<4sBBBBIH")  # magic, ver, type, state, pad, crc, tag_len
_RLEN = struct.Struct("<H")  # v2: route-names blob length


def _mask_bytes(chunk) -> bytes:
    """v2 route-names blob: conditionally-split chunks persist their
    route OUTPUT NAMES (bit positions are meaningless after a config
    reorder); empty blob = tag routing."""
    names = getattr(chunk, "route_names", None) or ()
    blob = "\n".join(names).encode("utf-8")[:65535]
    return _RLEN.pack(len(blob)) + blob


def _prio_byte(chunk) -> int:
    """QoS priority class in the v2 header's (previously unused) pad
    byte — 0 = unstamped, n+1 = class n — so a spilled/recovered chunk
    keeps its shed-by-priority class across a restart (old files read
    back as unstamped; old readers ignore the byte)."""
    prio = getattr(chunk, "priority", None)
    if prio is None:
        return 0
    return (int(prio) + 1) & 0xFF


class Storage:
    """Filesystem backend for chunk persistence + DLQ."""

    def __init__(self, path: str, checksum: bool = True):
        self.root = os.path.abspath(path)
        self.checksum = checksum
        self.streams_dir = os.path.join(self.root, "streams")
        self.dlq_dir = os.path.join(self.root, "dlq")
        os.makedirs(self.streams_dir, exist_ok=True)
        os.makedirs(self.dlq_dir, exist_ok=True)
        # chunk id → (open file handle or None, path)
        self._files: Dict[int, Tuple[Optional[object], str]] = {}
        self._quarantined: set = set()  # chunk ids already in the DLQ

    # -- write path --

    def _chunk_path(self, chunk: Chunk) -> str:
        d = os.path.join(self.streams_dir, chunk.in_name or "default")
        os.makedirs(d, exist_ok=True)
        # the in-process chunk id counter resets on restart; a random
        # suffix keeps new files from colliding with recovered ones
        return os.path.join(d, f"{chunk.id}-{os.urandom(4).hex()}.flb")

    def write_through(self, chunk: Chunk, data: bytes) -> None:
        """Persist an append immediately (crash-safe up to this write)."""
        if _fp.ACTIVE:
            # partial(n): torn write — persist only the first n bytes of
            # this append (recovery truncates at the last full record)
            d = _fp.fire("storage.append")
            if d is not None and d[0] == "partial":
                data = data[: d[1]]
        entry = self._files.get(chunk.id)
        if entry is None:
            path = self._chunk_path(chunk)
            f = open(path, "wb")
            tag = chunk.tag.encode("utf-8")
            f.write(_HEAD.pack(MAGIC, VERSION,
                               _TYPE_CODES.get(chunk.event_type, 0),
                               STATE_OPEN, _prio_byte(chunk), 0,
                               len(tag)))
            f.write(_mask_bytes(chunk))
            f.write(tag)
            self._files[chunk.id] = (f, path)
            entry = self._files[chunk.id]
        f = entry[0]
        f.write(data)
        if _fp.ACTIVE:
            # a crash here loses the buffered (written-but-unflushed)
            # append — the exact window write-through exists to bound
            _fp.fire("storage.flush")
        f.flush()

    def finalize(self, chunk: Chunk) -> None:
        """Stamp the CRC + finalized state (called at drain time)."""
        entry = self._files.get(chunk.id)
        if entry is None or entry[0] is None:
            return
        if _fp.ACTIVE:
            # a crash here leaves the chunk state=open on disk: recovery
            # must still replay the full payload (un-finalized contract)
            _fp.fire("storage.finalize")
        f, path = entry
        crc = zlib.crc32(chunk.get_bytes()) & 0xFFFFFFFF if self.checksum else 0
        f.flush()
        f.seek(0)
        tag = chunk.tag.encode("utf-8")
        f.write(_HEAD.pack(MAGIC, VERSION,
                           _TYPE_CODES.get(chunk.event_type, 0),
                           STATE_FINAL, _prio_byte(chunk), crc,
                           len(tag)))
        f.write(_mask_bytes(chunk))
        f.close()
        self._files[chunk.id] = (None, path)

    def is_tracked(self, chunk: Chunk) -> bool:
        """True when the chunk has a backing stream file (it will be
        recovered as backlog after a crash/stop)."""
        return chunk.id in self._files

    def delete(self, chunk: Chunk) -> None:
        """Drop the backing file once every route delivered the chunk."""
        entry = self._files.pop(chunk.id, None)
        if entry is None:
            return
        f, path = entry
        if f is not None:
            try:
                f.close()
            except OSError:
                pass
        try:
            os.unlink(path)
        except OSError:
            pass

    def quarantine(self, chunk: Chunk) -> str:
        """DLQ: persist a rejected chunk (exhausted retries / hard error)
        under dlq/ (flb_storage_quarantine_chunk equivalent)."""
        if chunk.id in self._quarantined:  # one DLQ copy per chunk even
            return ""                      # when several routes fail
        self._quarantined.add(chunk.id)
        path = os.path.join(self.dlq_dir,
                            f"{chunk.id}-{os.urandom(4).hex()}.flb")
        tag = chunk.tag.encode("utf-8")
        payload = chunk.get_bytes()
        crc = zlib.crc32(payload) & 0xFFFFFFFF if self.checksum else 0
        with open(path, "wb") as f:
            f.write(_HEAD.pack(MAGIC, VERSION,
                               _TYPE_CODES.get(chunk.event_type, 0),
                               STATE_FINAL, _prio_byte(chunk), crc,
                               len(tag)))
            f.write(_mask_bytes(chunk))
            f.write(tag)
            f.write(payload)
        return path

    # -- read path (backlog) --

    def _read_chunk_file(self, path: str) -> Optional[Chunk]:
        with open(path, "rb") as f:
            head = f.read(_HEAD.size)
            if len(head) < _HEAD.size:
                raise ValueError("truncated header")
            magic, ver, tcode, state, prio, crc, tag_len = \
                _HEAD.unpack(head)
            if magic != MAGIC or ver not in (1, VERSION):
                raise ValueError("bad magic/version")
            route_names = None
            if ver >= 2:
                (rlen,) = _RLEN.unpack(f.read(_RLEN.size))
                if rlen:
                    route_names = tuple(
                        f.read(rlen).decode("utf-8").split("\n"))
            tag = f.read(tag_len).decode("utf-8")
            payload = f.read()
        if state == STATE_FINAL and self.checksum and crc:
            if _fp.ACTIVE:
                # return(err) forces the corrupt-chunk path for a chunk
                # whose bytes are actually fine (quarantine plumbing
                # can be exercised without hand-flipping file bytes)
                _fp.fire("storage.crc_verify")
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                raise ValueError("crc mismatch")
        from ..codec.msgpack import Unpacker

        # a crash mid-write can leave a partial trailing event in an
        # un-finalized file: truncate at the last complete boundary so
        # raw-passthrough outputs never transmit a corrupt fragment
        u = Unpacker(payload)
        records = 0
        for _ in u:
            records += 1
        payload = payload[: u.tell()]
        chunk = Chunk(tag, _TYPE_NAMES.get(tcode, EVENT_TYPE_LOGS),
                      os.path.basename(os.path.dirname(path)))
        chunk.buf = bytearray(payload)
        chunk.records = records
        chunk.locked = True
        chunk.route_names = route_names
        # QoS class survives a restart (shed-by-priority + readmission
        # order stay correct for recovered spill); 0 = unstamped
        chunk.priority = prio - 1 if prio else None
        return chunk

    def scan_backlog(self) -> List[Chunk]:
        """Recover chunks left on disk by a previous run; corrupt files
        are quarantined into the DLQ directory (``<name>.corrupt``) so
        operators find every rejected payload in one place."""
        if _fp.ACTIVE:
            # crash here = dying mid-recovery: the NEXT restart must
            # still recover everything (recovery is idempotent)
            _fp.fire("storage.backlog_load")
        out: List[Chunk] = []
        for dirpath, _dirs, files in os.walk(self.streams_dir):
            for name in sorted(files):
                if not name.endswith(".flb"):
                    continue
                path = os.path.join(dirpath, name)
                try:
                    chunk = self._read_chunk_file(path)
                except Exception as e:
                    log.warning("storage: corrupt chunk %s (%s) "
                                "quarantined to DLQ", path, e)
                    try:
                        os.rename(path, os.path.join(
                            self.dlq_dir, name + ".corrupt"))
                    except OSError:
                        log.exception("storage: cannot quarantine %s",
                                      path)
                    continue
                if chunk.records == 0:
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                    continue
                # track so delivery deletes the file
                self._files[chunk.id] = (None, path)
                out.append(chunk)
        return out

    def dlq_chunks(self) -> List[Chunk]:
        """Read quarantined chunks (inspection / re-ingestion tooling)."""
        out = []
        for name in sorted(os.listdir(self.dlq_dir)):
            if name.endswith(".flb"):
                try:
                    out.append(
                        self._read_chunk_file(os.path.join(self.dlq_dir, name))
                    )
                except Exception:
                    # a corrupt DLQ file must not hide silently — the
                    # quarantine exists so operators can inspect it
                    log.warning("unreadable DLQ chunk %s skipped",
                                name, exc_info=True)
                    continue
        return out

    def close(self) -> None:
        for f, _ in list(self._files.values()):
            if f is not None:
                try:
                    f.close()
                except OSError:
                    pass
