"""Typed plugin configuration — config_map equivalent.

Reference: include/fluent-bit/flb_config_map.h:33-51 defines a declarative
per-plugin option schema (FLB_CONFIG_MAP_STR/INT/BOOL/SIZE/TIME/DOUBLE/
CLIST/SLIST...) that is auto-validated and written into plugin context
structs. Here a plugin declares ``config_map`` as a list of ConfigMapEntry;
``apply_config_map`` validates + coerces user properties onto the instance.

Also the service-level config (flush interval, grace, scheduler base/cap —
reference src/flb_config.c:190-193,369-370).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# ---------------------------------------------------------------------------
# Value coercion (reference: flb_utils.c flb_utils_size_to_bytes,
# flb_utils_time_to_seconds, flb_utils_bool)
# ---------------------------------------------------------------------------

_SIZE_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*([kKmMgG]?)b?\s*$")
_TIME_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*(ms|s|m|h|d)?\s*$")

_SIZE_MULT = {"": 1, "k": 1024, "m": 1024 ** 2, "g": 1024 ** 3}
_TIME_MULT = {None: 1.0, "ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}

TRUE_WORDS = {"true", "on", "yes", "1", "enabled"}
FALSE_WORDS = {"false", "off", "no", "0", "disabled"}


def parse_bool(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    s = str(v).strip().lower()
    if s in TRUE_WORDS:
        return True
    if s in FALSE_WORDS:
        return False
    raise ValueError(f"invalid boolean value: {v!r}")


def parse_size(v: Any) -> int:
    """'10M' → bytes (flb_utils_size_to_bytes)."""
    if isinstance(v, (int, float)):
        return int(v)
    m = _SIZE_RE.match(str(v))
    if not m:
        raise ValueError(f"invalid size value: {v!r}")
    return int(float(m.group(1)) * _SIZE_MULT[m.group(2).lower()])


def parse_time(v: Any) -> float:
    """'5s' / '100ms' → seconds."""
    if isinstance(v, (int, float)):
        return float(v)
    m = _TIME_RE.match(str(v))
    if not m:
        raise ValueError(f"invalid time value: {v!r}")
    return float(m.group(1)) * _TIME_MULT[m.group(2)]


def split_clist(v: Any, sep: str = ",") -> List[str]:
    if isinstance(v, (list, tuple)):
        return [str(x) for x in v]
    return [part.strip() for part in str(v).split(sep) if part.strip()]


def split_slist(v: Any, max_split: int = -1) -> List[str]:
    """Space-separated list (config_map SLIST): respects max_split so the
    trailing element may contain spaces (used e.g. by grep's 'Regex key
    pattern with spaces')."""
    if isinstance(v, (list, tuple)):
        return [str(x) for x in v]
    return str(v).split(None, max_split) if max_split >= 0 else str(v).split()


_COERCERS = {
    "raw": lambda v: v,  # pass-through (python-object properties, e.g. out_lib callback)
    "str": lambda v: str(v),
    "int": lambda v: int(str(v), 0),
    "double": lambda v: float(v),
    "bool": parse_bool,
    "size": parse_size,
    "time": parse_time,
    "clist": split_clist,
    "slist": split_slist,
}


@dataclass
class ConfigMapEntry:
    """One declarative plugin option."""

    name: str
    type: str = "str"  # str|int|double|bool|size|time|clist|slist
    default: Any = None
    multiple: bool = False  # option may appear multiple times (e.g. grep rules)
    slist_max_split: int = -1
    desc: str = ""

    def coerce(self, value: Any) -> Any:
        if self.type == "slist" and self.slist_max_split >= 0:
            return split_slist(value, self.slist_max_split)
        fn = _COERCERS.get(self.type)
        if fn is None:
            raise ValueError(f"unknown config_map type {self.type!r}")
        return fn(value)


class Properties:
    """Case-insensitive property bag with multi-value support.

    Reference config keys are case-insensitive (flb_config_prop_get uses
    strcasecmp); values set multiple times accumulate (grep Regex rules).
    """

    def __init__(self) -> None:
        self._items: List[tuple] = []  # (lower_key, original_key, value)

    def set(self, key: str, value: Any) -> None:
        self._items.append((key.lower(), key, value))

    def get(self, key: str, default: Any = None) -> Any:
        k = key.lower()
        for lk, _, v in reversed(self._items):
            if lk == k:
                return v
        return default

    def get_all(self, key: str) -> List[Any]:
        k = key.lower()
        return [v for lk, _, v in self._items if lk == k]

    def items(self):
        return [(orig, v) for _, orig, v in self._items]

    def __contains__(self, key: str) -> bool:
        k = key.lower()
        return any(lk == k for lk, _, _ in self._items)

    def update(self, d: Dict[str, Any]) -> None:
        for k, v in d.items():
            self.set(k, v)


def apply_config_map(config_map: List[ConfigMapEntry], props: Properties,
                     target: Any) -> None:
    """Validate + coerce properties onto ``target`` attributes.

    Unknown properties raise (the reference fails startup on unknown keys).
    Attribute name is the option name lowercased with '.' and '-' → '_'.
    """
    by_name = {e.name.lower(): e for e in config_map}
    seen_multi: Dict[str, list] = {}
    for key, value in props.items():
        lk = key.lower()
        entry = by_name.get(lk)
        if entry is None:
            # allow shared/core keys handled by the engine itself
            if lk in CORE_INSTANCE_KEYS:
                continue
            if getattr(target, "allow_unknown_properties", False):
                # dynamic (.so) plugins declare no config_map: every
                # property passes through to the native side verbatim
                continue
            raise ValueError(f"unknown property {key!r}")
        coerced = entry.coerce(value)
        attr = _attr_name(entry.name)
        if entry.multiple:
            seen_multi.setdefault(attr, []).append(coerced)
        else:
            setattr(target, attr, coerced)
    for attr, values in seen_multi.items():
        setattr(target, attr, values)
    # defaults
    for e in config_map:
        attr = _attr_name(e.name)
        if not hasattr(target, attr) or getattr(target, attr) is None:
            if e.multiple:
                if not hasattr(target, attr) or getattr(target, attr) is None:
                    setattr(target, attr, [])
            elif e.default is not None:
                setattr(target, attr, e.coerce(e.default))
            elif not hasattr(target, attr):
                setattr(target, attr, None)


def _attr_name(name: str) -> str:
    return name.lower().replace(".", "_").replace("-", "_")


# Instance-level keys consumed by the engine, valid for every plugin
# (reference: flb_input.c/flb_output.c/flb_filter.c common properties).
CORE_INSTANCE_KEYS = {
    "tag", "match", "match_regex", "alias", "log_level",
    "mem_buf_limit", "storage.type", "storage.pause_on_chunks_overlimit",
    "threaded", "workers", "retry_limit", "no_multiplex", "host", "port", "tls",
    "tls.verify", "tls.ca_file", "tls.crt_file", "tls.key_file", "tls.vhost",
    "http2",  # HTTP-based outputs: prior-knowledge h2c delivery
    "proxy",  # HTTP-based outputs: http:// forward proxy
    "route_condition",  # ingest-time conditional routing (outputs)
    "flush_timeout",  # fbtpu-guard per-output flush deadline (outputs)
    # fbtpu-qos tenant membership + contract (inputs; core/qos.py)
    "tenant", "tenant.weight", "tenant.priority", "tenant.rate",
    "tenant.burst", "tenant.overflow", "tenant.storage_limit",
    "tenant.flush_concurrency",
    "net.keepalive", "net.keepalive_idle_timeout",
    "net.keepalive_max_recycle", "net.max_worker_connections",
}


@dataclass
class ServiceConfig:
    """[SERVICE] section (reference src/flb_config.c + flb_config.h)."""

    flush: float = 1.0           # flush timer interval seconds
    grace: float = 5.0           # shutdown grace period
    daemon: bool = False
    log_level: str = "info"
    http_server: bool = False
    http_listen: str = "0.0.0.0"
    http_port: int = 2020
    hot_reload: bool = False
    # SIGHUP applies the config-file diff through a ReloadTxn
    # generation swap (core/reload_diff.py) instead of a full
    # stop/start; unsupported edits fall back to the restart path
    hot_reload_diff: bool = False
    scheduler_base: float = 5.0      # retry backoff base (flb_scheduler.h:29)
    scheduler_cap: float = 2000.0    # retry backoff cap  (flb_scheduler.h:30)
    retry_limit: int = 1             # default per-output retries
    task_map_size: int = 2048        # FLB_CONFIG_DEFAULT_TASK_MAP_SIZE
    storage_path: Optional[str] = None
    storage_sync: str = "normal"
    storage_checksum: bool = False
    storage_backlog_mem_limit: int = 5 * 1024 * 1024
    storage_max_chunks_up: int = 128  # pause threshold (flb_storage)
    # fbtpu-guard (core/guard.py — no reference equivalent): flush
    # deadlines, per-output circuit breakers, watchdog + load shedding
    guard_enable: bool = True
    guard_flush_timeout: float = 0.0     # 0 = off → soft-kill at 2×grace
    guard_breaker_failures: int = 5      # consecutive failures to open
    guard_breaker_error_rate: float = 0.5  # windowed failure fraction
    guard_breaker_window: int = 20       # outcomes in the rate window
    guard_breaker_cooldown: float = 5.0  # open → half-open delay
    guard_breaker_probes: int = 1        # half-open successes to close
    guard_shed_watermark: float = 0.8    # task-map occupancy fraction
    guard_stall_after: float = 30.0      # heartbeat age → "stalled"
    guard_leak_grace: float = 5.0        # soft-kill → leaked-thread count
    guard_worker_start_timeout: float = 10.0  # worker pool startup bound
    # fbtpu-qos (core/qos.py — no reference equivalent). qos_enable
    # gates ADMISSION QUOTAS only (QOS.md): fair dispatch runs
    # regardless (bit-compatible FIFO with a single default tenant)
    # and shed-by-priority keys off tenants spanning >1 class
    qos_enable: bool = True
    qos_quantum: int = 2 * 1024 * 1024   # DWRR bytes/round per weight
    qos_weight_floor: float = 0.05       # zero-weight starvation floor
    qos_default_weight: float = 1.0      # tenants that declare none
    qos_default_priority: int = 4        # 0 = highest of 8 classes
    qos_cycle_budget: int = 0            # bytes dispatched per flush
    #                                      cycle (0 = unlimited)
    qos_shed_hysteresis: float = 0.75    # readmit below thr × this
    # TPU execution options (new — no reference equivalent)
    tpu_enable: bool = True
    tpu_batch_records: int = 8192
    tpu_max_record_len: int = 512

    extra: Dict[str, Any] = field(default_factory=dict)

    _KEYMAP = {
        "flush": ("flush", parse_time),
        "grace": ("grace", parse_time),
        "daemon": ("daemon", parse_bool),
        "log_level": ("log_level", str),
        "http_server": ("http_server", parse_bool),
        "http_listen": ("http_listen", str),
        "http_port": ("http_port", int),
        "hot_reload": ("hot_reload", parse_bool),
        "hot_reload_diff": ("hot_reload_diff", parse_bool),
        "scheduler.base": ("scheduler_base", parse_time),
        "scheduler.cap": ("scheduler_cap", parse_time),
        "retry_limit": ("retry_limit", int),
        "task_map_size": ("task_map_size", int),
        "storage.path": ("storage_path", str),
        "storage.sync": ("storage_sync", str),
        "storage.checksum": ("storage_checksum", parse_bool),
        "storage.backlog.mem_limit": ("storage_backlog_mem_limit", parse_size),
        "storage.max_chunks_up": ("storage_max_chunks_up", int),
        "guard.enable": ("guard_enable", parse_bool),
        "guard.flush_timeout": ("guard_flush_timeout", parse_time),
        "guard.breaker_failures": ("guard_breaker_failures", int),
        "guard.breaker_error_rate": ("guard_breaker_error_rate", float),
        "guard.breaker_window": ("guard_breaker_window", int),
        "guard.breaker_cooldown": ("guard_breaker_cooldown", parse_time),
        "guard.breaker_probes": ("guard_breaker_probes", int),
        "guard.shed_watermark": ("guard_shed_watermark", float),
        "guard.stall_after": ("guard_stall_after", parse_time),
        "guard.leak_grace": ("guard_leak_grace", parse_time),
        "guard.worker_start_timeout":
            ("guard_worker_start_timeout", parse_time),
        "qos.enable": ("qos_enable", parse_bool),
        "qos.quantum": ("qos_quantum", parse_size),
        "qos.weight_floor": ("qos_weight_floor", float),
        "qos.default_weight": ("qos_default_weight", float),
        "qos.default_priority": ("qos_default_priority", int),
        "qos.cycle_budget": ("qos_cycle_budget", parse_size),
        "qos.shed_hysteresis": ("qos_shed_hysteresis", float),
        "tpu.enable": ("tpu_enable", parse_bool),
        "tpu.batch_records": ("tpu_batch_records", int),
        "tpu.max_record_len": ("tpu_max_record_len", int),
    }

    def set(self, key: str, value: Any) -> None:
        lk = key.lower()
        mapped = self._KEYMAP.get(lk)
        if mapped is None:
            self.extra[lk] = value
            return
        attr, fn = mapped
        setattr(self, attr, fn(value))
