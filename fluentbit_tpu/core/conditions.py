"""Conditions — field/op/value rules for processors and routing.

Reference: src/flb_conditionals.c (struct flb_condition: a rule list
with AND/OR combination; ops eq/neq/gt/lt/gte/lte/regex/not_regex/
in/not_in, record-accessor fields) consumed by processor units
(include/fluent-bit/flb_processor.h:69-90 ``condition``) and the
condition-based router (src/flb_router_condition.c).

YAML shape (the reference's processor condition form)::

    condition:
      op: and                 # or
      rules:
        - field: "$status"
          op: gte
          value: 500
        - field: "$level"
          op: in
          value: ["error", "fatal"]
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .record_accessor import RecordAccessor
from ..regex import FlbRegex

OPS = ("eq", "neq", "gt", "lt", "gte", "lte", "regex", "not_regex",
       "in", "not_in", "exists", "not_exists")


class Rule:
    __slots__ = ("ra", "op", "value", "_rx")

    def __init__(self, field: str, op: str, value: Any = None):
        op = op.lower()
        if op not in OPS:
            raise ValueError(f"condition: unknown op {op!r}")
        self.ra = RecordAccessor(field if field.startswith("$")
                                 else "$" + field)
        self.op = op
        self.value = value
        self._rx = FlbRegex(str(value)) if op in ("regex", "not_regex") \
            else None

    def eval(self, body: dict) -> bool:
        sentinel = object()
        v = self.ra.get(body, sentinel)
        if self.op == "exists":
            return v is not sentinel
        if self.op == "not_exists":
            return v is sentinel
        if v is sentinel:
            return False
        if self.op == "eq":
            return v == self.value
        if self.op == "neq":
            return v != self.value
        if self.op in ("gt", "lt", "gte", "lte"):
            try:
                if self.op == "gt":
                    return v > self.value
                if self.op == "lt":
                    return v < self.value
                if self.op == "gte":
                    return v >= self.value
                return v <= self.value
            except TypeError:
                return False
        if self.op in ("regex", "not_regex"):
            ok = isinstance(v, str) and self._rx.match(v)
            return ok if self.op == "regex" else not ok
        if self.op in ("in", "not_in"):
            members = self.value if isinstance(self.value, (list, tuple)) \
                else [self.value]
            return (v in members) if self.op == "in" else (v not in members)
        return False


class Condition:
    """flb_condition: AND/OR over a rule list."""

    def __init__(self, rules: List[Rule], op: str = "and"):
        op = (op or "and").lower()
        if op not in ("and", "or"):
            raise ValueError(f"condition: unknown combinator {op!r}")
        self.rules = rules
        self.op = op

    def eval(self, body: dict) -> bool:
        if not isinstance(body, dict):
            return False
        if self.op == "and":
            return all(r.eval(body) for r in self.rules)
        return any(r.eval(body) for r in self.rules)

    @classmethod
    def from_config(cls, cfg: Dict[str, Any]) -> "Condition":
        if not isinstance(cfg, dict) or "rules" not in cfg:
            raise ValueError("condition needs a 'rules' list")
        rules = []
        for r in cfg["rules"]:
            rules.append(Rule(r["field"], r.get("op", "eq"),
                              r.get("value")))
        return cls(rules, cfg.get("op", "and"))
