"""Record accessor — the ``$key['nested'][0]`` path language.

Reference: src/flb_record_accessor.c + flex/bison grammar
src/record_accessor/ra.l, ra.y. Paths address fields inside a record's body
(and metadata), support nested maps and array indexing, and can be embedded
inside template strings (used by rewrite_tag's new-tag templates, which also
expose $TAG, $TAG[n] and regex captures).

Grammar supported here (superset of what the five baseline configs need):
  $key                    top-level key
  $key['a']['b']          nested map access (single or double quotes)
  $key.a.b                dotted shorthand (ra.y KEY '.' KEY)
  $key[0]                 array index
  $TAG                    full tag;  $TAG[0] first dot-separated part
  $0..$9                  regex capture group (rewrite_tag context)
  literal text            passes through in templates
"""

from __future__ import annotations

import re
from typing import Any, List, Optional, Tuple

_PATH_TOKEN = re.compile(
    r"""\[(?:'(?P<sq>[^']*)'|"(?P<dq>[^"]*)"|(?P<idx>-?\d+))\]|\.(?P<dot>[A-Za-z0-9_\-]+)"""
)
_HEAD = re.compile(r"^\$(?P<head>[A-Za-z0-9_\-]+)")


class RecordAccessor:
    """Compiled accessor for a single ``$...`` path."""

    def __init__(self, pattern: str):
        self.pattern = pattern
        if not pattern.startswith("$"):
            # bare key name — grep's "Regex key val" form allows `key`
            self.head = pattern
            self.parts: List[Any] = []
            return
        m = _HEAD.match(pattern)
        if not m:
            raise ValueError(f"invalid record accessor {pattern!r}")
        self.head = m.group("head")
        self.parts = []
        for tok in _PATH_TOKEN.finditer(pattern, m.end()):
            if tok.group("sq") is not None:
                self.parts.append(tok.group("sq"))
            elif tok.group("dq") is not None:
                self.parts.append(tok.group("dq"))
            elif tok.group("idx") is not None:
                self.parts.append(int(tok.group("idx")))
            else:
                self.parts.append(tok.group("dot"))

    def get(self, record: dict, default: Any = None) -> Any:
        """Fetch the addressed value from a body map (flb_ra_get_value_object)."""
        cur: Any = record
        key: Any = self.head
        for part in [self.head] + self.parts:
            if isinstance(cur, dict):
                if part in cur:
                    cur = cur[part]
                elif isinstance(part, int) and str(part) in cur:
                    cur = cur[str(part)]
                else:
                    return default
            elif isinstance(cur, list) and isinstance(part, int):
                if -len(cur) <= part < len(cur):
                    cur = cur[part]
                else:
                    return default
            else:
                return default
        return cur

    def exists(self, record: dict) -> bool:
        sentinel = object()
        return self.get(record, sentinel) is not sentinel

    def update(self, record: dict, value: Any) -> bool:
        """Set the addressed value (flb_ra_update_value). Creates
        intermediate maps for missing string keys."""
        path = [self.head] + self.parts
        cur: Any = record
        for part in path[:-1]:
            if isinstance(cur, dict):
                nxt = cur.get(part)
                if not isinstance(nxt, (dict, list)):
                    nxt = {}
                    cur[part] = nxt
                cur = nxt
            elif isinstance(cur, list) and isinstance(part, int) and -len(cur) <= part < len(cur):
                cur = cur[part]
            else:
                return False
        last = path[-1]
        if isinstance(cur, dict):
            cur[last] = value
            return True
        if isinstance(cur, list) and isinstance(last, int) and -len(cur) <= last < len(cur):
            cur[last] = value
            return True
        return False

    def delete(self, record: dict) -> bool:
        path = [self.head] + self.parts
        cur: Any = record
        for part in path[:-1]:
            if isinstance(cur, dict) and part in cur:
                cur = cur[part]
            elif isinstance(cur, list) and isinstance(part, int) and -len(cur) <= part < len(cur):
                cur = cur[part]
            else:
                return False
        last = path[-1]
        if isinstance(cur, dict) and last in cur:
            del cur[last]
            return True
        if isinstance(cur, list) and isinstance(last, int) and -len(cur) <= last < len(cur):
            del cur[last]
            return True
        return False


# In templates only the bracket trail form is taken ($k['a'][0]); dotted
# shorthand would be ambiguous with literal '.' separators in tag templates.
_TEMPLATE_VAR = re.compile(
    r"""\$(?P<num>\d)|\$(?P<name>[A-Za-z_][A-Za-z0-9_\-]*)(?P<trail>(?:\[(?:'[^']*'|"[^"]*"|-?\d+)\])*)"""
)


class Template:
    """Template string with embedded accessors — rewrite_tag's new-tag
    composer (flb_ra_translate, reference src/flb_record_accessor.c).

    Variables: $TAG, $TAG[n], $0..$9 (regex captures), $field paths.
    """

    def __init__(self, text: str):
        self.text = text
        self._parts: List[Tuple[str, Any]] = []  # (kind, payload)
        pos = 0
        for m in _TEMPLATE_VAR.finditer(text):
            if m.start() > pos:
                self._parts.append(("lit", text[pos : m.start()]))
            if m.group("num") is not None:
                self._parts.append(("cap", int(m.group("num"))))
            else:
                name = m.group("name")
                trail = m.group("trail") or ""
                if name == "TAG":
                    if trail and re.fullmatch(r"\[\d+\]", trail):
                        self._parts.append(("tagpart", int(trail[1:-1])))
                    else:
                        self._parts.append(("tag", None))
                else:
                    self._parts.append(("ra", RecordAccessor("$" + name + trail)))
            pos = m.end()
        if pos < len(text):
            self._parts.append(("lit", text[pos:]))

    @property
    def static_for_tag(self) -> bool:
        """True when rendering depends on the tag alone (no record
        fields, no regex captures) — the batched rewrite_tag path
        renders such templates once per (rule, chunk) instead of once
        per record."""
        return all(k in ("lit", "tag", "tagpart") for k, _ in self._parts)

    def render(
        self,
        record: Optional[dict] = None,
        tag: str = "",
        captures: Optional[Tuple[str, ...]] = None,
    ) -> str:
        out: List[str] = []
        tag_parts = tag.split(".")
        for kind, payload in self._parts:
            if kind == "lit":
                out.append(payload)
            elif kind == "tag":
                out.append(tag)
            elif kind == "tagpart":
                out.append(tag_parts[payload] if payload < len(tag_parts) else "")
            elif kind == "cap":
                if captures and payload < len(captures) and captures[payload] is not None:
                    out.append(str(captures[payload]))
            else:  # ra
                val = payload.get(record or {})
                if val is not None:
                    out.append(val if isinstance(val, str) else _stringify(val))
        return "".join(out)


def _stringify(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)
