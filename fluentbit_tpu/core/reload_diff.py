"""SIGHUP config-file diff driver — reload WITHOUT the restart.

``hot_reload`` alone makes SIGHUP rebuild the whole pipeline: validate
the new file, stop the old engine (draining every chunk through the
grace window), start a fresh one. Correct, but a heavyweight answer to
"I added one grep rule" — every input re-opens its files/sockets, every
DFA recompiles, every metric series restarts.

With ``hot_reload_diff on`` the CLI calls :func:`reload_from_file`
first: parse the (already validated) config file, diff the declared
input/filter/output/parser sections against the RUNNING pipeline, and
stage exactly the delta on a :class:`~.qos.ReloadTxn` — the same
generation-swap transaction the admin API uses, so in-flight chunks
are never dropped and untouched instances keep their state (tail
offsets, retry timers, breaker history). An empty diff commits
nothing. Anything the transaction model cannot express — service-key
edits, custom plugins, stream tasks, YAML per-instance processors —
raises :class:`ReloadDiffUnsupported` and the CLI falls back to the
full-restart path, which handles everything.

Matching model:

- **inputs / outputs** are unordered multisets keyed on
  ``(plugin, normalized property items)``: an instance stays iff an
  identical declaration is still present; otherwise it is removed and
  the new declarations are added. (A property EDIT is remove+add —
  instance property mutation mid-flight is not part of the
  transaction model.)
- **filters** are an ordered chain. When the declared plugin sequence
  equals the running one, changed positions become
  ``replace_filter_items`` (the twin keeps the old name, metrics
  series and chain slot — the DFA-recompile shape). Any structural
  change (insert/delete/reorder) degrades to remove-all + add-all,
  which still preserves in-flight chunks but renumbers instances.
- **parsers** are add-only: a [PARSER] section whose name is unknown
  (or whose definition changed) is (re)declared; parsers absent from
  the file are left alone — they may come from ``parsers_file``
  includes the main file does not show.

Locking: everything here runs on the CLI reload thread with NO engine
lock held; ``ReloadTxn.commit`` takes ``_reload_lock`` then
``_ingest_lock`` itself (the canonical order fbtpu-locksmith pins).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

log = logging.getLogger("flb.reload_diff")

__all__ = ["ReloadDiffUnsupported", "reload_from_file"]


class ReloadDiffUnsupported(ValueError):
    """The edit cannot be expressed as a ReloadTxn delta — the caller
    must fall back to the full stop/start reload."""


def _norm_items(items) -> Tuple[Tuple[str, str], ...]:
    """Normalized property identity: lowercase keys, stringified
    values, declaration order preserved (repeated keys are semantic —
    grep Regex rules, tail Path globs)."""
    return tuple((str(k).lower(), str(v)) for k, v in items)


def _split_section(sec) -> Tuple[str, List[Tuple[str, str]]]:
    """(plugin name, remaining items) from a [INPUT]/[FILTER]/[OUTPUT]
    section; the Name key is the plugin, everything else is props."""
    name = None
    rest: List[Tuple[str, str]] = []
    for k, v in sec.properties:
        if str(k).lower() == "name":
            name = str(v)
        else:
            rest.append((k, v))
    if name is None:
        raise ReloadDiffUnsupported(
            f"[{sec.name}] section without Name")
    return name, rest


def _desired(cf) -> Dict[str, list]:
    """Per-kind desired declarations from a parsed ConfigFile;
    raises ReloadDiffUnsupported on sections the transaction model
    cannot stage."""
    out: Dict[str, list] = {"input": [], "filter": [], "output": [],
                            "parser": []}
    for sec in cf.sections:
        if sec.name == "service":
            continue  # see reload_from_file's service check
        if sec.name in ("parser", "multiline_parser"):
            if sec.name == "multiline_parser":
                raise ReloadDiffUnsupported(
                    "multiline parser sections need a restart")
            pname = sec.get("name")
            if not pname:
                raise ReloadDiffUnsupported("[PARSER] without Name")
            props = [(k, v) for k, v in sec.properties
                     if str(k).lower() != "name"]
            out["parser"].append((str(pname), props))
            continue
        if sec.name in ("custom", "stream_task", "plugins"):
            raise ReloadDiffUnsupported(
                f"[{sec.name}] sections need a restart")
        if sec.name not in ("input", "filter", "output"):
            raise ReloadDiffUnsupported(
                f"unknown config section [{sec.name}]")
        if sec.processors:
            raise ReloadDiffUnsupported(
                "per-instance processors need a restart")
        plugin, items = _split_section(sec)
        out[sec.name].append((plugin, items))
    return out


def _running(engine) -> Dict[str, list]:
    """The live pipeline's user-declared instances (hidden emitters
    and flux-SQL stand-ins are engine-internal — never diffed)."""
    return {
        "input": [i for i in engine.inputs
                  if getattr(i, "_hidden_owner", None) is None],
        "filter": [f for f in engine.filters
                   if not getattr(f, "_flux_sql_hidden", False)],
        "output": list(engine.outputs),
    }


def _ins_key(ins) -> Tuple[str, tuple]:
    return (ins.plugin.name, _norm_items(ins.properties.items()))


def _decl_key(decl) -> Tuple[str, tuple]:
    plugin, items = decl
    return (plugin, _norm_items(items))


def _diff_multiset(running, desired):
    """Greedy multiset match on (plugin, normalized items): returns
    (instances to remove, declarations to add)."""
    unmatched = list(desired)
    keep_keys = [_decl_key(d) for d in unmatched]
    removed = []
    for ins in running:
        k = _ins_key(ins)
        if k in keep_keys:
            keep_keys.remove(k)  # one declaration per instance
            unmatched.pop(next(
                i for i, d in enumerate(unmatched) if _decl_key(d) == k))
        else:
            removed.append(ins)
    return removed, unmatched


def reload_from_file(engine, path: str,
                     env: Optional[Dict[str, str]] = None):
    """Diff ``path`` against the running pipeline and commit the delta
    through one ReloadTxn generation swap.

    Returns ``(generation, summary)`` — generation is ``None`` when the
    file matches the running pipeline (nothing committed). Raises
    :class:`ReloadDiffUnsupported` when the edit needs the restart
    path, and propagates ReloadTxn build/commit errors (the old
    generation stays live either way).
    """
    from ..config_format import load_config_file
    from .qos import ReloadTxn

    cf = load_config_file(path, env=dict(env or {}))
    # the [SERVICE] section is deliberately IGNORED here: flush
    # timers, storage and the HTTP server are wired at start and the
    # transaction model cannot re-apply them — service edits take
    # effect on the next full restart. parsers_file/streams_file
    # includes were applied at startup and stay applied.

    want = _desired(cf)
    have = _running(engine)

    txn = ReloadTxn(engine)
    summary = {"add_inputs": 0, "rm_inputs": 0, "add_outputs": 0,
               "rm_outputs": 0, "add_filters": 0, "rm_filters": 0,
               "replace_filters": 0, "add_parsers": 0}

    for kind, add_items, rm in (
            ("input", txn.add_input_items, txn.remove_input),
            ("output", txn.add_output_items, txn.remove_output)):
        removed, added = _diff_multiset(have[kind], want[kind])
        for ins in removed:
            rm(ins.name)
            summary[f"rm_{kind}s"] += 1
        for plugin, items in added:
            add_items(plugin, items)
            summary[f"add_{kind}s"] += 1

    # filters: positional replace when the plugin chain is unchanged
    run_f = have["filter"]
    want_f = want["filter"]
    if [f.plugin.name for f in run_f] == [p for p, _ in want_f]:
        for ins, (plugin, items) in zip(run_f, want_f):
            if _norm_items(ins.properties.items()) != _norm_items(items):
                txn.replace_filter_items(ins.name, items)
                summary["replace_filters"] += 1
    else:
        for ins in run_f:
            txn.remove_filter(ins.name)
            summary["rm_filters"] += 1
        for plugin, items in want_f:
            txn.add_filter_items(plugin, items)
            summary["add_filters"] += 1

    # parsers: add-only (absent parsers may come from parsers_file)
    from ..parsers import create_parser

    for pname, props in want["parser"]:
        existing = engine.parsers.get(pname)
        fresh = create_parser(pname, **dict(props))
        if existing is not None and _parser_equal(existing, fresh):
            continue
        txn.add_parser(pname, **dict(props))
        summary["add_parsers"] += 1

    if not any(summary.values()):
        log.info("reload diff: configuration unchanged, nothing to do")
        return None, summary

    gen = txn.commit()
    log.info("reload diff committed generation %d: %s", gen,
             ", ".join(f"{k}={v}" for k, v in summary.items() if v))
    return gen, summary


def _parser_equal(a, b) -> bool:
    """Same parser definition? Compared on the public attribute dict
    with compiled regexes reduced to their source pattern (FlbRegex
    carries no __eq__); unknown shapes compare unequal so a changed
    definition is re-declared rather than skipped."""

    def fingerprint(p):
        d = {}
        for k, v in vars(p).items():
            if k.startswith("_"):
                continue
            if hasattr(v, "pattern"):
                v = ("regex", v.pattern, getattr(v, "ignorecase", False))
            d[k] = v
        return d

    try:
        return fingerprint(a) == fingerprint(b)
    except Exception:
        return False
