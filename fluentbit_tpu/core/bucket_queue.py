"""Priority bucket queue + engine event priorities.

Reference: include/fluent-bit/flb_bucket_queue.h (N FIFO buckets, min
priority served first) and flb_engine_macros.h:60-79 — 8 priorities,
scheduler/timers/shutdown at the top (0), network at 1, flush at 2.
The engine enqueues its ready callbacks here and drains in priority
order, so a retry timer firing during a flush burst jumps the line the
same way the reference's bucket queue serves FLB_ENGINE_PRIORITY_CB_SCHED
events before FLB_ENGINE_PRIORITY_FLUSH ones.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Iterator, List

PRIORITY_COUNT = 8
PRIORITY_TOP = 0                      # scheduler / timers / shutdown
PRIORITY_NETWORK = 1
PRIORITY_FLUSH = PRIORITY_NETWORK + 1
PRIORITY_DEFAULT = PRIORITY_COUNT - 1


class BucketQueue:
    """N FIFO buckets; pop() serves the lowest-numbered non-empty
    bucket (flb_bucket_queue_add/pop_min)."""

    __slots__ = ("_buckets", "_size")

    def __init__(self, priorities: int = PRIORITY_COUNT):
        self._buckets: List[deque] = [deque() for _ in range(priorities)]
        self._size = 0

    def add(self, priority: int, item: Any) -> None:
        if priority < 0:
            priority = 0
        elif priority >= len(self._buckets):
            priority = len(self._buckets) - 1
        self._buckets[priority].append(item)
        self._size += 1

    def pop(self) -> Any:
        for bucket in self._buckets:
            if bucket:
                self._size -= 1
                return bucket.popleft()
        raise IndexError("pop from empty BucketQueue")

    def drain(self) -> Iterator[Any]:
        while self._size:
            yield self.pop()

    def __len__(self) -> int:
        return self._size

    def __bool__(self) -> bool:
        return self._size > 0
