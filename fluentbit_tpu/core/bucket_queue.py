"""Priority bucket queue + engine event priorities + the
deficit-weighted-round-robin fair layer (fbtpu-qos).

Reference: include/fluent-bit/flb_bucket_queue.h (N FIFO buckets, min
priority served first) and flb_engine_macros.h:60-79 — 8 priorities,
scheduler/timers/shutdown at the top (0), network at 1, flush at 2.
The engine enqueues its ready callbacks here and drains in priority
order, so a retry timer firing during a flush burst jumps the line the
same way the reference's bucket queue serves FLB_ENGINE_PRIORITY_CB_SCHED
events before FLB_ENGINE_PRIORITY_FLUSH ones.

:class:`DeficitFairQueue` extends the same priority-bucket shape with a
per-bucket DWRR ring over tenant flows (Shreedhar & Varghese DRR):
strict priority across classes, weighted fairness within a class. The
engine's chunk dispatch drains through it (core/qos.py) so a flooding
tenant saturates only its own weight share of dispatch slots. The
reference has no equivalent — flb_engine_dispatch walks inputs in
configuration order, which is exactly the starvation fbtpu-qos removes.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from typing import Any, Iterator, List, Optional, Tuple

PRIORITY_COUNT = 8
PRIORITY_TOP = 0                      # scheduler / timers / shutdown
PRIORITY_NETWORK = 1
PRIORITY_FLUSH = PRIORITY_NETWORK + 1
PRIORITY_DEFAULT = PRIORITY_COUNT - 1

#: QoS priority classes (0 = highest). Same width as the engine's
#: event priorities so one mental model covers both; the default class
#: a tenant lands in is configuration (`qos.default_priority`).
QOS_CLASS_COUNT = PRIORITY_COUNT


class BucketQueue:
    """N FIFO buckets; pop() serves the lowest-numbered non-empty
    bucket (flb_bucket_queue_add/pop_min)."""

    __slots__ = ("_buckets", "_size")

    def __init__(self, priorities: int = PRIORITY_COUNT):
        self._buckets: List[deque] = [deque() for _ in range(priorities)]
        self._size = 0

    def add(self, priority: int, item: Any) -> None:
        if priority < 0:
            priority = 0
        elif priority >= len(self._buckets):
            priority = len(self._buckets) - 1
        self._buckets[priority].append(item)
        self._size += 1

    def pop(self) -> Any:
        for bucket in self._buckets:
            if bucket:
                self._size -= 1
                return bucket.popleft()
        raise IndexError("pop from empty BucketQueue")

    def drain(self) -> Iterator[Any]:
        while self._size:
            yield self.pop()

    def __len__(self) -> int:
        return self._size

    def __bool__(self) -> bool:
        return self._size > 0


class _Flow:
    """One tenant's FIFO within a priority bucket + its DWRR state."""

    __slots__ = ("name", "weight", "deficit", "items", "cost")

    def __init__(self, name: str, weight: float):
        self.name = name
        self.weight = weight
        self.deficit = 0.0
        self.items: deque = deque()  # (cost, item)
        self.cost = 0.0              # queued bytes (gauge feed)


class DeficitFairQueue:
    """Deficit-weighted round-robin over per-tenant flows inside
    priority buckets.

    - **strict priority across classes**: :meth:`pop` always serves the
      lowest-numbered non-empty class; a class drains completely before
      the next is touched (the shed-by-priority contract's dispatch
      twin).
    - **DWRR within a class**: each backlogged flow accumulates
      ``quantum × weight`` deficit per round-robin visit and may send
      while its head cost fits the deficit. Standard DRR bound: over
      any backlogged window of R rounds a flow sends at most
      ``R·quantum·weight + max_cost`` — never more than one max-cost
      item over its weight share per round (pinned by the property
      test in tests/test_qos.py).
    - **starvation floor**: effective weight is
      ``max(weight, weight_floor)``, so a zero-weight tenant still
      accumulates deficit and drains at the floor rate instead of
      starving forever.

    Deficits persist while a flow is backlogged and reset when it goes
    idle (DRR's anti-burst rule: an idle flow cannot bank credit).
    Not thread-safe — the owner (core/qos.py) serializes access.
    """

    def __init__(self, quantum: float, weight_floor: float = 0.05,
                 classes: int = QOS_CLASS_COUNT):
        # every chunk costs >= 1, so a non-positive quantum would add
        # zero deficit per visit and spin pop_ex forever
        self.quantum = max(1.0, float(quantum))
        self.weight_floor = max(1e-6, float(weight_floor))
        self.classes = classes
        # class → OrderedDict[name, _Flow]: the OrderedDict IS the
        # round-robin ring (popped flows re-append on re-arrival)
        self._rings: List["OrderedDict[str, _Flow]"] = [
            OrderedDict() for _ in range(classes)
        ]
        # per-class: has the ring's HEAD flow received its one
        # per-visit quantum grant yet? (DRR grants once per visit; a
        # flow serves until its deficit runs dry, then the pointer
        # advances — without this flag a flow whose quantum covers its
        # head cost would re-grant itself forever and monopolize)
        self._granted: List[bool] = [False] * classes
        self._size = 0

    def _clamp(self, cls: int) -> int:
        return min(max(int(cls), 0), self.classes - 1)

    def push(self, cls: int, tenant: str, weight: float, cost: float,
             item: Any) -> None:
        ring = self._rings[self._clamp(cls)]
        flow = ring.get(tenant)
        if flow is None:
            flow = _Flow(tenant, weight)
            ring[tenant] = flow
        flow.weight = weight  # weights may be re-declared live (reload)
        flow.items.append((max(0.0, float(cost)), item))
        flow.cost += max(0.0, float(cost))
        self._size += 1

    def pop(self) -> Optional[Any]:
        """Serve one item in strict-priority + DWRR order; None when
        empty."""
        got = self.pop_ex()
        return got[1] if got is not None else None

    def pop_ex(self) -> Optional[Tuple[str, Any]]:
        """:meth:`pop` + the serving tenant name (metrics feed)."""
        for cls, ring in enumerate(self._rings):
            if not ring:
                continue
            # starvation-free: every visit adds quantum·max(weight,
            # floor) > 0 deficit, so any head item is eventually
            # affordable after finitely many rotations
            while True:
                name, flow = next(iter(ring.items()))
                if not self._granted[cls]:
                    # arrival at this flow: its one per-visit grant
                    flow.deficit += self.quantum * max(flow.weight,
                                                       self.weight_floor)
                    self._granted[cls] = True
                cost, item = flow.items[0]
                if flow.deficit < cost:
                    # deficit exhausted for this visit: the pointer
                    # advances; the flow carries its remaining deficit
                    # into the next round
                    ring.move_to_end(name)
                    self._granted[cls] = False
                    continue
                flow.items.popleft()
                flow.deficit -= cost
                flow.cost -= cost
                self._size -= 1
                if not flow.items:
                    # idle flows bank no credit (DRR's anti-burst rule)
                    flow.deficit = 0.0
                    del ring[name]
                    self._granted[cls] = False
                return (name, item)
        return None

    def drain(self) -> List[Any]:
        """Take everything in priority+fair order (task-map-full
        parking, shutdown readmission)."""
        out = []
        while True:
            got = self.pop()
            if got is None:
                return out
            out.append(got)

    def pending(self) -> "OrderedDict[Tuple[int, str], Tuple[int, float]]":
        """(class, tenant) → (queued items, queued cost) snapshot."""
        out: "OrderedDict[Tuple[int, str], Tuple[int, float]]" = \
            OrderedDict()
        for cls, ring in enumerate(self._rings):
            for name, flow in ring.items():
                out[(cls, name)] = (len(flow.items), flow.cost)
        return out

    def __len__(self) -> int:
        return self._size

    def __bool__(self) -> bool:
        return self._size > 0
