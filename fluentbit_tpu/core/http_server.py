"""Admin HTTP server — health, metrics, uptime, reload.

Reference: src/http_server (flb_hs.c + api/v1: health.c, metrics.c,
uptime.c, plugins, storage; api/v2: reload.c). Runs on the engine's
asyncio loop when ``[SERVICE] http_server on`` (started from
flb_engine_start in the reference, src/flb_engine.c:1074-1080).

Endpoints:
  GET  /                       banner (name/version)
  GET  /api/v1/health          readiness verdict (fbtpu-guard,
                               core/guard.py). Healthy → 200 "ok"
                               (text, reference-compatible). Otherwise
                               a JSON body {"status": ..., "breakers":
                               {output: closed|half-open|open}, ...}:
                               - "degraded" (200): some breaker is not
                                 closed, chunks are shed, or the task
                                 map is past the shed watermark —
                                 healthy routes still flow;
                               - "stalled" (503): the housekeeping
                                 heartbeat is older than
                                 guard.stall_after — the engine loop
                                 is wedged or starved, readiness
                                 checks should fail the instance.
  GET  /api/v1/health/guard    the same verdict, always as JSON (for
                               dashboards that want breaker state while
                               the verdict is still "ok")
  GET  /api/v1/qos             fbtpu-qos per-tenant state (QOS.md):
                               reload generation + each tenant's
                               weight/priority/quota, admission
                               counters and fair-queue depth (the same
                               block rides /api/v1/health's JSON body)
  GET  /api/v1/metrics         internal metrics as JSON
  GET  /api/v1/metrics/prometheus   Prometheus text exposition
  GET  /api/v1/uptime          uptime seconds
  GET  /api/v1/plugins         configured plugin instances
  GET  /api/v1/storage         chunk storage overview
  GET  /api/v2/reload          {"hot_reload_count": N}
  POST /api/v2/reload          trigger hot reload (requires the host
                               process to wire engine.reload_callback,
                               e.g. the CLI's SIGHUP path)
  GET    /api/v1/failpoints          armed failpoints + trigger counts
  POST   /api/v1/failpoints/<name>   arm ({"spec": "..."} or raw spec)
  DELETE /api/v1/failpoints[/<name>] disarm one / all (FAULTS.md)
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Optional

from ..plugins.net_http import http_response, read_http_request
from .upstream import close_quietly

log = logging.getLogger("flb.http_server")


def _version() -> str:
    from .. import __version__

    return __version__


class AdminServer:
    def __init__(self, engine, listen: str = "0.0.0.0", port: int = 2020):
        self.engine = engine
        self.listen = listen
        self.port = port
        self.bound_port: Optional[int] = None

    async def serve(self) -> None:
        try:
            server = await asyncio.start_server(self._handle, self.listen,
                                                self.port)
        except OSError as e:
            # surface bind failures immediately — a silent task death
            # leaves health checks failing while the engine looks fine
            log.error("admin server cannot listen on %s:%s: %s",
                      self.listen, self.port, e)
            return
        self.bound_port = server.sockets[0].getsockname()[1]
        async with server:
            await server.serve_forever()

    async def _handle(self, reader, writer) -> None:
        try:
            while True:
                req = await read_http_request(reader)
                if req is None:
                    break
                method, uri, headers, req_body = req
                status, body, ctype = self._route(
                    method, uri.split("?")[0], req_body
                )
                writer.write(http_response(status, body, ctype))
                await writer.drain()
                if headers.get("connection", "").lower() == "close":
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            close_quietly(writer)

    def _route(self, method: str, path: str, req_body: bytes = b""):
        e = self.engine
        if path.startswith("/api/v1/trace"):
            return self._route_trace(method, path, req_body)
        if path.startswith("/api/v1/failpoints"):
            return self._route_failpoints(method, path, req_body)
        if path == "/":
            return 200, json.dumps(
                {"fluentbit_tpu": {"version": _version(),
                                   "edition": "tpu-native"}}
            ).encode(), "application/json"
        if path == "/api/v1/health":
            h = e.guard.health()
            if h["status"] == "ok":
                return 200, b"ok\n", "text/plain"
            code = 503 if h["status"] == "stalled" else 200
            return code, json.dumps(h).encode(), "application/json"
        if path == "/api/v1/health/guard":
            return 200, json.dumps(e.guard.health()).encode(), \
                "application/json"
        if path == "/api/v1/qos":
            return 200, json.dumps(e.qos.snapshot()).encode(), \
                "application/json"
        if path == "/api/v1/metrics/prometheus":
            return 200, e.metrics.to_prometheus().encode(), \
                "text/plain; version=0.0.4"
        if path == "/api/v1/metrics":
            return 200, json.dumps(e.metrics.to_msgpack_obj(),
                                   default=str).encode(), "application/json"
        if path == "/api/v1/uptime":
            up = time.time() - e.started_at if e.started_at else 0.0
            return 200, json.dumps(
                {"uptime_sec": int(up),
                 "uptime_hr": f"up {int(up) // 86400}d {int(up) % 86400 // 3600}h"
                              f" {int(up) % 3600 // 60}m {int(up) % 60}s"}
            ).encode(), "application/json"
        if path == "/api/v1/plugins":
            return 200, json.dumps({
                "inputs": [i.display_name for i in e.inputs],
                "filters": [f.display_name for f in e.filters],
                "outputs": [o.display_name for o in e.outputs],
            }).encode(), "application/json"
        if path == "/api/v1/storage":
            layer = {"chunks": {
                "total_chunks": sum(i.pool.pending_chunks for i in e.inputs),
                "mem_chunks": sum(i.pool.pending_chunks for i in e.inputs
                                  if i.storage_type == "memory"),
                "fs_chunks": sum(i.pool.pending_chunks for i in e.inputs
                                 if i.storage_type == "filesystem"),
            }}
            return 200, json.dumps({"storage_layer": layer}).encode(), \
                "application/json"
        if path == "/api/v2/reload":
            if method == "POST":
                cb = getattr(e, "reload_callback", None)
                if cb is None:
                    return 400, b'{"error": "hot reload not enabled"}\n', \
                        "application/json"
                try:
                    cb()
                except Exception:
                    log.exception("reload callback failed")
                    return 500, b"", "application/json"
                return 200, b'{"reload": "in progress"}\n', "application/json"
            return 200, json.dumps(
                {"hot_reload_count": e.reload_count}
            ).encode(), "application/json"
        return 404, b"not found\n", "text/plain"

    def _route_failpoints(self, method: str, path: str, req_body: bytes):
        """Fault-injection control (mirrors the chunk-trace tap):
        GET /api/v1/failpoints — armed sites + counters;
        POST /api/v1/failpoints/<name> — arm with the body's spec
        ({"spec": "..."} JSON or a raw DSL string);
        DELETE /api/v1/failpoints[/<name>] — disarm one or all."""
        from .. import failpoints as fp

        parts = [p for p in path.split("/") if p]
        name = parts[3] if len(parts) > 3 else None
        if method == "GET":
            return 200, json.dumps({
                "failpoints": fp.snapshot(),
                "sites": list(fp.SITES),
                "http_control": fp.http_control_enabled(),
            }).encode(), "application/json"
        if not fp.http_control_enabled():
            # the admin port doubles as the metrics endpoint and often
            # listens on 0.0.0.0 — arming faults (crash = SIGKILL) over
            # it requires the launch-time opt-in
            return 403, (b'{"error": "failpoint mutation disabled; '
                         b'launch with FBTPU_FAILPOINTS_HTTP=1"}\n'), \
                "application/json"
        if method == "POST":
            if name is None:
                return 400, b'{"error": "failpoint name required"}\n', \
                    "application/json"
            spec = req_body.decode("utf-8", "replace").strip()
            try:
                obj = json.loads(spec)
                if isinstance(obj, dict):
                    spec = str(obj.get("spec", ""))
            except ValueError:
                pass  # raw DSL body
            try:
                fp.enable(name, spec)
            except ValueError as e:
                return 400, json.dumps({"error": str(e)}).encode(), \
                    "application/json"
            return 200, b'{"status": "ok"}\n', "application/json"
        if method == "DELETE":
            if name is None:
                fp.reset()
                return 200, b'{"status": "ok"}\n', "application/json"
            if fp.disable(name):
                return 200, b'{"status": "ok"}\n', "application/json"
            return 404, b'{"error": "not armed"}\n', "application/json"
        return 400, b"", "application/json"

    def _route_trace(self, method: str, path: str, req_body: bytes):
        """Chunk-trace control (src/http_server/api/v1/trace.c):
        GET /api/v1/trace — active taps; POST/DELETE
        /api/v1/trace/<input> — enable/disable."""
        e = self.engine
        parts = [p for p in path.split("/") if p]
        if len(parts) == 3:  # /api/v1/trace
            if method == "GET":
                return 200, json.dumps({
                    "inputs": {
                        name: {"output_tag": ctx["output_tag"],
                               "chunks": ctx["count"]}
                        for name, ctx in e.traces.items()
                    }
                }).encode(), "application/json"
            return 400, b'{"error": "input name required"}\n', \
                "application/json"
        input_name = parts[3]
        if method == "POST":
            output_tag = "trace"
            if req_body:
                try:
                    obj = json.loads(req_body)
                    if isinstance(obj, dict):
                        output_tag = obj.get("output_tag", "trace")
                except ValueError:
                    pass
            if e.enable_trace(input_name, output_tag):
                return 200, b'{"status": "ok"}\n', "application/json"
            return 404, b'{"error": "unknown input"}\n', "application/json"
        if method == "DELETE":
            if e.disable_trace(input_name):
                return 200, b'{"status": "ok"}\n', "application/json"
            return 404, b'{"error": "no trace active"}\n', "application/json"
        return 400, b"", "application/json"
