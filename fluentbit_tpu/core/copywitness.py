"""fbtpu-memscope ground truth: the host-copy witness recorder.

The static copy census (analysis/memscope.py) is a model of where the
ingest path materializes bytes; this module keeps it honest the same
way core/lockorder.py keeps the lock-order graph honest. Every
instrumented materialization site on the ingest→staging path calls
:func:`count` with its canonical site id and the byte count. In normal
operation that is a single falsy-global check — nothing recorded. With
``FBTPU_COPY_WITNESS`` set in the environment at import/enable time,
each call accumulates (events, bytes) per site into a process-global
table.

The tier-1 crosscheck (tests/test_memscope.py) drives representative
ingest workloads under the witness and asserts **static ⊇ dynamic**:
every site the process actually exercised exists in the committed
census (analysis/copy_budget.json), and each site's observed
bytes-copied-per-ingested-byte does not exceed the census's claimed
multiplicity. A dynamic site missing from the static census means the
analyzer's walk lost a copy — the test fails loudly instead of the
model silently rotting.

Site ids are the census's canonical node ids
(``engine.decoded.materialize``, ``storage.replay.materialize`` …) —
the two sides join on these strings, so adding a materialization to
the ingest path means adding both the :func:`count` call and the
census site in the same PR (the crosscheck catches a drift).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Tuple

__all__ = ["count", "witness_enabled", "witness_counts",
           "witness_reset", "refresh"]

#: site id -> (events, bytes) accumulated since the last reset.
_counts: Dict[str, Tuple[int, int]] = {}
_counts_guard = threading.Lock()

# read once and cached in a module global so the hot-path cost of a
# disabled witness is one falsy load; tests flip it via refresh()
_enabled = bool(os.environ.get("FBTPU_COPY_WITNESS"))


def refresh() -> None:
    """Re-read ``FBTPU_COPY_WITNESS`` (tests set the env after import)."""
    global _enabled
    with _counts_guard:
        _enabled = bool(os.environ.get("FBTPU_COPY_WITNESS"))


def witness_enabled() -> bool:
    return _enabled


def count(site: str, nbytes: int) -> None:
    """Record one materialization event at ``site`` (no-op unless the
    witness is enabled)."""
    if not _enabled:
        return
    with _counts_guard:
        ev, by = _counts.get(site, (0, 0))
        _counts[site] = (ev + 1, by + int(nbytes))


def witness_counts() -> Dict[str, Tuple[int, int]]:
    """Snapshot of site -> (events, bytes) since the last reset."""
    with _counts_guard:
        return dict(_counts)


def witness_reset() -> None:
    with _counts_guard:
        _counts.clear()
