"""Batched filter dispatch support — the chunk view filters see on the
raw fast path, plus the double-buffered staging pipeline.

``RawChunk`` wraps one append's encoded bytes as they move through a
chain of batch-capable filters (``FilterPlugin.process_batch``): the
record count one stage discovers travels to the next as its walk hint
(skipping the counting pre-pass), and ``src`` carries the appending
input instance so filters with a hidden emitter (rewrite_tag) can
recognise their own re-entered records without touching the
engine-global ``_ingest_src`` (which the parallel raw path must not
share across inputs).

``double_buffered`` is the depth-2 dispatch pipeline of the engine's
batched filter path: host msgpack extraction (staging) of segment N+1
overlaps the in-flight device kernel of segment N, and each result is
forced one segment behind its dispatch. On a real accelerator the
overlap hides the host staging walk behind the DFA scan; on the CPU
backend it degrades to the sequential order at no extra cost.

The hook contract (machine-checked by fbtpu-lint's batch-exactness
pack, ``fluentbit_tpu.analysis.batch`` — see ANALYSIS.md):

- ``None`` (or any raise) from ``process_batch`` DECLINES the chunk:
  the engine re-runs the chain per-record from this filter onward, so
  a decline must be dominated by ZERO committed side effects (counter
  incs, emitter appends, tag rewrites) — commit last, or guard the
  committing call and succeed;
- a hook that commits side effects declares ``stateful_batch = True``
  on its class, which switches a downstream decline from a full-chain
  restart to the decoded-tail continuation;
- span-gather re-emits preserve FIRST-SEEN record order (the
  per-record path's pending-dict insertion order): group by first
  contributing record index, never iterate a set.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

__all__ = ["RawChunk", "double_buffered", "segment_bounds"]


class RawChunk:
    """One append's raw chunk bytes on the batched filter chain.

    data    : bytes (memoryviews from a previous filter's arena are
              materialized on first use)
    tag     : the append's routing tag
    n       : record count, or None until a stage discovers it
    src     : the appending InputInstance (emitter re-entry guard)
    engine  : the owning engine (metrics, emitter access)
    """

    __slots__ = ("data", "tag", "n", "src", "engine")

    def __init__(self, data, tag: str, n: Optional[int] = None,
                 src=None, engine=None):
        self.data = data
        self.tag = tag
        self.n = n
        self.src = src
        self.engine = engine

    def replace(self, data, n: Optional[int]) -> None:
        """Swap in a filter's output (count may be unknown again)."""
        self.data = data
        self.n = n

    def as_bytes(self) -> bytes:
        """The chunk as ``bytes`` (ctypes-callable); materializes a
        previous stage's arena view exactly once."""
        if not isinstance(self.data, bytes):
            self.data = bytes(self.data)
        return self.data


def segment_bounds(n: int, seg_records: int) -> List[tuple]:
    """Split ``n`` records into [start, end) segments of at most
    ``seg_records`` (the double-buffer grain)."""
    if seg_records <= 0 or n <= seg_records:
        return [(0, n)]
    return [(s, min(s + seg_records, n))
            for s in range(0, n, seg_records)]


def double_buffered(stage_iter: Iterable[Any],
                    dispatch: Callable[[Any], Any],
                    collect: Optional[Callable[[Any], Any]] = None,
                    depth: int = 2) -> List[Any]:
    """Staging/kernel pipeline with ``depth`` segments in flight
    (default 2 — the classic double buffer).

    ``stage_iter`` performs the host-side extraction work lazily (each
    ``__next__`` stages one segment); ``dispatch`` launches the device
    kernel for a staged segment and must return without forcing the
    result (jax dispatch is asynchronous); ``collect`` forces a
    dispatched result (default ``np.asarray``). The loop dispatches
    segment i, stages segment i+1 while i's kernel is in flight, then
    forces the oldest in-flight segment once ``depth`` are alive — so
    host extraction and device execution overlap with at most ``depth``
    segments live. The mesh path runs depth 2 per *sharded* launch
    (one launch already spans every device); deeper pipelines serve
    backends whose dispatch queue rewards more in-flight work.
    """
    from collections import deque

    import numpy as np

    if collect is None:
        collect = np.asarray
    if depth < 2:
        depth = 2
    out: List[Any] = []
    pending: deque = deque()
    for staged in stage_iter:
        pending.append(dispatch(staged))
        if len(pending) >= depth:
            out.append(collect(pending.popleft()))
    while pending:
        out.append(collect(pending.popleft()))
    return out
