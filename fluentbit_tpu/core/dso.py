"""Dynamic (.so) plugin loading — the flb_plugin.c role.

Reference: src/flb_plugin.c:200-326 — ``flb_plugin_load`` dlopens a
shared object, derives the registration symbol from the file name, and
links the plugin struct into the registry; exposed via the CLI ``-e``
flag and ``[PLUGINS]``/plugins-file config. The same contract here:
``load_dso_plugin(path)`` loads a C ABI object (``native/
fbtpu_plugin.h``), wraps its vtable in an InputPlugin/OutputPlugin
subclass, and registers it under the struct's name. The reference
proves native-language plugins with its Zig demo (lib/zig_fluent_bit);
this build's proof is ``native/demo_plugins/`` built with g++ in the
runtime tests.
"""

from __future__ import annotations

import ctypes
import json
import logging
import os
from typing import Optional

log = logging.getLogger("flb.dso")

FBTPU_PLUGIN_ABI_VERSION = 1

_EMIT_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_char_p,
                            ctypes.c_char_p, ctypes.c_longlong)


class _OutputVtable(ctypes.Structure):
    _fields_ = [
        ("abi_version", ctypes.c_int),
        ("name", ctypes.c_char_p),
        ("description", ctypes.c_char_p),
        ("init", ctypes.CFUNCTYPE(ctypes.c_void_p, ctypes.c_char_p)),
        ("flush", ctypes.CFUNCTYPE(
            ctypes.c_int, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_ubyte), ctypes.c_longlong,
            ctypes.c_char_p)),
        ("destroy", ctypes.CFUNCTYPE(None, ctypes.c_void_p)),
    ]


class _InputVtable(ctypes.Structure):
    _fields_ = [
        ("abi_version", ctypes.c_int),
        ("name", ctypes.c_char_p),
        ("description", ctypes.c_char_p),
        ("collect_interval", ctypes.c_double),
        ("init", ctypes.CFUNCTYPE(ctypes.c_void_p, ctypes.c_char_p)),
        ("collect", ctypes.CFUNCTYPE(
            ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_char_p, _EMIT_FN)),
        ("destroy", ctypes.CFUNCTYPE(None, ctypes.c_void_p)),
    ]


def plugin_stem(path: str) -> str:
    """File name → registration stem (path_to_plugin_name role): strip
    directory, extension, and an optional flb- prefix."""
    base = os.path.basename(path)
    stem = base.split(".", 1)[0]
    if stem.startswith("flb-"):
        stem = stem[len("flb-"):]
    return stem


def elf_has_export(path: str, names) -> Optional[bool]:
    """Probe the ELF dynamic symbol table for any of ``names`` WITHOUT
    loading the object — dlopen runs static initializers/constructors,
    and the 'rejected objects must never be mapped' invariant says a
    malformed plugin's code must never execute. Returns True/False, or
    None when the file is not parseable as ELF (non-ELF platforms fall
    back to dlopen-and-check)."""
    import struct as _s

    want = {n.encode() if isinstance(n, str) else n for n in names}
    try:
        with open(path, "rb") as f:
            ident = f.read(16)
            if len(ident) < 16 or ident[:4] != b"\x7fELF":
                return None
            is64 = ident[4] == 2
            end = "<" if ident[5] == 1 else ">"
            if is64:
                f.seek(40)
                (shoff,) = _s.unpack(end + "Q", f.read(8))
                f.seek(58)
                shentsize, shnum = _s.unpack(end + "HH", f.read(4))
            else:
                f.seek(32)
                (shoff,) = _s.unpack(end + "I", f.read(4))
                f.seek(46)
                shentsize, shnum = _s.unpack(end + "HH", f.read(4))
            if not shoff or not shnum or shnum > 65535:
                return None
            sections = []
            for i in range(shnum):
                f.seek(shoff + i * shentsize)
                hdr = f.read(shentsize)
                if is64:
                    typ, = _s.unpack_from(end + "I", hdr, 4)
                    link, = _s.unpack_from(end + "I", hdr, 40)
                    off, size = _s.unpack_from(end + "QQ", hdr, 24)
                    entsize, = _s.unpack_from(end + "Q", hdr, 56)
                else:
                    typ, = _s.unpack_from(end + "I", hdr, 4)
                    off, size = _s.unpack_from(end + "II", hdr, 16)
                    link, = _s.unpack_from(end + "I", hdr, 24)
                    entsize, = _s.unpack_from(end + "I", hdr, 36)
                sections.append((typ, off, size, link, entsize))
            for typ, off, size, link, entsize in sections:
                if typ != 11:  # SHT_DYNSYM
                    continue
                if link >= len(sections) or not entsize:
                    return None
                _t, stroff, strsize, _l, _e = sections[link]
                f.seek(stroff)
                strtab = f.read(strsize)
                f.seek(off)
                syms = f.read(size)
                shndx_off = 6 if is64 else 14
                for so in range(0, len(syms) - entsize + 1, entsize):
                    (name_off,) = _s.unpack_from(end + "I", syms, so)
                    if not name_off or name_off >= len(strtab):
                        continue
                    # an UNDEFINED entry (st_shndx == SHN_UNDEF) is an
                    # import, not an export: an object that merely
                    # REFERENCES FLBPluginRegister must not pass
                    (shndx,) = _s.unpack_from(end + "H", syms,
                                              so + shndx_off)
                    if shndx == 0:
                        continue
                    nul = strtab.find(b"\x00", name_off)
                    if strtab[name_off:nul] in want:
                        return True
                return False
            return None  # stripped of dynsym: undecidable
    except (OSError, _s.error):
        return None


def _probe_exports(path: str, names, kind: str) -> None:
    """Reject (pre-dlopen) an object that exports none of ``names``."""
    if elf_has_export(path, names) is False:
        raise ValueError(
            f"cannot load {kind} {path!r}: registration structure is "
            f"missing ({' / '.join(sorted(str(n) for n in names))}) — "
            f"rejected before mapping; constructors never ran")


def _props_json(instance) -> bytes:
    props = {}
    for _lk, key, value in instance.properties._items:
        props[key] = value if isinstance(value, (str, int, float, bool)) \
            else str(value)
    return json.dumps(props).encode()


def load_dso_plugin(path: str, registry=None):
    """dlopen + register; returns the new plugin class. Raises
    ValueError on a malformed object (missing/unsupported symbol)."""
    from .plugin import InputPlugin, OutputPlugin
    from .plugin import registry as default_registry

    reg = registry if registry is not None else default_registry
    stem = plugin_stem(path)
    symbol = f"{stem}_plugin"
    if not stem.startswith(("in_", "out_")):
        # not the in-house vtable naming convention: it may still be a
        # Go-proxy-contract object, whose name comes from the plugin
        # itself (FLBPluginRegister), not the file
        return load_proxy_plugin(path, registry)
    # probe the export table BEFORE dlopen: a rejected object's static
    # initializers must never run (ADVICE.md: the invariant regressed
    # when the proxy fallback made every stem loadable)
    _probe_exports(path, {symbol, "FLBPluginRegister"}, "plugin")
    try:
        dso = ctypes.CDLL(os.path.abspath(path))
    except OSError as e:
        raise ValueError(f"cannot load plugin {path!r}: {e}") from e
    vt_cls = _OutputVtable if stem.startswith("out_") else _InputVtable
    try:
        vt = vt_cls.in_dll(dso, symbol)
    except ValueError as e:
        # in_/out_-named object without the vtable struct: fall back to
        # the proxy contract before rejecting (fluent-bit-go objects
        # are conventionally named out_*.so too)
        if hasattr(dso, "FLBPluginRegister"):
            return load_proxy_plugin(path, registry)
        raise ValueError(
            f"cannot load plugin {path!r}: registration structure "
            f"is missing {symbol!r}") from e
    if stem.startswith("out_"):
        return _register_output(reg, OutputPlugin, dso, vt, path)
    return _register_input(reg, InputPlugin, dso, vt, path)


def _check_abi(vt, path: str) -> str:
    if vt.abi_version != FBTPU_PLUGIN_ABI_VERSION:
        raise ValueError(
            f"plugin {path!r}: ABI version {vt.abi_version} "
            f"(host speaks {FBTPU_PLUGIN_ABI_VERSION})")
    name = (vt.name or b"").decode("utf-8", "replace")
    if not name:
        raise ValueError(f"plugin {path!r}: empty plugin name")
    return name


def _register_output(reg, OutputPlugin, dso, vt, path):
    from .plugin import FlushResult

    name = _check_abi(vt, path)

    class DsoOutput(OutputPlugin):
        description = (vt.description or b"").decode("utf-8", "replace")
        allow_unknown_properties = True  # props pass through as JSON
        _dso = dso  # keep the handle alive with the class
        _vt = vt

        def init(self, instance, engine) -> None:
            ctx = self._vt.init(_props_json(instance))
            if not ctx:
                raise RuntimeError(f"{self.name}: native init failed")
            self._ctx = ctypes.c_void_p(ctx)

        async def flush(self, data: bytes, tag: str, engine):
            buf = (ctypes.c_ubyte * len(data)).from_buffer_copy(data)
            rc = self._vt.flush(self._ctx, buf, len(data),
                                tag.encode("utf-8", "replace"))
            return {0: FlushResult.OK, 1: FlushResult.RETRY}.get(
                rc, FlushResult.ERROR)

        def exit(self) -> None:
            ctx = getattr(self, "_ctx", None)
            if ctx:
                self._vt.destroy(ctx)
                self._ctx = None

    DsoOutput.name = name
    DsoOutput.__name__ = f"Dso_{name}"
    reg.register(DsoOutput)
    log.info("dso: registered output plugin %r from %s", name, path)
    return DsoOutput


def _register_input(reg, InputPlugin, dso, vt, path):
    name = _check_abi(vt, path)
    interval = vt.collect_interval if vt.collect_interval > 0 else 1.0

    class DsoInput(InputPlugin):
        description = (vt.description or b"").decode("utf-8", "replace")
        allow_unknown_properties = True  # props pass through as JSON
        collect_interval = interval
        _dso = dso
        _vt = vt

        def init(self, instance, engine) -> None:
            ctx = self._vt.init(_props_json(instance))
            if not ctx:
                raise RuntimeError(f"{self.name}: native init failed")
            self._ctx = ctypes.c_void_p(ctx)

        def collect(self, engine) -> None:
            from ..codec.events import encode_event, now_event_time

            records = []

            def emit(_host, tag, json_text, length):
                # c_char_p already arrived as a NUL-bounded bytes
                # object; slicing by the advertised length stays
                # inside it even when the plugin lies about length
                try:
                    body = json.loads((json_text or b"")[:length])
                except (ValueError, TypeError):
                    return
                records.append((
                    (tag or b"").decode("utf-8", "replace"), body))

            cb = _EMIT_FN(emit)
            rc = self._vt.collect(
                self._ctx, None,
                (self.instance.tag or "").encode("utf-8", "replace"),
                cb)
            if rc < 0:
                log.warning("%s: native collect failed", self.name)
                return
            groups = {}
            for tag, body in records:
                tag = tag or self.instance.tag
                groups.setdefault(tag, []).append(
                    encode_event(body, now_event_time()))
            for tag, bufs in groups.items():
                engine.input_log_append(self.instance, tag,
                                        b"".join(bufs), len(bufs))

        def exit(self) -> None:
            ctx = getattr(self, "_ctx", None)
            if ctx:
                self._vt.destroy(ctx)
                self._ctx = None

    DsoInput.name = name
    DsoInput.__name__ = f"Dso_{name}"
    reg.register(DsoInput)
    log.info("dso: registered input plugin %r from %s", name, path)
    return DsoInput


# ---------------------------------------------------------------------
# Go-proxy-style foreign-runtime ABI (flb_plugin_proxy.c:347-433 +
# src/proxy/go/go.{c,h}): the HOST calls the object's exported
# ``FLBPluginRegister(def)``; the plugin fills the definition struct
# (type/name/description), then the host resolves the per-type callback
# set (FLBPluginInit / FLBPluginFlush[Ctx] / FLBPluginInputCallback /
# FLBPluginExit) and hands the plugin a callback TABLE (struct flb_api)
# through which it reads instance properties — the exact contract
# cgo-built fluent-bit-go plugins compile against.
# ---------------------------------------------------------------------

FLB_PROXY_INPUT_PLUGIN = 1
FLB_PROXY_OUTPUT_PLUGIN = 2

# fluent-bit-go return codes (output package)
_PROXY_FLB_ERROR = 0
_PROXY_FLB_OK = 1
_PROXY_FLB_RETRY = 2


class _ProxyDef(ctypes.Structure):
    """struct flb_plugin_proxy_def (flb_plugin_proxy.h:36-44)."""

    _fields_ = [
        ("type", ctypes.c_int),
        ("proxy", ctypes.c_int),
        ("flags", ctypes.c_int),
        ("name", ctypes.c_char_p),
        ("description", ctypes.c_char_p),
        ("event_type", ctypes.c_int),
    ]


# returns char* as c_void_p: a c_char_p restype would make ctypes
# convert a Python bytes temporarily (dangling pointer + the
# "memory leak in callback" warning); the address of a host-pinned
# buffer is stable until the next lookup for the same key
_GET_PROP_FN = ctypes.CFUNCTYPE(ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_void_p)
_LOG_CHECK_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                                 ctypes.c_int)


class _FlbApi(ctypes.Structure):
    """struct flb_api — field ORDER is the ABI. The layout follows
    include/fluent-bit/flb_api.h (NOT flb_api.c's assignment order):
    the header appends custom_get_property/custom_log_check at the END
    'to preserve ABI', so a cgo-built fluent-bit-go plugin compiled
    against the header indexes slots 2-6 as the cmt/log entries."""

    _fields_ = [
        ("output_get_property", _GET_PROP_FN),
        ("input_get_property", _GET_PROP_FN),
        ("output_get_cmt_instance",
         ctypes.CFUNCTYPE(ctypes.c_void_p, ctypes.c_void_p)),
        ("input_get_cmt_instance",
         ctypes.CFUNCTYPE(ctypes.c_void_p, ctypes.c_void_p)),
        ("log_print", ctypes.c_void_p),  # variadic: not bridged
        ("input_log_check", _LOG_CHECK_FN),
        ("output_log_check", _LOG_CHECK_FN),
        ("custom_get_property", _GET_PROP_FN),
        ("custom_log_check", _LOG_CHECK_FN),
    ]


class _GoOutputPlugin(ctypes.Structure):
    """struct flbgo_output_plugin (src/proxy/go/go.h:26-37)."""

    _fields_ = [
        ("name", ctypes.c_char_p),
        ("api", ctypes.POINTER(_FlbApi)),
        ("o_ins", ctypes.c_void_p),
        ("context", ctypes.c_void_p),
        ("cb_init", ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p)),
        ("cb_flush", ctypes.CFUNCTYPE(
            ctypes.c_int, ctypes.c_void_p, ctypes.c_size_t,
            ctypes.c_char_p)),
        ("cb_flush_ctx", ctypes.CFUNCTYPE(
            ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_size_t, ctypes.c_char_p)),
        ("cb_exit", ctypes.CFUNCTYPE(ctypes.c_int)),
        ("cb_exit_ctx", ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p)),
    ]


class _GoInputPlugin(ctypes.Structure):
    """struct flbgo_input_plugin (src/proxy/go/go.h:39-51)."""

    _fields_ = [
        ("name", ctypes.c_char_p),
        ("api", ctypes.POINTER(_FlbApi)),
        ("i_ins", ctypes.c_void_p),
        ("context", ctypes.c_void_p),
        ("cb_init", ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p)),
        ("cb_collect", ctypes.CFUNCTYPE(
            ctypes.c_int, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_size_t))),
        ("cb_collect_ctx", ctypes.CFUNCTYPE(
            ctypes.c_int, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_size_t))),
        ("cb_cleanup", ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p)),
        ("cb_cleanup_ctx", ctypes.CFUNCTYPE(
            ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p)),
        ("cb_exit", ctypes.CFUNCTYPE(ctypes.c_int)),
    ]


# instance handles passed through the void* o_ins/i_ins slots: the
# callback resolves them back to Instance objects. Keyed by a token,
# never a raw Python pointer.
_proxy_instances: dict = {}
_proxy_prop_cache: dict = {}  # returned c_char_p buffers stay alive


def _proxy_drop_handle(handle) -> None:
    """Release an instance handle AND its pinned property buffers
    (they would otherwise accumulate across plugin create/exit
    cycles for the process lifetime)."""
    if handle is None:
        return
    _proxy_instances.pop(handle, None)
    for k in [k for k in _proxy_prop_cache if k[0] == handle]:
        del _proxy_prop_cache[k]


def _proxy_get_property(key, handle):
    ins = _proxy_instances.get(int(handle or 0))
    if ins is None or not key:
        return None
    val = ins.properties.get(key.decode("utf-8", "replace"))
    if val is None:
        return None
    buf = ctypes.create_string_buffer(str(val).encode("utf-8"))
    _proxy_prop_cache[(int(handle), key)] = buf  # pin until next call
    return ctypes.addressof(buf)


def _make_api() -> _FlbApi:
    api = _FlbApi()
    get_prop = _GET_PROP_FN(_proxy_get_property)
    api.output_get_property = get_prop
    api.input_get_property = get_prop
    api.custom_get_property = get_prop
    api.log_print = None
    # FBTPU_DSO_API_PROBE=1 makes the three log_check slots return
    # distinct per-kind values (1/2/3) so the ABI tests can PROVE a
    # call reached its exact slot — an order regression hands back a
    # neighbouring entry. Production keeps the quiet 0 for all kinds
    # (log_check is a boolean gate; a nonzero stub would flood plugins
    # that log whenever their level "passes").
    probe = os.environ.get("FBTPU_DSO_API_PROBE") == "1"
    api.input_log_check = _LOG_CHECK_FN(
        lambda _i, _l: 1 if probe else 0)
    api.output_log_check = _LOG_CHECK_FN(
        lambda _i, _l: 2 if probe else 0)
    api.custom_log_check = _LOG_CHECK_FN(
        lambda _i, _l: 3 if probe else 0)
    # pin the closures with the struct
    api._refs = (get_prop, api.input_log_check, api.output_log_check,
                 api.custom_log_check)
    return api


def _proxy_symbol(dso, name, proto):
    try:
        fn = getattr(dso, name)
    except AttributeError:
        return None
    return ctypes.cast(fn, proto)


def load_proxy_plugin(path: str, registry=None):
    """Load a Go-proxy-contract shared object: call its
    FLBPluginRegister with a definition struct, then register the
    resulting plugin under the name the PLUGIN chose (not the file
    name). Returns the new plugin class."""
    from .plugin import registry as default_registry

    reg = registry if registry is not None else default_registry
    # pre-dlopen probe: an object without the registration export is
    # rejected before any of its code can run
    _probe_exports(path, {"FLBPluginRegister"}, "proxy plugin")
    try:
        dso = ctypes.CDLL(os.path.abspath(path))
    except OSError as e:
        raise ValueError(f"cannot load proxy plugin {path!r}: {e}") from e
    try:
        register = dso.FLBPluginRegister
    except AttributeError as e:
        raise ValueError(
            f"cannot load proxy plugin {path!r}: no FLBPluginRegister "
            f"export") from e
    register.restype = ctypes.c_int
    register.argtypes = [ctypes.POINTER(_ProxyDef)]
    pdef = _ProxyDef()
    if register(ctypes.byref(pdef)) < 0:
        raise ValueError(f"proxy plugin {path!r}: FLBPluginRegister "
                         f"failed")
    name = (pdef.name or b"").decode("utf-8", "replace")
    if not name:
        raise ValueError(f"proxy plugin {path!r}: empty plugin name")
    if pdef.type == FLB_PROXY_OUTPUT_PLUGIN:
        return _register_proxy_output(reg, dso, pdef, name, path)
    if pdef.type == FLB_PROXY_INPUT_PLUGIN:
        return _register_proxy_input(reg, dso, pdef, name, path)
    raise ValueError(
        f"proxy plugin {path!r}: unsupported type {pdef.type}")


def _register_proxy_output(reg, dso, pdef, name, path):
    from .plugin import FlushResult, OutputPlugin

    cb_init = _proxy_symbol(dso, "FLBPluginInit",
                            _GoOutputPlugin._fields_[4][1])
    if cb_init is None:
        raise ValueError(f"proxy plugin {path!r}: no FLBPluginInit")
    cb_flush = _proxy_symbol(dso, "FLBPluginFlush",
                             _GoOutputPlugin._fields_[5][1])
    cb_flush_ctx = _proxy_symbol(dso, "FLBPluginFlushCtx",
                                 _GoOutputPlugin._fields_[6][1])
    if cb_flush is None and cb_flush_ctx is None:
        raise ValueError(f"proxy plugin {path!r}: no FLBPluginFlush or "
                         f"FLBPluginFlushCtx")
    cb_exit = _proxy_symbol(dso, "FLBPluginExit",
                            _GoOutputPlugin._fields_[7][1])
    cb_exit_ctx = _proxy_symbol(dso, "FLBPluginExitCtx",
                                _GoOutputPlugin._fields_[8][1])
    desc = (pdef.description or b"").decode("utf-8", "replace")

    class ProxyOutput(OutputPlugin):
        description = desc
        allow_unknown_properties = True
        _dso = dso  # keep mapped

        def init(self, instance, engine) -> None:
            self._handle = id(instance)
            _proxy_instances[self._handle] = instance
            self._api = _make_api()
            self._plug = _GoOutputPlugin()
            self._plug.name = name.encode()
            self._plug.api = ctypes.pointer(self._api)
            self._plug.o_ins = self._handle
            if cb_flush:
                self._plug.cb_flush = cb_flush
            if cb_flush_ctx:
                self._plug.cb_flush_ctx = cb_flush_ctx
            rc = cb_init(ctypes.byref(self._plug))
            if rc <= 0:
                raise RuntimeError(
                    f"{name}: FLBPluginInit returned {rc}")

        async def flush(self, data: bytes, tag: str, engine):
            buf = ctypes.create_string_buffer(data, len(data))
            t = tag.encode("utf-8", "replace")
            # ctx-variant only when the plugin SET a context
            # (go.c proxy_go_output_flush dispatches the same way);
            # FLBPluginFlushCtx(NULL, ...) would crash ctx-assuming
            # plugins that export both symbols
            if cb_flush_ctx is not None and self._plug.context:
                rc = cb_flush_ctx(self._plug.context, buf, len(data), t)
            elif cb_flush is not None:
                rc = cb_flush(buf, len(data), t)
            else:
                rc = cb_flush_ctx(self._plug.context, buf, len(data), t)
            return {_PROXY_FLB_OK: FlushResult.OK,
                    _PROXY_FLB_RETRY: FlushResult.RETRY}.get(
                        rc, FlushResult.ERROR)

        def exit(self) -> None:
            if cb_exit_ctx is not None and self._plug.context:
                cb_exit_ctx(self._plug.context)
            elif cb_exit is not None:
                cb_exit()
            _proxy_drop_handle(getattr(self, "_handle", None))

    ProxyOutput.name = name
    ProxyOutput.__name__ = f"Proxy_{name}"
    reg.register(ProxyOutput)
    log.info("dso: registered proxy output %r from %s", name, path)
    return ProxyOutput


def _register_proxy_input(reg, dso, pdef, name, path):
    from .plugin import InputPlugin

    cb_init = _proxy_symbol(dso, "FLBPluginInit",
                            _GoInputPlugin._fields_[4][1])
    if cb_init is None:
        raise ValueError(f"proxy plugin {path!r}: no FLBPluginInit")
    cb_collect = _proxy_symbol(dso, "FLBPluginInputCallback",
                               _GoInputPlugin._fields_[5][1])
    if cb_collect is None:
        raise ValueError(
            f"proxy plugin {path!r}: no FLBPluginInputCallback")
    cb_cleanup = _proxy_symbol(dso, "FLBPluginInputCleanupCallback",
                               _GoInputPlugin._fields_[7][1])
    cb_exit = _proxy_symbol(dso, "FLBPluginExit",
                            _GoInputPlugin._fields_[9][1])
    desc = (pdef.description or b"").decode("utf-8", "replace")

    class ProxyInput(InputPlugin):
        description = desc
        allow_unknown_properties = True
        collect_interval = 1.0
        _dso = dso

        def init(self, instance, engine) -> None:
            self._handle = id(instance)
            _proxy_instances[self._handle] = instance
            self._api = _make_api()
            self._plug = _GoInputPlugin()
            self._plug.name = name.encode()
            self._plug.api = ctypes.pointer(self._api)
            self._plug.i_ins = self._handle
            rc = cb_init(ctypes.byref(self._plug))
            if rc <= 0:
                raise RuntimeError(
                    f"{name}: FLBPluginInit returned {rc}")

        def collect(self, engine) -> None:
            from ..codec.events import fast_count_records

            data = ctypes.c_void_p()
            size = ctypes.c_size_t(0)
            rc = cb_collect(ctypes.byref(data), ctypes.byref(size))
            if rc < 0 or not data or not size.value:
                return
            try:
                raw = ctypes.string_at(data, size.value)
            finally:
                # the plugin malloc'd the buffer; its cleanup callback
                # (or libc free) releases it — the reference proxy does
                # exactly this after enqueueing (flb_plugin_proxy.c)
                if cb_cleanup is not None:
                    cb_cleanup(data)
                else:
                    ctypes.CDLL(None).free(data)
            n = fast_count_records(raw)
            if not n:
                return
            engine.input_log_append(self.instance, self.instance.tag,
                                    raw, n)

        def exit(self) -> None:
            if cb_exit is not None:
                cb_exit()
            _proxy_drop_handle(getattr(self, "_handle", None))

    ProxyInput.name = name
    ProxyInput.__name__ = f"Proxy_{name}"
    reg.register(ProxyInput)
    log.info("dso: registered proxy input %r from %s", name, path)
    return ProxyInput
