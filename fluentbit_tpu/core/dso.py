"""Dynamic (.so) plugin loading — the flb_plugin.c role.

Reference: src/flb_plugin.c:200-326 — ``flb_plugin_load`` dlopens a
shared object, derives the registration symbol from the file name, and
links the plugin struct into the registry; exposed via the CLI ``-e``
flag and ``[PLUGINS]``/plugins-file config. The same contract here:
``load_dso_plugin(path)`` loads a C ABI object (``native/
fbtpu_plugin.h``), wraps its vtable in an InputPlugin/OutputPlugin
subclass, and registers it under the struct's name. The reference
proves native-language plugins with its Zig demo (lib/zig_fluent_bit);
this build's proof is ``native/demo_plugins/`` built with g++ in the
runtime tests.
"""

from __future__ import annotations

import ctypes
import json
import logging
import os
from typing import Optional

log = logging.getLogger("flb.dso")

FBTPU_PLUGIN_ABI_VERSION = 1

_EMIT_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_char_p,
                            ctypes.c_char_p, ctypes.c_longlong)


class _OutputVtable(ctypes.Structure):
    _fields_ = [
        ("abi_version", ctypes.c_int),
        ("name", ctypes.c_char_p),
        ("description", ctypes.c_char_p),
        ("init", ctypes.CFUNCTYPE(ctypes.c_void_p, ctypes.c_char_p)),
        ("flush", ctypes.CFUNCTYPE(
            ctypes.c_int, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_ubyte), ctypes.c_longlong,
            ctypes.c_char_p)),
        ("destroy", ctypes.CFUNCTYPE(None, ctypes.c_void_p)),
    ]


class _InputVtable(ctypes.Structure):
    _fields_ = [
        ("abi_version", ctypes.c_int),
        ("name", ctypes.c_char_p),
        ("description", ctypes.c_char_p),
        ("collect_interval", ctypes.c_double),
        ("init", ctypes.CFUNCTYPE(ctypes.c_void_p, ctypes.c_char_p)),
        ("collect", ctypes.CFUNCTYPE(
            ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_char_p, _EMIT_FN)),
        ("destroy", ctypes.CFUNCTYPE(None, ctypes.c_void_p)),
    ]


def plugin_stem(path: str) -> str:
    """File name → registration stem (path_to_plugin_name role): strip
    directory, extension, and an optional flb- prefix."""
    base = os.path.basename(path)
    stem = base.split(".", 1)[0]
    if stem.startswith("flb-"):
        stem = stem[len("flb-"):]
    return stem


def _props_json(instance) -> bytes:
    props = {}
    for _lk, key, value in instance.properties._items:
        props[key] = value if isinstance(value, (str, int, float, bool)) \
            else str(value)
    return json.dumps(props).encode()


def load_dso_plugin(path: str, registry=None):
    """dlopen + register; returns the new plugin class. Raises
    ValueError on a malformed object (missing/unsupported symbol)."""
    from .plugin import InputPlugin, OutputPlugin
    from .plugin import registry as default_registry

    reg = registry if registry is not None else default_registry
    stem = plugin_stem(path)
    symbol = f"{stem}_plugin"
    if not stem.startswith(("in_", "out_")):
        # cheap check FIRST — rejected objects must never be mapped
        # (dlopen runs their static initializers)
        raise ValueError(
            f"cannot load plugin {path!r}: stem {stem!r} must start "
            f"with in_ or out_")
    try:
        dso = ctypes.CDLL(os.path.abspath(path))
    except OSError as e:
        raise ValueError(f"cannot load plugin {path!r}: {e}") from e
    if stem.startswith("out_"):
        try:
            vt = _OutputVtable.in_dll(dso, symbol)
        except ValueError as e:
            raise ValueError(
                f"cannot load plugin {path!r}: registration structure "
                f"is missing {symbol!r}") from e
        return _register_output(reg, OutputPlugin, dso, vt, path)
    if stem.startswith("in_"):
        try:
            vt = _InputVtable.in_dll(dso, symbol)
        except ValueError as e:
            raise ValueError(
                f"cannot load plugin {path!r}: registration structure "
                f"is missing {symbol!r}") from e
        return _register_input(reg, InputPlugin, dso, vt, path)
    raise AssertionError("unreachable")  # stem validated above


def _check_abi(vt, path: str) -> str:
    if vt.abi_version != FBTPU_PLUGIN_ABI_VERSION:
        raise ValueError(
            f"plugin {path!r}: ABI version {vt.abi_version} "
            f"(host speaks {FBTPU_PLUGIN_ABI_VERSION})")
    name = (vt.name or b"").decode("utf-8", "replace")
    if not name:
        raise ValueError(f"plugin {path!r}: empty plugin name")
    return name


def _register_output(reg, OutputPlugin, dso, vt, path):
    from .plugin import FlushResult

    name = _check_abi(vt, path)

    class DsoOutput(OutputPlugin):
        description = (vt.description or b"").decode("utf-8", "replace")
        allow_unknown_properties = True  # props pass through as JSON
        _dso = dso  # keep the handle alive with the class
        _vt = vt

        def init(self, instance, engine) -> None:
            ctx = self._vt.init(_props_json(instance))
            if not ctx:
                raise RuntimeError(f"{self.name}: native init failed")
            self._ctx = ctypes.c_void_p(ctx)

        async def flush(self, data: bytes, tag: str, engine):
            buf = (ctypes.c_ubyte * len(data)).from_buffer_copy(data)
            rc = self._vt.flush(self._ctx, buf, len(data),
                                tag.encode("utf-8", "replace"))
            return {0: FlushResult.OK, 1: FlushResult.RETRY}.get(
                rc, FlushResult.ERROR)

        def exit(self) -> None:
            ctx = getattr(self, "_ctx", None)
            if ctx:
                self._vt.destroy(ctx)
                self._ctx = None

    DsoOutput.name = name
    DsoOutput.__name__ = f"Dso_{name}"
    reg.register(DsoOutput)
    log.info("dso: registered output plugin %r from %s", name, path)
    return DsoOutput


def _register_input(reg, InputPlugin, dso, vt, path):
    name = _check_abi(vt, path)
    interval = vt.collect_interval if vt.collect_interval > 0 else 1.0

    class DsoInput(InputPlugin):
        description = (vt.description or b"").decode("utf-8", "replace")
        allow_unknown_properties = True  # props pass through as JSON
        collect_interval = interval
        _dso = dso
        _vt = vt

        def init(self, instance, engine) -> None:
            ctx = self._vt.init(_props_json(instance))
            if not ctx:
                raise RuntimeError(f"{self.name}: native init failed")
            self._ctx = ctypes.c_void_p(ctx)

        def collect(self, engine) -> None:
            from ..codec.events import encode_event, now_event_time

            records = []

            def emit(_host, tag, json_text, length):
                # c_char_p already arrived as a NUL-bounded bytes
                # object; slicing by the advertised length stays
                # inside it even when the plugin lies about length
                try:
                    body = json.loads((json_text or b"")[:length])
                except (ValueError, TypeError):
                    return
                records.append((
                    (tag or b"").decode("utf-8", "replace"), body))

            cb = _EMIT_FN(emit)
            rc = self._vt.collect(
                self._ctx, None,
                (self.instance.tag or "").encode("utf-8", "replace"),
                cb)
            if rc < 0:
                log.warning("%s: native collect failed", self.name)
                return
            groups = {}
            for tag, body in records:
                tag = tag or self.instance.tag
                groups.setdefault(tag, []).append(
                    encode_event(body, now_event_time()))
            for tag, bufs in groups.items():
                engine.input_log_append(self.instance, tag,
                                        b"".join(bufs), len(bufs))

        def exit(self) -> None:
            ctx = getattr(self, "_ctx", None)
            if ctx:
                self._vt.destroy(ctx)
                self._ctx = None

    DsoInput.name = name
    DsoInput.__name__ = f"Dso_{name}"
    reg.register(DsoInput)
    log.info("dso: registered input plugin %r from %s", name, path)
    return DsoInput
