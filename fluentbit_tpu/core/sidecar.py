"""Record-offset sidecar for filesystem chunk files (fbtpu-memscope).

The backlog replay path used to pay a full Python msgpack walk per
recovered chunk just to count records and find the crash-torn tail
(storage._read_chunk_file). The sidecar persists the record boundary
table AT APPEND TIME — the ingest path already knows it (the decode
path tracks per-event ends while joining raw spans; the raw path's
native scanner discovers it in C) — so replay can map the chunk file
read-only and stage straight through ``native.stage_field_into``
without re-walking the payload. The PR-4 S3 digest-map sidecar is the
pattern: a small companion file next to the object it describes.

Layout (``<chunk>.flb.offs``)::

    FBTO | ver u8 | state u8 | crc32 u32le      (header, 10 bytes)
    u64le record END offsets, strictly increasing, relative to the
    payload start (not the file start)

``state`` mirrors the chunk file: 0 = open (entries are advisory — a
crash may have torn either file, replay must validate), 1 = finalized
(``crc`` covers the entry bytes; stamped together with the chunk CRC
at drain time, so a FINAL chunk + FINAL sidecar with matching CRCs is
trusted outright and the replay walk is skipped entirely).

Torn-sidecar contract (the soak/fuzz surface): a partial trailing
entry is truncated at the last full 8 bytes; entries past the payload
length are dropped (the chunk data flush and the sidecar flush are
separate syscalls — a crash between them leaves the sidecar ahead or
behind, both recoverable); any monotonicity violation invalidates the
whole table and replay falls back to the decode walk. The fallback is
always bit-exact: the sidecar can only ever accelerate, never change,
what replay yields.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Iterable, Optional, Tuple

import numpy as np

__all__ = ["SIDECAR_SUFFIX", "SidecarWriter", "sidecar_path",
           "read_sidecar", "STATE_OPEN", "STATE_FINAL"]

MAGIC = b"FBTO"
VERSION = 1
STATE_OPEN = 0
STATE_FINAL = 1

_HEAD = struct.Struct("<4sBBI")  # magic, ver, state, crc32(entries)

SIDECAR_SUFFIX = ".offs"


def sidecar_path(chunk_path: str) -> str:
    """The offset-table companion of a chunk file."""
    return chunk_path + SIDECAR_SUFFIX


class SidecarWriter:
    """Incremental offset-table writer bound to one chunk stream file.

    ``append_ends`` takes the END offsets of the records inside ONE
    appended span, relative to that span; the writer rebases them onto
    the running payload length so the persisted entries are absolute
    within the payload. Callers flush the chunk data first, then the
    sidecar — replay tolerates either file being ahead of the other.
    """

    __slots__ = ("path", "_f", "_base", "_crc", "_dead")

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "wb")
        self._f.write(_HEAD.pack(MAGIC, VERSION, STATE_OPEN, 0))
        self._f.flush()
        self._base = 0
        self._crc = 0
        self._dead = False

    @property
    def dead(self) -> bool:
        return self._dead

    def append_ends(self, span_len: int,
                    ends: Optional[Iterable[int]]) -> None:
        """Record one appended span's record END offsets.

        ``ends`` None means the caller could not produce a boundary
        table for this span (native scanner unavailable / undecodable
        bytes): the sidecar is now incomplete FOREVER for this chunk,
        so it is unlinked rather than left lying — a partial table
        that silently skips a span would replay the wrong records.
        """
        if self._dead:
            return
        if ends is None:
            self.kill()
            return
        base = self._base
        payload = b"".join(
            struct.pack("<q", base + int(e)) for e in ends)
        if payload:
            self._f.write(payload)
            self._f.flush()
            self._crc = zlib.crc32(payload, self._crc)
        self._base = base + span_len

    def finalize(self) -> None:
        """Stamp state=final + entry CRC (drain time, with the chunk's
        own CRC stamp) and close the handle."""
        if self._dead:
            return
        self._f.flush()
        self._f.seek(0)
        self._f.write(_HEAD.pack(MAGIC, VERSION, STATE_FINAL,
                                 self._crc & 0xFFFFFFFF))
        self._f.close()
        self._dead = True

    def close(self) -> None:
        if not self._dead:
            try:
                self._f.close()
            except OSError:
                pass
            self._dead = True

    def kill(self) -> None:
        """Abandon the sidecar: close and unlink (incomplete tables
        must not survive — see append_ends)."""
        self.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass


def read_sidecar(path: str, payload_len: int
                 ) -> Optional[Tuple[int, np.ndarray, bool]]:
    """Load + validate an offset table against a payload length.

    Returns ``(state, ends, trusted_layout)`` or None when the file is
    absent/unusable. ``ends`` holds only entries that are strictly
    increasing, positive, and <= payload_len (a torn trailing entry is
    truncated at the last full 8 bytes; entries past the payload are
    dropped — the chunk flush may have lost the bytes they describe).
    ``trusted_layout`` is True only when the sidecar is FINAL and its
    entry CRC matches — the caller may then skip the validation walk,
    provided the chunk payload itself passed its own CRC.

    Any monotonicity violation invalidates the WHOLE table (a bit flip
    in one entry says nothing about its neighbours): returns None and
    replay takes the decode walk.
    """
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return None
    if len(blob) < _HEAD.size:
        return None
    magic, ver, state, crc = _HEAD.unpack_from(blob)
    if magic != MAGIC or ver != VERSION:
        return None
    if state not in (STATE_OPEN, STATE_FINAL):
        # a state byte neither open nor final is corruption, not a
        # crash window — nothing else in the file can be believed
        return None
    body = blob[_HEAD.size:]
    body = body[: len(body) - (len(body) % 8)]
    trusted = False
    if state == STATE_FINAL:
        trusted = (zlib.crc32(body) & 0xFFFFFFFF) == crc
        if not trusted:
            # a FINAL sidecar with a bad CRC is corrupt, not torn:
            # nothing in it can be believed
            return None
    ends = np.frombuffer(body, dtype="<i8")
    if ends.size:
        if int(ends[0]) <= 0 or bool((np.diff(ends) <= 0).any()):
            return None
        keep = int(np.searchsorted(ends, payload_len, side="right"))
        if ends.size > keep:
            ends = ends[:keep]
            trusted = False  # the table outran the flushed payload
    return int(state), ends.astype(np.int64, copy=False), trusted
