"""Internal metrics — cmetrics equivalent.

Reference: lib/cmetrics (cmt_counter/cmt_gauge/cmt_histogram) used
throughout the engine (fluentbit_input_records_total at ingest
src/flb_input_chunk.c:3053-3070, filter add/drop src/flb_filter.c:218-303,
output proc/retry/drop src/flb_engine.c:382-467). Provides Prometheus text
exposition (the /api/v1/metrics/prometheus endpoint) and msgpack encoding so
metrics can flow *as data* through the pipeline (in_fluentbit_metrics).
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .lockorder import make_lock

DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class _Metric:
    kind = ""

    def __init__(self, registry: "MetricsRegistry", ns: str, subsystem: str,
                 name: str, desc: str, label_keys: Sequence[str] = ()):
        self.ns = ns
        self.subsystem = subsystem
        self.name = name
        self.desc = desc
        self.label_keys = tuple(label_keys)
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = registry._lock
        registry._add(self)

    @property
    def fqname(self) -> str:
        parts = [p for p in (self.ns, self.subsystem, self.name) if p]
        return "_".join(parts)

    def _key(self, labels: Sequence[str]) -> Tuple[str, ...]:
        labels = tuple(str(x) for x in labels)
        if len(labels) != len(self.label_keys):
            raise ValueError(
                f"{self.fqname}: expected {len(self.label_keys)} labels, got {len(labels)}"
            )
        return labels

    def samples(self) -> List[Tuple[Tuple[str, ...], float]]:
        with self._lock:
            return list(self._values.items())

    def clear(self) -> None:
        """Drop every labeled series (frequency-mode top-k refresh)."""
        with self._lock:
            self._values.clear()

    def remove_matching(self, label_key: str, value: str) -> None:
        """Drop only the labeled series where ``label_key`` equals
        ``value`` — the wholesale-refresh primitive for metric families
        SHARED by several publishers (the flux exporters all write
        ``fluentbit_flux_*`` in the engine registry; one instance's
        stale-series refresh must not clobber its siblings')."""
        with self._lock:
            try:
                i = self.label_keys.index(label_key)
            except ValueError:
                return
            for k in [k for k in self._values if k[i] == value]:
                del self._values[k]


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, labels: Sequence[str] = ()) -> None:
        k = self._key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value

    add = inc

    def get(self, labels: Sequence[str] = ()) -> float:
        k = self._key(labels)
        with self._lock:
            return self._values.get(k, 0.0)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, labels: Sequence[str] = ()) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def inc(self, value: float = 1.0, labels: Sequence[str] = ()) -> None:
        k = self._key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value

    def dec(self, value: float = 1.0, labels: Sequence[str] = ()) -> None:
        self.inc(-value, labels)

    def set_max(self, value: float, labels: Sequence[str] = ()) -> None:
        """High-water semantics: keep the largest value ever set (the
        guard's task-map occupancy high-water mark)."""
        k = self._key(labels)
        with self._lock:
            if value > self._values.get(k, float("-inf")):
                self._values[k] = float(value)

    def get(self, labels: Sequence[str] = ()) -> float:
        k = self._key(labels)
        with self._lock:
            return self._values.get(k, 0.0)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, registry, ns, subsystem, name, desc,
                 label_keys: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(registry, ns, subsystem, name, desc, label_keys)
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}

    def observe(self, value: float, labels: Sequence[str] = ()) -> None:
        k = self._key(labels)
        with self._lock:
            counts = self._counts.get(k)
            if counts is None:
                counts = [0] * (len(self.buckets) + 1)  # +inf bucket
                self._counts[k] = counts
            idx = bisect.bisect_left(self.buckets, value)
            counts[idx] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._values[k] = self._values.get(k, 0.0) + 1  # total count

    def hist_samples(self):
        with self._lock:
            return {k: (list(v), self._sums.get(k, 0.0)) for k, v in self._counts.items()}


class MetricsRegistry:
    """A cmt context."""

    def __init__(self) -> None:
        self._lock = make_lock("MetricsRegistry._lock",
                               reentrant=True)
        self._metrics: Dict[str, _Metric] = {}

    def _add(self, metric: _Metric) -> None:
        with self._lock:
            self._metrics[metric.fqname] = metric

    # get-or-create runs entirely under the registry lock (RLock — the
    # metric constructor re-enters it via _add): two threads racing to
    # create the same counter must get the SAME object, or one side's
    # increments land on an orphan and vanish from exposition

    def counter(self, ns: str, subsystem: str, name: str, desc: str = "",
                label_keys: Sequence[str] = ()) -> Counter:
        key = "_".join(p for p in (ns, subsystem, name) if p)
        with self._lock:
            m = self._metrics.get(key)
            if isinstance(m, Counter):
                return m
            return Counter(self, ns, subsystem, name, desc, label_keys)

    def gauge(self, ns: str, subsystem: str, name: str, desc: str = "",
              label_keys: Sequence[str] = ()) -> Gauge:
        key = "_".join(p for p in (ns, subsystem, name) if p)
        with self._lock:
            m = self._metrics.get(key)
            if isinstance(m, Gauge):
                return m
            return Gauge(self, ns, subsystem, name, desc, label_keys)

    def histogram(self, ns: str, subsystem: str, name: str, desc: str = "",
                  label_keys: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        key = "_".join(p for p in (ns, subsystem, name) if p)
        with self._lock:
            m = self._metrics.get(key)
            if isinstance(m, Histogram):
                return m
            return Histogram(self, ns, subsystem, name, desc, label_keys,
                             buckets)

    def metrics(self) -> Iterable[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    # -- exposition --

    def to_prometheus(self) -> str:
        """Prometheus text format (api/v1/metrics/prometheus equivalent)."""
        return payload_to_prometheus(self.to_msgpack_obj())

    def to_msgpack_obj(self) -> dict:
        """Encode as a plain structure for the metrics pipeline."""
        ts = time.time()
        metrics = []
        for m in self.metrics():
            entry = {
                "name": m.fqname,
                "type": m.kind,
                "desc": m.desc,
                "labels": list(m.label_keys),
                "ts": ts,
                "values": [
                    {"labels": list(k), "value": v} for k, v in m.samples()
                ],
            }
            if isinstance(m, Histogram):
                entry["buckets"] = list(m.buckets)
                entry["hist"] = [
                    {"labels": list(k), "counts": c, "sum": s}
                    for k, (c, s) in m.hist_samples().items()
                ]
            metrics.append(entry)
        return {"meta": {"ts": ts}, "metrics": metrics}


def payload_to_prometheus(obj: dict) -> str:
    """Render a metrics-as-data payload (MetricsRegistry.to_msgpack_obj
    shape) as Prometheus text — the out_prometheus_exporter / stdout
    rendering of METRICS-type chunks."""
    out: List[str] = []
    for m in obj.get("metrics", []):
        fq = m.get("name", "")
        if m.get("desc"):
            out.append(f"# HELP {fq} {m['desc']}")
        out.append(f"# TYPE {fq} {m.get('type', 'untyped')}")
        keys = tuple(m.get("labels", []))
        if m.get("type") == "histogram":
            buckets = m.get("buckets", [])
            for h in m.get("hist", []):
                labels = tuple(h.get("labels", []))
                base = _fmt_labels(keys, labels)
                cum = 0
                counts = h.get("counts", [])
                for b, c in zip(buckets, counts):
                    cum += c
                    le = _fmt_labels(keys + ("le",), labels + (_fmt_float(b),))
                    out.append(f"{fq}_bucket{le} {cum}")
                if len(counts) > len(buckets):
                    cum += counts[-1]
                le = _fmt_labels(keys + ("le",), labels + ("+Inf",))
                out.append(f"{fq}_bucket{le} {cum}")
                out.append(f"{fq}_sum{base} {_fmt_float(h.get('sum', 0.0))}")
                out.append(f"{fq}_count{base} {cum}")
        else:
            for s in m.get("values", []):
                out.append(
                    f"{fq}{_fmt_labels(keys, tuple(s.get('labels', [])))} "
                    f"{_fmt_float(s.get('value', 0.0))}"
                )
    return "\n".join(out) + "\n"


def is_metrics_payload(obj) -> bool:
    return isinstance(obj, dict) and "metrics" in obj and "meta" in obj


def _fmt_labels(keys: Sequence[str], values: Sequence[str]) -> str:
    if not keys:
        return ""
    pairs = ",".join(
        f'{k}="{_escape(v)}"' for k, v in zip(keys, values)
    )
    return "{" + pairs + "}"


def _escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_float(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))
