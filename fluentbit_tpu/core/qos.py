"""fbtpu-qos — multi-tenant weighted-fair ingest, graded shedding
support, and hot config reload (QOS.md has the operator contract).

The paper's target is one agent serving traffic from millions of
users; the engine previously had exactly one isolation primitive — the
all-or-nothing ``mem_buf_limit`` pause — and exactly one shedding mode
(fbtpu-guard's shed-all above a single watermark). One flooding input
could starve every other tag's dispatch and any config change required
a restart that dropped in-flight chunks. This module is the graded
control plane on top:

- **tenants** — every input (and therefore every chunk) belongs to a
  tenant: a name + DWRR ``weight`` + priority ``class`` (0 = highest)
  + optional ingest quota (token bucket, bytes/second). Inputs declare
  membership with the ``tenant`` / ``tenant.*`` instance keys; inputs
  that declare nothing share the ``default`` tenant with service-level
  defaults, and the whole plane then degenerates to one FIFO flow —
  i.e. the unconfigured pipeline behaves exactly as before.

- **ingest admission** — ``Engine.input_log_append`` /
  ``input_event_append`` call :meth:`Qos.admit` before any work. Over
  quota, the append is *deferred* (returns -1, the reference's
  backpressure verdict — callers retry) or *shed* (dropped, counted)
  per the tenant's ``tenant.overflow`` policy. The fbtpu-lint rule
  ``qos-unmetered-ingest`` (analysis/qos.py) flags any new ingest
  entry point that bypasses this call.

- **weighted-fair dispatch** — ``Engine.flush_all`` drains ready
  chunks through a :class:`~.bucket_queue.DeficitFairQueue`: strict
  priority across classes, deficit-weighted round robin across tenants
  within a class. When dispatch capacity is scarce (task map near
  full, or ``qos.cycle_budget`` set), the scarce slots are allocated
  by weight instead of input order — a flooding tenant saturates only
  its own share.

- **hot config reload** — :class:`ReloadTxn` adds/removes/replaces
  inputs, filters, outputs and parsers behind a *generation swap*: new
  instances are built and initialized (including native DFA /
  ``GrepTables`` recompilation) entirely off-line, then the engine's
  instance lists — treated as copy-on-write everywhere — are swapped
  by reference under the ingest lock in one critical section that also
  bumps ``engine.generation`` / ``engine.reload_count``. In-flight
  chunks are never dropped: removed inputs' pending chunks drain into
  the dispatch backlog, and in-flight flushes hold direct references
  to their (possibly removed) outputs until they settle.

Shed-by-priority lives in ``core/guard.py`` (the guard owns the
watermark machinery); it reads the chunk priorities this plane stamps.
``fluentbit_qos_*`` metric families and the ``/api/v1/health`` tenant
block are documented in QOS.md.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .. import failpoints as _fp
from .bucket_queue import QOS_CLASS_COUNT, DeficitFairQueue
from .lockorder import make_lock
from .scheduler import TokenBucket

log = logging.getLogger("flb.qos")

#: Admission verdicts (:meth:`Qos.admit`).
ADMIT, DEFER, SHED = 0, 1, 2

#: Name of the tenant inputs fall into when they declare none.
DEFAULT_TENANT = "default"


class Tenant:
    """One tenant's QoS contract: fair-share weight, priority class,
    optional ingest quota, overflow policy."""

    __slots__ = ("name", "weight", "priority", "bucket", "overflow",
                 "rate", "burst", "storage_limit", "flush_concurrency",
                 "flush_semaphore")

    def __init__(self, name: str, weight: float, priority: int,
                 rate: Optional[float] = None,
                 burst: Optional[float] = None,
                 overflow: str = "defer", clock=time.monotonic,
                 storage_limit: Optional[int] = None,
                 flush_concurrency: Optional[int] = None):
        self.name = name
        self.weight = float(weight)
        self.priority = min(max(int(priority), 0), QOS_CLASS_COUNT - 1)
        self.rate = rate
        self.burst = burst
        self.overflow = overflow
        # cap on the tenant's LIVE filesystem footprint in bytes (sum
        # of stream chunk payloads currently on disk); None = unmetered
        self.storage_limit = storage_limit
        self.bucket = (TokenBucket(rate, burst, clock=clock)
                       if rate else None)
        # cap on the tenant's CONCURRENT flush attempts across all
        # outputs (None = uncapped): one noisy tenant cannot occupy
        # every output worker slot while quieter tenants queue
        self.flush_concurrency = flush_concurrency
        self.flush_semaphore = self._make_flush_semaphore()

    def _make_flush_semaphore(self):
        import asyncio

        return (asyncio.Semaphore(self.flush_concurrency)
                if self.flush_concurrency else None)


class Qos:
    """Per-engine QoS plane. Created with the engine (like the guard);
    one ``default`` tenant exists from the start, so the unconfigured
    steady state is a dict hit + one counter per append.

    Concurrency: ``_tenants`` and ``_queue`` are touched from ingest
    threads (admission / tenant resolution), the engine loop and
    ``flush_now`` caller threads (dispatch), and reload transactions;
    all access holds ``_lock``. Chunk stamping (``qos_tenant`` /
    ``priority``) happens before the chunk is shared with dispatch.
    """

    def __init__(self, engine, clock=time.monotonic):
        self.engine = engine
        self.clock = clock
        self._lock = make_lock("Qos._lock")
        self._tenants: Dict[str, Tenant] = {}
        # True once tenants span MORE than one priority class: the
        # guard's shed-by-priority pass only engages then — a
        # single-class pipeline keeps the original park-on-backlog
        # behavior (shedding one class below itself is meaningless).
        # Read lock-free on the dispatch path (benign staleness of one
        # flush cycle); recomputed under _lock on tenant changes.
        self._graded = False
        svc = engine.service
        self._queue = DeficitFairQueue(
            quantum=float(svc.qos_quantum),
            weight_floor=svc.qos_weight_floor)
        # per-tenant LIVE filesystem footprint (stream chunk payload
        # bytes currently on disk) + the per-chunk charge ledger that
        # refunds it when delivery deletes the backing file. Only
        # tenants that declare tenant.storage_limit are tracked — the
        # unconfigured pipeline pays nothing here.
        self._storage_used: Dict[str, int] = {}
        self._storage_chunk: Dict[int, Tuple[str, int]] = {}
        # chunks whose persistence was shed once stay shed: admitting
        # a LATER append after a refund would persist a file missing
        # its leading records — replay would silently resurrect a
        # hole-y chunk after a crash
        self._storage_shed_chunks: set = set()

        m = engine.metrics
        self.m_admitted = m.counter(
            "fluentbit", "qos", "admitted_bytes_total",
            "Bytes admitted past tenant quota", ("tenant",))
        self.m_deferred = m.counter(
            "fluentbit", "qos", "deferred_total",
            "Appends deferred (backpressured) by tenant quota",
            ("tenant",))
        self.m_shed_in = m.counter(
            "fluentbit", "qos", "shed_bytes_total",
            "Bytes shed at ingest by tenant overflow policy", ("tenant",))
        self.m_dispatched = m.counter(
            "fluentbit", "qos", "dispatched_chunks_total",
            "Chunks dispatched through the fair scheduler", ("tenant",))
        self.m_queue_chunks = m.gauge(
            "fluentbit", "qos", "queue_chunks",
            "Chunks waiting in the fair dispatch queue", ("tenant",))
        self.m_queue_bytes = m.gauge(
            "fluentbit", "qos", "queue_bytes",
            "Bytes waiting in the fair dispatch queue", ("tenant",))
        self.m_lag = m.histogram(
            "fluentbit", "qos", "scheduler_lag_seconds",
            "Chunk create → fair-scheduler dispatch latency", ("tenant",))
        self.m_priority_shed = m.counter(
            "fluentbit", "qos", "priority_shed_chunks_total",
            "Chunks spilled by shed-by-priority pressure", ("tenant",))
        self.m_storage_used = m.gauge(
            "fluentbit", "storage_quota", "used_bytes",
            "Live filesystem footprint charged to the tenant storage "
            "quota", ("tenant",))
        self.m_storage_shed = m.counter(
            "fluentbit", "storage_quota", "shed_bytes_total",
            "Write-through bytes shed by the tenant storage quota "
            "(chunk kept memory-only)", ("tenant",))
        self.m_generation = m.gauge(
            "fluentbit", "qos", "reload_generation",
            "Current hot-reload configuration generation")
        self.m_reloads = m.counter(
            "fluentbit", "qos", "reloads_total",
            "Committed hot-reload generation swaps")

    # -- config ---------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return bool(self.engine.service.qos_enable)

    def tenant(self, name: str, **params) -> Tenant:
        """Get-or-create a tenant; explicit ``params`` override the
        stored contract (last declaration wins — a reload re-declaring
        a tenant's weight takes effect on the next dispatch round)."""
        svc = self.engine.service
        with self._lock:
            t = self._tenants.get(name)
            if t is None:
                t = Tenant(
                    name,
                    weight=params.get("weight",
                                      svc.qos_default_weight),
                    priority=params.get("priority",
                                        svc.qos_default_priority),
                    rate=params.get("rate"),
                    burst=params.get("burst"),
                    overflow=params.get("overflow", "defer"),
                    clock=self.clock,
                    storage_limit=params.get("storage_limit"),
                    flush_concurrency=params.get("flush_concurrency"))
                self._tenants[name] = t
                self._graded = len({x.priority for x in
                                    self._tenants.values()}) > 1
                return t
        # update outside the dict-creation critical section: Tenant
        # field writes are atomic assignments and torn combinations
        # only ever mix two declared-valid configs for one cycle
        if "weight" in params:
            t.weight = float(params["weight"])
        if "priority" in params:
            t.priority = min(max(int(params["priority"]), 0),
                             QOS_CLASS_COUNT - 1)
        if "overflow" in params:
            t.overflow = params["overflow"]
        if "storage_limit" in params:
            t.storage_limit = (None if params["storage_limit"] is None
                               else int(params["storage_limit"]))
        if "flush_concurrency" in params \
                and params["flush_concurrency"] != t.flush_concurrency:
            # rebuild like the bucket: in-flight flushes release the
            # OLD semaphore they acquired (held by reference in the
            # attempt's finally), new attempts queue on the new cap
            t.flush_concurrency = (
                None if params["flush_concurrency"] is None
                else int(params["flush_concurrency"]))
            t.flush_semaphore = t._make_flush_semaphore()
        if ("rate" in params or "burst" in params) and (
                params.get("rate", t.rate) != t.rate
                or params.get("burst", t.burst) != t.burst):
            # absent keys mean "no change" (same as weight/priority
            # above) — a re-declaration that only tightens the burst
            # must rebuild the bucket too, and one that only moves the
            # rate keeps the declared burst
            t.rate = params.get("rate", t.rate)
            t.burst = params.get("burst", t.burst)
            t.bucket = (TokenBucket(t.rate, t.burst, clock=self.clock)
                        if t.rate else None)
        if "priority" in params:
            with self._lock:
                self._graded = len({x.priority for x in
                                    self._tenants.values()}) > 1
        return t

    def graded(self) -> bool:
        """True when tenants span more than one priority class — the
        precondition for shed-by-priority (guard.maybe_shed)."""
        return self._graded

    def flush_slot(self, chunk):
        """The chunk's tenant flush-concurrency semaphore, or None
        when the tenant is uncapped/undeclared. Read at every flush
        attempt (engine._flush_body) so a reload that re-declares
        ``tenant.flush_concurrency`` takes effect on the next
        attempt, not the next restart."""
        name = getattr(chunk, "qos_tenant", None) or DEFAULT_TENANT
        with self._lock:
            t = self._tenants.get(name)
        return None if t is None else t.flush_semaphore

    def tenant_for_input(self, ins) -> Tenant:
        """Resolve (and cache on the instance) the input's tenant."""
        t = getattr(ins, "_qos_tenant", None)
        if t is None:
            name = getattr(ins, "tenant_name", None) or DEFAULT_TENANT
            params = getattr(ins, "tenant_params", None) or {}
            t = self.tenant(name, **params)
            ins._qos_tenant = t
        return t

    # -- ingest admission ----------------------------------------------

    def admit(self, ins, n_bytes: int) -> int:
        """Meter one append against the input's tenant quota. Returns
        :data:`ADMIT`, :data:`DEFER` (caller returns -1: the
        reference's backpressure verdict) or :data:`SHED` (the append
        is dropped and counted)."""
        if getattr(ins, "qos_exempt", False):
            # hidden emitter inputs (engine.hidden_input): the bytes
            # were metered once at the original ingest point — replay
            # hops must neither charge the quota a second time nor
            # DEFER (their fire-and-forget callers would drop the
            # already-admitted record)
            return ADMIT
        t = self.tenant_for_input(ins)
        if _fp.ACTIVE:
            _fp.fire("qos.admit")
        if t.bucket is None or not self.enabled:
            self.m_admitted.inc(n_bytes, (t.name,))
            return ADMIT
        if t.bucket.try_take(n_bytes):
            self.m_admitted.inc(n_bytes, (t.name,))
            return ADMIT
        if t.overflow == "shed":
            self.m_shed_in.inc(n_bytes, (t.name,))
            return SHED
        self.m_deferred.inc(1, (t.name,))
        return DEFER

    def admit_stamped(self, name: str, n_bytes: int) -> int:
        """Meter one append against a tenant resolved BY NAME — the
        fan-in path (plugins/net_forward.ForwardInput), where the
        tenant identity arrives as a wire stamp on the forward option
        map, not from the local input instance. Same verdicts as
        :meth:`admit`; the caller turns DEFER into a delayed/withheld
        ack (the forward hop's backpressure signal) rather than an
        input pause. Charges the same per-tenant buckets and counters,
        so a tenant's quota holds fleet-wide: edge-local ingest and
        relayed ingest drain one budget."""
        t = self.tenant(name)
        if _fp.ACTIVE:
            _fp.fire("qos.admit")
        if t.bucket is None or not self.enabled:
            self.m_admitted.inc(n_bytes, (t.name,))
            return ADMIT
        if t.bucket.try_take(n_bytes):
            self.m_admitted.inc(n_bytes, (t.name,))
            return ADMIT
        if t.overflow == "shed":
            self.m_shed_in.inc(n_bytes, (t.name,))
            return SHED
        self.m_deferred.inc(1, (t.name,))
        return DEFER

    def stamped_defer_hint(self, name: str, n_bytes: int) -> float:
        """:meth:`defer_hint` for a by-name (wire-stamped) tenant."""
        t = self.tenant(name)
        if t.bucket is None:
            return 0.0
        return t.bucket.delay_for(n_bytes)

    def resume_paused(self, inputs) -> None:
        """Un-pause inputs paused by quota DEFER once their tenant's
        bucket can admit an append the size of the one that deferred
        (rides the housekeeping timer — the quota twin of the
        mem_buf_limit drained-pool resume). Resuming on a single
        token would churn: the resumed collector consumes a read the
        very next DEFER drops."""
        svc = self.engine.service
        for ins in inputs:
            if getattr(ins, "paused_by_qos", False) and \
                    self.defer_hint(
                        ins, getattr(ins, "_qos_defer_cost", 1) or 1
                    ) <= 0.0:
                # the bucket says go, but the resume must also honor
                # the buffer watermarks the drain-path resume checks
                # (engine.flush_all): un-pausing over mem_buf_limit
                # would hand the collector one read the next append's
                # backpressure check rejects — and that path skips
                # quota pauses, so nobody else would resume this input
                with ins.ingest_lock:
                    buf_ok = (
                        not ins.mem_buf_limit
                        or ins.pool.pending_bytes < ins.mem_buf_limit
                    ) and (
                        not getattr(ins, "pause_on_chunks_overlimit",
                                    False)
                        or ins.pool.pending_chunks
                        < svc.storage_max_chunks_up
                    )
                if buf_ok:
                    ins.paused_by_qos = False
                    ins.set_paused(False)

    def refund(self, ins, n_bytes: int) -> None:
        """Return an admitted take that never landed (the append was
        refused after admission — removed-input race). The bucket gets
        its tokens back; the admitted-bytes counter keeps the tiny
        monotonic skew (Prometheus counters never decrement)."""
        t = self.tenant_for_input(ins)
        if t.bucket is not None and self.enabled:
            t.bucket.give_back(n_bytes)

    def admit_storage(self, ins, chunk, n_bytes: int) -> int:
        """Meter one write-through append against the tenant's
        filesystem-footprint quota (``tenant.storage_limit``). Returns
        :data:`ADMIT` or :data:`SHED` — never :data:`DEFER`: skipping
        persistence is not backpressure, the chunk stays buffered in
        memory and delivery proceeds; only crash durability for the
        shed bytes is given up (counted per tenant in
        ``fluentbit_storage_quota_shed_bytes_total``).

        ``ins`` may be None (guard spill of an already-dispatched
        chunk) — the chunk's stamped tenant resolves instead. A stamp
        already on the chunk ALWAYS wins over the input's tenant: a
        relayed chunk (forward fan-in) belongs to the edge tenant named
        on the wire, not to the aggregator input that received it, so
        its storage footprint lands on the right fleet-wide quota.
        Tenants with no declared limit are never tracked, so the
        unconfigured pipeline pays one attribute probe per append."""
        stamped = getattr(chunk, "qos_tenant", None)
        if stamped is not None:
            t = self.tenant(stamped)
        elif ins is not None:
            t = self.tenant_for_input(ins)
        else:
            t = self.tenant(DEFAULT_TENANT)
        limit = t.storage_limit
        if limit is None or not self.enabled:
            return ADMIT
        with self._lock:
            used = self._storage_used.get(t.name, 0)
            if chunk.id in self._storage_shed_chunks or \
                    used + n_bytes > limit:
                over = True
                self._storage_shed_chunks.add(chunk.id)
            else:
                over = False
                self._storage_used[t.name] = used + n_bytes
                name, charged = self._storage_chunk.get(
                    chunk.id, (t.name, 0))
                self._storage_chunk[chunk.id] = (name,
                                                 charged + n_bytes)
        if over:
            self.m_storage_shed.inc(n_bytes, (t.name,))
            return SHED
        self.m_storage_used.set(used + n_bytes, (t.name,))
        return ADMIT

    def release_storage(self, chunk) -> None:
        """Refund a chunk's storage-quota charge once its backing file
        is deleted (delivery complete / quarantined away). Chunks that
        were never charged — unmetered tenants, recovered backlog files
        — are a no-op."""
        with self._lock:
            self._storage_shed_chunks.discard(chunk.id)
            got = self._storage_chunk.pop(chunk.id, None)
            if got is None:
                return
            name, charged = got
            used = max(0, self._storage_used.get(name, 0) - charged)
            if used:
                self._storage_used[name] = used
            else:
                self._storage_used.pop(name, None)
        self.m_storage_used.set(used, (name,))

    def defer_hint(self, ins, n_bytes: int) -> float:
        """Seconds until a deferred append of ``n_bytes`` could be
        admitted (pacing hint for callers that want to sleep instead of
        spin)."""
        t = self.tenant_for_input(ins)
        if t.bucket is None:
            return 0.0
        return t.bucket.delay_for(n_bytes)

    # -- fair dispatch (driven by Engine.flush_all) ---------------------

    def enqueue(self, ins, chunk) -> None:
        """Stamp the chunk's tenant/priority and queue it for fair
        dispatch. ``ins`` may be None (backlog / recovered / readmitted
        chunks) — the stamp already on the chunk wins, so a chunk keeps
        its class across shed/readmit/restart cycles."""
        name = chunk.qos_tenant
        if name is None and ins is not None:
            # instance-cached resolve: one lookup, not a name round-
            # trip back through the locked tenant() update path
            t = self.tenant_for_input(ins)
        else:
            t = self.tenant(name if name is not None
                            else DEFAULT_TENANT)
        chunk.qos_tenant = t.name
        if chunk.priority is None:
            chunk.priority = t.priority
        with self._lock:
            self._queue.push(chunk.priority, t.name, t.weight,
                             float(chunk.size or 1), chunk)

    def pop_ready(self):
        """Next chunk in strict-priority + DWRR order, or None.

        Pure queue pop — dispatch accounting happens in
        ``note_dispatched`` once the caller KNOWS the chunk got a task
        slot, so a task-map-full repark doesn't double-count the same
        chunk (and pollute the lag histogram) every cycle it waits."""
        with self._lock:
            got = self._queue.pop_ex()
        if got is None:
            return None
        _name, chunk = got
        return chunk

    def note_dispatched(self, chunk) -> None:
        """Count one successful dispatch (called by flush_all after
        ``_dispatch_chunk`` accepted the chunk)."""
        name = chunk.qos_tenant or DEFAULT_TENANT
        self.m_dispatched.inc(1, (name,))
        self.m_lag.observe(max(0.0, time.time() - chunk.created), (name,))

    def drain_pending(self) -> List[Any]:
        """Take every queued chunk (task-map-full parking: the caller
        re-parks them on the engine backlog, preserving fair order)."""
        with self._lock:
            return self._queue.drain()

    def pending_count(self) -> int:
        with self._lock:
            return len(self._queue)

    def update_gauges(self) -> None:
        """Refresh the per-tenant queue gauges (rides the guard's
        housekeeping timer — never a per-chunk cost)."""
        with self._lock:
            pending = self._queue.pending()
            names = list(self._tenants)
        depth: Dict[str, Tuple[int, float]] = {}
        for (_cls, name), (n, cost) in pending.items():
            d = depth.get(name, (0, 0.0))
            depth[name] = (d[0] + n, d[1] + cost)
        for name in names:
            n, cost = depth.get(name, (0, 0.0))
            self.m_queue_chunks.set(n, (name,))
            self.m_queue_bytes.set(cost, (name,))

    def reap_tenants(self) -> None:
        """Drop tenants no live input references (reload commit calls
        this post-swap). A daemon cycling per-customer tenant names
        through periodic reloads must not accumulate one Tenant —
        plus per-tick gauge work in update_gauges/snapshot — per name
        ever declared. Tenants with chunks still in the fair queue are
        kept; a reaped tenant whose stamped chunks later readmit from
        the backlog is re-created on demand at enqueue (the chunk
        carries its priority stamp; the weight reverts to the default
        until an input re-declares the contract)."""
        live = {DEFAULT_TENANT}
        for ins in self.engine.inputs:
            live.add(getattr(ins, "tenant_name", None) or DEFAULT_TENANT)
        with self._lock:
            queued = {name for (_cls, name) in self._queue.pending()}
            dead = [n for n in self._tenants
                    if n not in live and n not in queued]
            for n in dead:
                del self._tenants[n]
            if dead:
                self._graded = len({x.priority for x in
                                    self._tenants.values()}) > 1
        for n in dead:
            # stop publishing depth for a gone tenant (its last value
            # would otherwise linger in the registry forever)
            self.m_queue_chunks.set(0, (n,))
            self.m_queue_bytes.set(0, (n,))

    # -- observability ---------------------------------------------------

    def snapshot(self) -> dict:
        """Per-tenant state for ``/api/v1/health`` + ``/api/v1/qos``."""
        with self._lock:
            tenants = list(self._tenants.values())
            pending = self._queue.pending()
            storage_used = dict(self._storage_used)
        depth: Dict[str, int] = {}
        for (_cls, name), (n, _cost) in pending.items():
            depth[name] = depth.get(name, 0) + n
        out = {}
        for t in tenants:
            out[t.name] = {
                "weight": t.weight,
                "priority": t.priority,
                "rate": t.rate,
                "overflow": t.overflow,
                "queued_chunks": depth.get(t.name, 0),
                "storage_limit": t.storage_limit,
                "storage_used_bytes": storage_used.get(t.name, 0),
                "admitted_bytes": self.m_admitted.get((t.name,)),
                "deferred": self.m_deferred.get((t.name,)),
                "shed_bytes": self.m_shed_in.get((t.name,)),
            }
        return {
            "generation": self.engine.generation,
            "tenants": out,
        }


# ---------------------------------------------------------------------------
# hot config reload — the generation swap
# ---------------------------------------------------------------------------


class ReloadTxn:
    """One atomic configuration change against a RUNNING engine.

    Usage (also wired to ``engine.reload_callback`` by embedders)::

        txn = engine.reload_txn()
        txn.add_output("stdout", match="aux.*")
        txn.replace_filter("grep.0")       # recompile DFA/GrepTables
        txn.remove_input("tail.1")
        gen = txn.commit()

    ``commit()`` builds + initializes every new instance **off-line**
    (this is where grep/parser DFA tables and native ``GrepTables``
    compile — in-flight appends keep using the old objects), then swaps
    the engine's instance lists *by reference* in one ingest-lock
    critical section. The lists are copy-on-write everywhere in the
    engine, so a concurrent append/flush iterating a snapshot reference
    can never observe a torn (half-swapped) configuration; the same
    critical section bumps ``engine.generation`` and
    ``engine.reload_count``, making both atomic with respect to the
    housekeeping timer. In-flight chunks survive: removed inputs'
    pending chunks drain into the dispatch backlog before their
    collectors stop, and removed outputs retire only after their
    in-flight flushes settle (``engine.stop`` reaps their worker
    pools).

    A transaction is single-use; ``commit`` raises on a second call.
    The ``engine.reload_commit`` failpoint fires after the build phase
    and before the swap — the crash window where every new table exists
    but the old generation is still live.
    """

    def __init__(self, engine):
        self.engine = engine
        self._add_inputs: List = []
        self._add_filters: List = []
        self._add_outputs: List = []
        self._remove: Dict[str, set] = {
            "input": set(), "filter": set(), "output": set()}
        self._replace_filters: List[Tuple[str, str, dict]] = []
        self._add_parsers: List[Tuple[str, dict]] = []
        self._remove_parsers: set = set()
        self._committed = False

    # -- staging ---------------------------------------------------------

    def add_input(self, name: str, **props):
        self._add_inputs.append((name, props))
        return self

    def add_filter(self, name: str, **props):
        self._add_filters.append((name, props))
        return self

    def add_output(self, name: str, **props):
        self._add_outputs.append((name, props))
        return self

    def add_input_items(self, name: str, items):
        """Stage an input from a properties ITEM LIST — repeated keys
        (a tail input's several Path rules) and declaration order are
        semantic; the config-file diff driver (core/reload_diff.py)
        stages through these instead of the ``**props`` dict forms."""
        self._add_inputs.append((name, list(items)))
        return self

    def add_filter_items(self, name: str, items):
        self._add_filters.append((name, list(items)))
        return self

    def add_output_items(self, name: str, items):
        self._add_outputs.append((name, list(items)))
        return self

    def remove_input(self, name: str):
        self._remove["input"].add(name)
        return self

    def remove_filter(self, name: str):
        self._remove["filter"].add(name)
        return self

    def remove_output(self, name: str):
        self._remove["output"].add(name)
        return self

    def replace_filter(self, target: str, name: Optional[str] = None,
                       **props):
        """Swap ``target`` (display name) for a freshly built instance
        — with no ``props``, the SAME configuration is recompiled (the
        DFA-recompile-mid-stream shape); the new instance takes the
        old one's chain position."""
        self._replace_filters.append((target, name or "", props))
        return self

    def replace_filter_items(self, target: str, items,
                             name: Optional[str] = None):
        """`replace_filter` from a properties ITEM LIST (see
        `add_input_items`); an empty list means "recompile the same
        configuration" exactly like the no-props dict form."""
        self._replace_filters.append((target, name or "", list(items)))
        return self

    def add_parser(self, name: str, **props):
        self._add_parsers.append((name, props))
        return self

    def remove_parser(self, name: str):
        self._remove_parsers.add(name)
        return self

    # -- commit ----------------------------------------------------------

    @staticmethod
    def _matches(ins, name: str) -> bool:
        return name in (ins.name, ins.display_name)

    def _resolve_removals(self, current, kind: str):
        removed = []
        for name in self._remove[kind]:
            hit = [i for i in current if self._matches(i, name)]
            if not hit:
                raise ValueError(
                    f"reload: unknown {kind} instance {name!r}")
            removed.extend(hit)
        return removed

    def commit(self) -> int:
        if self._committed:
            raise RuntimeError("reload transaction already committed")
        self._committed = True
        engine = self.engine
        # one transaction at a time: the swap writes back keep+new
        # lists derived from this commit's snapshot, so a concurrent
        # commit's changes would be silently lost — only the snapshot
        # taken INSIDE the lock is guaranteed current
        with engine._reload_lock:
            # checked under the lock: engine.stop() sets _stopping and
            # then takes this lock as a barrier, so a commit either
            # completes before stop's retired-output reap or refuses
            # here — never lands retirements on a stopping OR stopped
            # engine (stop() already exited every instance; a commit
            # after it would double-exit removed plugins and strand
            # retirements no housekeeping will ever reap). start()
            # resets the flag, so a restarted engine reloads normally
            if engine._stopping:
                raise RuntimeError("reload: engine is stopping")
            return self._commit_locked(engine)

    def _commit_locked(self, engine) -> int:
        # snapshot references: COW discipline means these lists never
        # mutate under us even while ingest/dispatch keeps running
        cur_inputs = engine.inputs
        cur_filters = engine.filters
        cur_outputs = engine.outputs

        rm_inputs = self._resolve_removals(cur_inputs, "input")
        rm_filters = self._resolve_removals(cur_filters, "filter")
        rm_outputs = self._resolve_removals(cur_outputs, "output")
        # retire removed names BEFORE the build phase numbers the new
        # instances: a same-transaction remove+add of one plugin must
        # not hand the newcomer the dead instance's name (persisted
        # route_names / metric series would re-bind to it). Recording
        # early is safe across an abort — a spuriously retired name
        # only makes numbering skip it, never collide
        with engine._ingest_lock:
            for ins in rm_inputs + rm_filters + rm_outputs:
                engine._retired_names.setdefault(
                    type(ins).__name__, set()).add(ins.name)
        replaced_ids: set = set()
        for target, _n, _p in self._replace_filters:
            hit = [f for f in cur_filters if self._matches(f, target)]
            if not hit:
                raise ValueError(
                    f"reload: unknown filter instance {target!r}")
            if any(f in rm_filters for f in hit):
                raise ValueError(
                    f"reload: filter {target!r} is both removed and "
                    "replaced in the same transaction")
            # two replaces of one slot would silently drop the first
            # built twin un-exited (its hidden emitter leaks) and exit
            # the old instance twice
            ids = {id(f) for f in hit}
            if ids & replaced_ids:
                raise ValueError(
                    f"reload: filter {target!r} replaced twice in the "
                    "same transaction")
            replaced_ids |= ids

        # ---- build phase (off-line: the expensive part) ----
        # parsers first: a new filter may resolve a new parser at init.
        # The dict swap is an atomic reference assignment and filters
        # resolve parser objects at init (the old generation keeps its
        # own references) — but a LATER build failure must not leave
        # the new parser dict live while everything else stays on the
        # old generation, so the whole phase unwinds on error below.
        # same contract as _resolve_removals: a typo'd parser name must
        # abort the transaction, not silently leave the parser live
        unknown_parsers = self._remove_parsers - set(engine.parsers)
        if unknown_parsers:
            raise ValueError(
                f"reload: unknown parser(s) {sorted(unknown_parsers)}")
        old_parsers = engine.parsers
        new_parsers = {k: v for k, v in engine.parsers.items()
                       if k not in self._remove_parsers}
        from ..parsers import create_parser

        for name, props in self._add_parsers:
            p = create_parser(name, **props)
            new_parsers[name] = p
        engine.parsers = new_parsers

        built: List = []  # every new instance, for unwind on failure

        def build(kind, create, staged, peers):
            out = []
            for name, props in staged:
                ins = engine._make_instance(create, name, props,
                                            peers + out)
                engine._init_instance(ins)
                out.append(ins)
                built.append(ins)
            return out

        keep_inputs = [i for i in cur_inputs if i not in rm_inputs]
        keep_filters = [f for f in cur_filters if f not in rm_filters]
        keep_outputs = [o for o in cur_outputs if o not in rm_outputs]

        try:
            new_inputs = build("input", engine.registry.create_input,
                               self._add_inputs, keep_inputs)
            new_outputs = build("output", engine.registry.create_output,
                                self._add_outputs, keep_outputs)

            # filter replacements: build the twin, remember the slot
            replacements: Dict[int, Any] = {}
            swapped_out: List = []
            for target, name, props in self._replace_filters:
                idx, old = next(
                    (i, f) for i, f in enumerate(keep_filters)
                    if self._matches(f, target))
                plugin_name = name or old.plugin.name
                ins = engine.registry.create_filter(plugin_name)
                # the replacement KEEPS the old instance's identity
                # (name / alias): metrics series and route continuity
                # survive the recompile
                ins.name = old.name
                built.append(ins)
                # the properties ITEM LIST, not a dict: repeated keys
                # (a grep filter's several Regex rules) and declaration
                # order are semantic. replace_filter_items stages the
                # list directly; the dict form converts here
                if hasattr(props, "items"):
                    items = list(props.items()) if props \
                        else old.properties.items()
                else:
                    items = props or old.properties.items()
                for k, v in items:
                    ins.set(k, v)
                engine._init_instance(ins)
                replacements[idx] = ins
                swapped_out.append(old)
            next_filters = [replacements.get(i, f)
                            for i, f in enumerate(keep_filters)]
            add_filters = build("filter", engine.registry.create_filter,
                                self._add_filters, next_filters)
            if _fp.ACTIVE:
                # crash window: every new table compiled, old
                # generation still live — recovery must come up on the
                # OLD config. An injected (non-crash) error aborts
                # through the same unwind as a build failure
                _fp.fire("engine.reload_commit")
        except BaseException:
            # abort with the OLD generation fully intact: un-swap the
            # parser dict and tear down whatever was already built —
            # nothing new is reachable from the engine yet, EXCEPT
            # hidden emitters the built filters' inits registered
            # (engine.hidden_input COW-appends them): unlink those too
            engine.parsers = old_parsers
            built_ids = {id(b) for b in built}
            orphans = [i for i in engine.inputs
                       if getattr(i, "_hidden_owner", None) is not None
                       and id(i._hidden_owner) in built_ids]
            if orphans:
                with engine._ingest_lock:
                    engine.inputs = [i for i in engine.inputs
                                     if i not in orphans]
            for ins in built + orphans:
                if getattr(ins, "_initialized", False):
                    try:
                        ins.plugin.exit()
                    except Exception:
                        log.exception(
                            "reload abort: built instance %s exit "
                            "failed", ins.display_name)
            raise
        # added filters keep engine.filter()'s ordering contract: user
        # filters run BEFORE hidden flux-SQL filters
        pos = len(next_filters)
        while pos > 0 and getattr(next_filters[pos - 1],
                                  "_flux_sql_hidden", False):
            pos -= 1
        next_filters[pos:pos] = add_filters

        # ---- swap phase (one critical section) ----
        # hidden emitters ride their owner's lifecycle: a removed or
        # replaced filter's (or removed input's) emitter must unlink
        # with it, or every reload leaks one initialized input whose
        # dead pool flush_all would drain forever. Emitters created by
        # the build phase belong to NEW owners and are untouched.
        dead_owners = {id(x) for x in rm_inputs + rm_filters
                       + swapped_out}
        orphan_emitters = [
            i for i in engine.inputs
            if getattr(i, "_hidden_owner", None) is not None
            and id(i._hidden_owner) in dead_owners]
        rm_inputs = rm_inputs + orphan_emitters

        # new inputs' tenant contracts register BEFORE the swap makes
        # them ingestable (same eager rule as engine.start: a flood
        # must never beat its own quota declaration)
        for ins in new_inputs:
            engine.qos.tenant_for_input(ins)

        drained = []
        with engine._ingest_lock:
            for ins in rm_inputs:
                with ins.ingest_lock:
                    # flag BEFORE draining, under the input's own lock:
                    # a parallel-raw append blocked on ingest_lock
                    # otherwise lands in the pool right after the drain
                    # and is acked into an orphaned pool flush_all will
                    # never visit again. Append paths re-check
                    # ins.removed under this lock and refuse (the
                    # caller sees 0 ingested — un-acked, so
                    # at-least-once holds)
                    ins.removed = True
                    pool_chunks = ins.pool.drain()
                t = engine.qos.tenant_for_input(ins)
                for chunk in pool_chunks:
                    # keep the removed input's tenant identity: these
                    # chunks re-enter dispatch via the backlog, where
                    # enqueue(None, ...) has no input to resolve from
                    # — without the stamp a top-priority tenant's
                    # in-flight data would be re-classed to the
                    # default tenant (and its shed watermark) exactly
                    # during the reload
                    if chunk.qos_tenant is None:
                        chunk.qos_tenant = t.name
                    if chunk.priority is None:
                        chunk.priority = t.priority
                    if engine.storage is not None and \
                            ins.storage_type == "filesystem":
                        try:
                            engine.storage.finalize(chunk)
                        except Exception:
                            # disk full / storage fault mid-swap: the
                            # swap section has no abort path (inputs
                            # are already flagged removed), so a
                            # finalize error must not wedge a half-
                            # committed generation. The chunk still
                            # reaches the backlog in memory — delivery
                            # proceeds; only crash-recovery durability
                            # for THIS chunk is degraded
                            log.exception(
                                "reload: finalize of drained chunk "
                                "from %s failed; chunk kept in-memory",
                                ins.display_name)
                drained.extend(pool_chunks)
            engine._backlog.extend(drained)
            # re-resolve against the LIVE list: the build phase's
            # plugin inits may have appended hidden emitter inputs
            # (rewrite_tag / log_to_metrics pattern) that must survive
            live_inputs = [i for i in engine.inputs
                           if i not in rm_inputs]
            # conditional-routing bitmasks index the OLD outputs list.
            # Dispatch resolves persisted route NAMES first, so the
            # chunks themselves are reload-proof — but the pool's
            # active map KEYS on the mask value, so a post-swap append
            # computing the same mask against the NEW outputs would
            # merge into an old-generation chunk and inherit its stale
            # names. Rotate those chunks closed; fresh appends open
            # fresh chunks with names from the new list.
            # only when the outputs list actually changes: a parser- or
            # filter-only reload leaves every mask valid, and rotating
            # anyway would fragment in-progress conditional chunks on
            # each DFA recompile
            if rm_outputs or new_outputs:
                for src in live_inputs:
                    with src.ingest_lock:
                        src.pool.rotate_conditional()
            engine.inputs = live_inputs + new_inputs
            engine.filters = next_filters
            engine.outputs = keep_outputs + new_outputs
            engine.generation += 1
            engine.reload_count += 1
            gen = engine.generation
            # rm_* names were retired before the build phase (so a
            # same-transaction add can't take them); the orphan
            # emitters discovered since retire here. Replacements are
            # NOT retired — the twin keeps the name by design
            for ins in orphan_emitters:
                engine._retired_names.setdefault(
                    type(ins).__name__, set()).add(ins.name)

        # ---- post-swap (old generation unreachable for new work;
        # ins.removed was already flagged inside the swap section) ----
        # chunk-trace taps hold their target instance (and its pool)
        # alive through engine.traces; a stale entry also blocks
        # re-enabling the trace on a same-named replacement input
        with engine._ingest_lock:
            for ins in rm_inputs:
                ctx = engine.traces.get(ins.name)
                if ctx is not None and ctx["input"] is ins:
                    engine.traces.pop(ins.name, None)
        for ins in rm_inputs:
            thread = getattr(ins, "collector_thread", None)
            if thread is not None and (
                    thread.is_alive()
                    or getattr(ins, "_exited_by_collector", False)):
                # the collector thread owns the plugin's I/O: it sees
                # ins.removed at its next tick, unwinds, and calls
                # plugin.exit() itself — exiting here would close
                # files/sockets under an in-flight collect(), and the
                # flag covers the race where it already exited between
                # the swap and this check (a dead thread with the flag
                # unset means the engine stopped it pre-removal:
                # nothing is in flight, inline exit is safe and the
                # only exit this input will get)
                continue
            task = ins.collector_task
            if task is not None and engine.loop is not None \
                    and not engine.loop.is_closed():
                # cancel on the loop and exit only AFTER the task has
                # unwound (done callback runs on the loop thread), so
                # exit() never races a collect/server coroutine
                def _exit_done(_t, _ins=ins):
                    try:
                        _ins.plugin.exit()
                    except Exception:
                        log.exception("removed input %s exit failed",
                                      _ins.display_name)

                def _cancel(_t=task, _cb=_exit_done):
                    _t.add_done_callback(_cb)
                    _t.cancel()

                try:
                    engine.loop.call_soon_threadsafe(_cancel)
                    continue
                except RuntimeError:
                    pass  # loop already shut down: nothing in flight
            try:
                ins.plugin.exit()
            except Exception:
                log.exception("removed input %s exit failed",
                              ins.display_name)
        for f in rm_filters + swapped_out:
            try:
                f.plugin.exit()
            except Exception:
                log.exception("removed filter %s exit failed",
                              f.display_name)
        # removed outputs: in-flight tasks hold direct references and
        # finish normally; pools are reaped by housekeeping (or stop()).
        # Under _ingest_lock: _reap_retired_outputs does a read-filter-
        # replace of this list under the same lock, and an unlocked
        # extend racing that replace would vanish — the output would
        # then never be reaped, not even at stop()
        with engine._ingest_lock:
            engine._retired_outputs.extend(rm_outputs)
        for ins in new_inputs:
            engine.ensure_collector(ins)
        if engine.running:
            for out in new_outputs:
                engine._ensure_worker_pool(out)

        qos = engine.qos
        qos.reap_tenants()
        qos.m_generation.set(gen)
        qos.m_reloads.inc(1)
        log.info(
            "qos: reload generation %d committed (+%d/-%d inputs, "
            "+%d/-%d/%d~ filters, +%d/-%d outputs)", gen,
            len(new_inputs), len(rm_inputs), len(add_filters),
            len(rm_filters), len(self._replace_filters),
            len(new_outputs), len(rm_outputs))
        return gen
