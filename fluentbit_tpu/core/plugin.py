"""Plugin model — vtables, instances, registry.

Reference: the C plugin vtables flb_input_plugin / flb_filter_plugin /
flb_output_plugin (include/fluent-bit/flb_input.h, flb_filter.h,
flb_output.h) with cb_init/cb_collect/cb_filter/cb_flush/cb_exit, and the
per-instance property machinery in src/flb_input.c / flb_output.c /
flb_filter.c. Plugins here are Python classes registered by name; the
registry replaces the cmake plugin gating (cmake/plugins_options.cmake).
"""

from __future__ import annotations

import contextvars
import enum
import logging
import threading
from typing import Any, Callable, Dict, List, Optional, Type

from .config import ConfigMapEntry, Properties, apply_config_map
from .lockorder import make_lock
from .router import Route
from ..codec.chunk import Chunk, ChunkPool, EVENT_TYPE_LOGS

log = logging.getLogger("flb")

# The chunk whose payload the CURRENT flush attempt is delivering,
# exposed to output plugins the same way the guard's cooperative-cancel
# event is (core/guard.py CANCEL_EVENT): set by the engine around
# plugin.flush, re-set on worker loops (contextvars do not cross
# run_coroutine_threadsafe). Outputs that relay pipeline metadata —
# out_forward propagating the chunk's tenant/priority stamps across the
# fan-in hop — read it instead of growing the flush() signature that
# every registered output implements.
FLUSH_CHUNK: "contextvars.ContextVar[Optional[Chunk]]" = \
    contextvars.ContextVar("flb_flush_chunk", default=None)


class FlushResult(enum.Enum):
    """Output flush verdicts (reference FLB_OK/FLB_RETRY/FLB_ERROR,
    include/fluent-bit/flb_output.h FLB_OUTPUT_RETURN)."""

    OK = 1
    RETRY = 2
    ERROR = 3


class FilterResult(enum.Enum):
    """Filter verdicts (FLB_FILTER_NOTOUCH / FLB_FILTER_MODIFIED)."""

    NOTOUCH = 1
    MODIFIED = 2


class Plugin:
    """Common plugin base."""

    name: str = ""
    description: str = ""
    config_map: List[ConfigMapEntry] = []
    # event types the plugin handles (logs/metrics/traces); logs by default
    event_types = (EVENT_TYPE_LOGS,)

    def __init__(self) -> None:
        self.instance: Optional["Instance"] = None

    # lifecycle
    def init(self, instance: "Instance", engine) -> None:  # cb_init
        pass

    def exit(self) -> None:  # cb_exit
        pass


class InputPlugin(Plugin):
    """Input vtable. Collect models supported:
    - interval collectors: declare ``collect_interval`` (seconds) and
      implement ``collect(engine)`` — flb_input_set_collector_time
    - server inputs: implement ``start_server(engine)`` returning an
      awaitable/task — the in_http/in_forward style
    - library inputs: expose ``push`` for direct injection (in_lib)
    """

    default_tag: Optional[str] = None
    collect_interval: Optional[float] = None
    threaded_capable: bool = False

    def collect(self, engine) -> None:
        pass

    async def start_server(self, engine) -> None:
        pass

    def pause(self) -> None:  # cb_pause (backpressure)
        pass

    def resume(self) -> None:  # cb_resume
        pass


class FilterPlugin(Plugin):
    """Filter vtable: ``filter(events, tag) -> (FilterResult, events')``.

    The reference cb_filter gets the whole chunk msgpack buffer
    (src/flb_filter.c:202-210); here filters get the decoded event list for
    the chunk-sized append and return a replacement list (or the same list
    with NOTOUCH). Byte-level identity for untouched records is preserved
    because events carry their raw spans (event.raw) and the chunk writer
    re-uses them verbatim.

    Batched fast path: a filter may additionally advertise
    ``can_process_batch()`` and implement ``process_batch(chunk)`` over a
    :class:`~fluentbit_tpu.core.chunk_batch.RawChunk` — the engine then
    routes whole appends through it on the raw ingest path (no Python
    decode), exactly like filter_grep's ``filter_raw``. The hook returns
    ``(n_records_out, data_out)`` or ``(n_out, data_out, n_in)`` (when
    the batch pass discovered the input record count), or None to
    decline — the engine then falls back to the bit-exact per-record
    path, so exotic option combinations cost nothing but the fallback.
    """

    #: True when the raw/batched path is pure (immutable config, no
    #: cross-record state): the engine may then run the chain for
    #: multiple inputs in parallel under per-input locks only
    thread_safe_raw: bool = False

    def filter(self, events: list, tag: str, engine) -> tuple:
        return (FilterResult.NOTOUCH, events)

    def can_process_batch(self) -> bool:
        """True when ``process_batch`` can serve this instance's
        configuration (checked per append; cheap)."""
        return False

    def process_batch(self, chunk) -> Optional[tuple]:
        """Whole-chunk batched execution; None declines to per-record."""
        return None


class OutputPlugin(Plugin):
    """Output vtable: async ``flush(chunk_bytes, tag) -> FlushResult``."""

    synchronous: bool = False  # FLB_OUTPUT_SYNCHRONOUS
    no_multiplex: bool = False  # FLB_OUTPUT_NO_MULTIPLEX

    async def flush(self, data: bytes, tag: str, engine) -> FlushResult:
        return FlushResult.OK


class CustomPlugin(Plugin):
    """Custom vtable (reference src/flb_custom.c, flb_custom_init_all at
    src/flb_engine.c:973): initialized BEFORE the pipeline plugins; a
    custom may create input/filter/output instances programmatically
    (the calyptia control-plane pattern)."""


class ProcessorPlugin(Plugin):
    """Processor vtable — per-instance pipelines with stages/conditions
    (reference src/flb_processor.c). Runs on decoded events at input ingest
    or output flush."""

    def process_logs(self, events: list, tag: str, engine) -> list:
        return events

    def process_metrics(self, contexts: list, tag: str, engine) -> list:
        return contexts

    def process_traces(self, spans: list, tag: str, engine) -> list:
        return spans


class Instance:
    """A configured plugin instance (flb_input_instance etc.)."""

    def __init__(self, plugin: Plugin, kind: str):
        self.plugin = plugin
        self.kind = kind  # input|filter|output|processor|custom
        # provisional name; the engine re-numbers per context
        # (reference: instance names are in_emitter.0 style, per flb_config)
        self.name = f"{plugin.name}.0"
        self.alias: Optional[str] = None
        self.properties = Properties()
        self.route = Route(match="*")
        plugin.instance = self

    def set(self, key: str, value: Any) -> None:
        self.properties.set(key, value)

    def prop(self, key: str, default: Any = None) -> Any:
        return self.properties.get(key, default)

    def configure(self) -> None:
        """Apply config_map + core keys."""
        apply_config_map(self.plugin.config_map, self.properties, self.plugin)
        self.alias = self.properties.get("alias")
        match = self.properties.get("match")
        match_regex = self.properties.get("match_regex")
        if match or match_regex:
            self.route = Route(match=match, match_regex=match_regex)

    @property
    def display_name(self) -> str:
        return self.alias or self.name


class InputInstance(Instance):
    def __init__(self, plugin: InputPlugin):
        super().__init__(plugin, "input")
        self.pool = ChunkPool(self.name)
        self.tag: Optional[str] = None
        self.mem_buf_limit: int = 0  # 0 = unlimited
        self.paused = False
        self.storage_type = "memory"
        self.processors: List = []  # input-side processor pipeline
        self.collector_task = None
        self.threaded = False  # run the collector on its own OS thread
        self.collector_thread = None
        self.removed = False  # set by hot reload: collectors stop
        self.paused_by_qos = False  # quota DEFER pause (engine resume)
        # fbtpu-qos tenant membership (core/qos.py): resolved lazily
        # and cached as _qos_tenant on first admission
        self.tenant_name: Optional[str] = None
        self.tenant_params: dict = {}
        # serializes this input's pool: every append/drain of this
        # input's chunks holds it, so raw-path ingest can run WITHOUT
        # the engine-global lock when the filter chain allows (reference:
        # per-input chunk maps, src/flb_input_log.c:1524). RLock — the
        # global-lock paths nest it around their pool touches.
        self.ingest_lock = make_lock("InputInstance.ingest_lock",
                                     reentrant=True)

    def set_paused(self, paused: bool) -> bool:
        """Atomically flip the backpressure flag and fire the plugin's
        cb_pause/cb_resume (src/flb_input.c:740-788). Ingest threads and
        the engine loop both reach the check-then-act; without the lock
        two appends crossing the limit double-fire pause() (fbtpu-lint
        guarded-by: `paused`). Collectors still READ the flag lock-free
        — transient staleness there only delays a collect tick."""
        with self.ingest_lock:
            if self.paused == paused:
                return False
            self.paused = paused
            cb = self.plugin.pause if paused else self.plugin.resume
            try:
                cb()
            except Exception:
                log.exception("%s %s callback failed", self.display_name,
                              "pause" if paused else "resume")
        return True

    def configure(self) -> None:
        super().configure()
        # default tag = per-instance name (dummy.0, dummy.1, ...) so two
        # instances of the same plugin never merge streams (reference:
        # instance tag defaults to the instance name)
        self.tag = self.properties.get("tag") or self.plugin.default_tag or self.name
        from .config import parse_bool, parse_size
        mbl = self.properties.get("mem_buf_limit")
        self.mem_buf_limit = parse_size(mbl) if mbl else 0
        self.storage_type = self.properties.get("storage.type", "memory")
        # storage.pause_on_chunks_overlimit (src/flb_input.c:169):
        # filesystem-backed inputs pause at storage.max_chunks_up
        self.pause_on_chunks_overlimit = parse_bool(
            self.properties.get("storage.pause_on_chunks_overlimit", False)
        )
        # threaded collector (reference FLB_INPUT_THREADED /
        # `threaded on`, src/flb_input_thread.c:225): collection work
        # runs on a dedicated OS thread; the append path stays
        # thread-safe via the engine's ingest locking
        self.threaded = parse_bool(self.properties.get("threaded", False))
        # fbtpu-qos tenant declaration (QOS.md): `tenant <name>` joins
        # the input to a tenant; tenant.* keys declare that tenant's
        # contract (last declaration wins, so one input can carry the
        # contract for a tenant several inputs share)
        self.tenant_name = self.properties.get("tenant")
        params: dict = {}
        w = self.properties.get("tenant.weight")
        if w is not None:
            params["weight"] = float(w)
        pr = self.properties.get("tenant.priority")
        if pr is not None:
            params["priority"] = int(pr)
        rate = self.properties.get("tenant.rate")
        if rate is not None:
            params["rate"] = float(parse_size(rate))  # bytes/second
        burst = self.properties.get("tenant.burst")
        if burst is not None:
            params["burst"] = float(parse_size(burst))
        sl = self.properties.get("tenant.storage_limit")
        if sl is not None:
            # cap on the tenant's LIVE filesystem footprint (bytes of
            # stream chunk files); over it, write-through is shed and
            # the chunk stays memory-only (Qos.admit_storage)
            params["storage_limit"] = int(parse_size(sl))
        fc = self.properties.get("tenant.flush_concurrency")
        if fc is not None:
            # cap on the tenant's concurrent flush attempts across all
            # outputs (QOS.md); enforced next to the per-output worker
            # semaphore in engine._flush_body
            fc = int(fc)
            if fc < 1:
                raise ValueError(
                    f"tenant.flush_concurrency must be >= 1, got {fc}")
            params["flush_concurrency"] = fc
        ovf = self.properties.get("tenant.overflow")
        if ovf is not None:
            ovf = str(ovf).lower()
            if ovf not in ("defer", "shed"):
                raise ValueError(
                    f"tenant.overflow must be defer|shed, got {ovf!r}")
            params["overflow"] = ovf
        self.tenant_params = params


class FilterInstance(Instance):
    def __init__(self, plugin: FilterPlugin):
        super().__init__(plugin, "filter")


class OutputInstance(Instance):
    def __init__(self, plugin: OutputPlugin):
        super().__init__(plugin, "output")
        self.retry_limit: Optional[int] = None  # None → service default
        # fbtpu-guard per-output flush deadline (None → service
        # guard.flush_timeout → 2×grace; core/guard.py)
        self.flush_timeout: Optional[float] = None
        self.workers: int = 0
        self.processors: List = []
        # flush-concurrency bound, built at configure():
        # synchronous/no_multiplex → 1; workers N → N; else unbounded
        self.flush_semaphore = None
        # test hooks (reference: flb_output_set_test / test_formatter mode,
        # src/flb_engine_dispatch.c:101-137)
        self.test_formatter: Optional[Callable] = None
        self.http2 = False  # prior-knowledge h2c delivery
        self.proxy = None   # (host, port) of an http:// forward proxy
        self.worker_pool = None  # OutputWorkerPool when workers > 0
        # ingest-time conditional route (flb_router_condition.c):
        # records failing the condition never enter this output's chunks
        self.route_condition = None

    def configure(self) -> None:
        super().configure()
        from .config import parse_bool

        conds = self.properties.get_all("route_condition")
        if conds:
            from .conditions import Condition, Rule

            rules = []
            for c in conds:
                parts = c.split(None, 2) if isinstance(c, str) else list(c)
                if len(parts) < 2:
                    raise ValueError(
                        f"route_condition needs 'field op [value]': {c!r}")
                field, op = parts[0], parts[1]
                value: object = parts[2] if len(parts) > 2 else None
                # numeric coercion ONLY for ordering ops — eq/neq on a
                # numeric-looking STRING field must stay expressible
                if isinstance(value, str) and op.lower() in (
                        "gt", "lt", "gte", "lte"):
                    try:
                        value = int(value)
                    except ValueError:
                        try:
                            value = float(value)
                        except ValueError:
                            pass
                rules.append(Rule(field, op, value))
            self.route_condition = Condition(rules, "and")

        # fail fast on a bad value (config_map-typed options do the
        # same); an invalid bool must not surface per-flush
        self.http2 = parse_bool(self.properties.get("http2", False))
        pxy = self.properties.get("proxy")
        if pxy:
            # reference proxy_parse (flb_http_client.c:744): http:// only
            # (https proxies are an explicit FIXME there too)
            from urllib.parse import urlsplit
            if "://" not in pxy:
                pxy = "http://" + pxy
            parts = urlsplit(pxy)
            if parts.scheme != "http":
                raise ValueError(
                    f"proxy: only http:// proxies are supported, got {pxy!r}")
            self.proxy = (parts.hostname, parts.port or 80)
            if parts.username:
                import base64 as _b64
                cred = f"{parts.username}:{parts.password or ''}"
                self.proxy_auth = "Basic " + _b64.b64encode(
                    cred.encode()).decode()
            else:
                self.proxy_auth = None
        ft = self.properties.get("flush_timeout")
        if ft is not None:
            from .config import parse_time
            self.flush_timeout = parse_time(ft)
        rl = self.properties.get("retry_limit")
        if rl is not None:
            if str(rl).lower() in ("no_limits", "false", "no_retries_forever", "unlimited"):
                self.retry_limit = -1
            else:
                self.retry_limit = int(rl)
        w = self.properties.get("workers")
        if w is not None:
            self.workers = int(w)
        import asyncio as _asyncio
        from .config import parse_bool as _pb

        if self.plugin.synchronous or self.plugin.no_multiplex or \
                _pb(self.properties.get("no_multiplex", False)):
            self.flush_semaphore = _asyncio.Semaphore(1)
        elif self.workers > 0:
            self.flush_semaphore = _asyncio.Semaphore(self.workers)


class Registry:
    """Plugin name → class registry for all plugin kinds."""

    def __init__(self) -> None:
        self.inputs: Dict[str, Type[InputPlugin]] = {}
        self.filters: Dict[str, Type[FilterPlugin]] = {}
        self.outputs: Dict[str, Type[OutputPlugin]] = {}
        self.processors: Dict[str, Type[ProcessorPlugin]] = {}
        self.customs: Dict[str, Type[CustomPlugin]] = {}

    def register(self, cls: Type[Plugin]) -> Type[Plugin]:
        if issubclass(cls, InputPlugin):
            self.inputs[cls.name] = cls
        elif issubclass(cls, FilterPlugin):
            self.filters[cls.name] = cls
        elif issubclass(cls, OutputPlugin):
            self.outputs[cls.name] = cls
        elif issubclass(cls, ProcessorPlugin):
            self.processors[cls.name] = cls
        elif issubclass(cls, CustomPlugin):
            self.customs[cls.name] = cls
        else:
            raise TypeError(f"unknown plugin kind {cls!r}")
        return cls

    def create_input(self, name: str) -> InputInstance:
        return InputInstance(self._get(self.inputs, name, "input")())

    def create_filter(self, name: str) -> FilterInstance:
        return FilterInstance(self._get(self.filters, name, "filter")())

    def create_output(self, name: str) -> OutputInstance:
        return OutputInstance(self._get(self.outputs, name, "output")())

    def create_processor(self, name: str):
        inst = Instance(self._get(self.processors, name, "processor")(), "processor")
        return inst

    def create_custom(self, name: str):
        return Instance(self._get(self.customs, name, "custom")(),
                        "custom")

    @staticmethod
    def _get(table: dict, name: str, kind: str):
        cls = table.get(name)
        if cls is None:
            raise ValueError(f"unknown {kind} plugin {name!r} (have: {sorted(table)})")
        return cls


#: Global default registry; plugins self-register at import via
#: ``@registry.register``.
registry = Registry()
