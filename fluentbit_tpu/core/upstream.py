"""Upstream connection pools + HA node sets.

Reference: src/flb_upstream.c (per-destination pools with keepalive —
`net.keepalive`, `net.keepalive_idle_timeout`, `net.keepalive_max_recycle`
config map at flb_upstream.c:63-90) and src/flb_upstream_ha.c +
flb_upstream_node.c (named upstream files with weighted [NODE] sections
used by out_forward). The TPU build's clients are asyncio streams; a
pooled connection is an (reader, writer) pair parked until the idle
timeout.
"""

from __future__ import annotations

import asyncio
import time
from typing import List, Optional, Tuple

from .config import parse_bool, parse_time

# -- async DNS with TTL cache (the c-ares role: src/flb_net_dns.h) --
# asyncio's default resolver blocks a thread per lookup and re-resolves
# every dial; outputs dial per flush, so a short-TTL cache removes the
# lookup from the hot path.

_dns_cache: dict = {}
_DNS_TTL = 30.0


def close_quietly(writer) -> None:
    """Best-effort transport teardown — THE close for every socket
    error/exit path. ``StreamWriter.close()`` raises ``OSError`` on an
    already-dead transport and ``RuntimeError`` on a closed owning
    loop; both mean "nothing left to close". Anything else is a real
    bug and propagates (fbtpu-lint swallowed-error stance)."""
    try:
        writer.close()
    except (OSError, RuntimeError):
        pass


async def resolve(host: str, port: int) -> List[str]:
    """Every resolved address for host, in getaddrinfo preference order
    (literal addresses pass through as a single entry). Callers must
    keep the multi-address connect fallback — returning one address
    would break dual-stack / multi-A-record destinations."""
    import ipaddress
    import socket

    try:
        ipaddress.ip_address(host)
        return [host]
    except ValueError:
        pass
    now = time.time()
    hit = _dns_cache.get((host, port))
    if hit is not None and hit[1] > now:
        return hit[0]
    import asyncio as _asyncio

    loop = _asyncio.get_running_loop()
    infos = await loop.getaddrinfo(host, port,
                                   type=socket.SOCK_STREAM)
    addrs: List[str] = []
    for info in infos:
        a = info[4][0]
        if a not in addrs:
            addrs.append(a)
    _dns_cache[(host, port)] = (addrs, now + _DNS_TTL)
    if len(_dns_cache) > 512:
        # bound the cache for real: evict the soonest-expiring entries
        # (an expired-only sweep removes nothing when all are live)
        for k in sorted(_dns_cache, key=lambda k: _dns_cache[k][1])[
                : len(_dns_cache) - 512]:
            _dns_cache.pop(k, None)
    return addrs


def invalidate_dns(host: str, port: int) -> None:
    _dns_cache.pop((host, port), None)


class Upstream:
    """Keepalive pool for one destination (flb_upstream equivalent).

    ``get()`` pops a live idle connection or dials a new one;
    ``release(reusable=True)`` parks it for reuse. Dead idles (peer
    closed, idle timeout, recycle count exceeded) are dropped on pop.
    """

    def __init__(self, instance, host: str, port: int,
                 connect_timeout: float = 10.0):
        self.instance = instance
        self.host = host
        self.port = port
        self.connect_timeout = connect_timeout
        props = getattr(instance, "properties", None)
        get = props.get if props is not None else (lambda *a: None)
        self.keepalive = parse_bool(get("net.keepalive", True))
        # TIME-typed in the reference: "30s" etc. must parse
        self.idle_timeout = parse_time(
            get("net.keepalive_idle_timeout", 30) or 30)
        self.max_recycle = int(get("net.keepalive_max_recycle", 0) or 0)
        self.max_idle = int(get("net.max_worker_connections", 4) or 4)
        # parked connections are keyed by their OWNING event loop: with
        # output worker threads (flb_output_thread.c) flushes run on
        # several loops, and an asyncio stream must only be awaited on
        # the loop that created it (the reference keeps per-worker
        # keepalive queues for the same reason)
        self._idle: dict = {}  # loop -> [(reader, writer, parked, uses)]

    def _bucket(self) -> List[tuple]:
        loop = asyncio.get_running_loop()
        return self._idle.setdefault(loop, [])

    def _sweep(self, bucket: List[tuple], now: float) -> None:
        """Close idles past the timeout — LIFO reuse would otherwise
        strand the oldest parked sockets forever (the reference's
        keepalive sweep runs off the 1.5s housekeeping timer)."""
        keep = []
        for entry in bucket:
            if now - entry[2] > self.idle_timeout:
                self._close(entry[1])
            else:
                keep.append(entry)
        bucket[:] = keep

    async def get(self) -> Tuple[object, object, bool, int]:
        """(reader, writer, reused, use_count)."""
        now = time.time()
        bucket = self._bucket()
        self._sweep(bucket, now)  # the single expiry path
        while bucket:
            reader, writer, parked, uses = bucket.pop()
            if reader.at_eof() or writer.is_closing():
                self._close(writer)
                continue
            return reader, writer, True, uses
        from .tls import open_connection

        reader, writer = await open_connection(
            self.instance, self.host, self.port,
            timeout=self.connect_timeout)
        return reader, writer, False, 0

    def release(self, reader, writer, reusable: bool,
                use_count: int = 0) -> None:
        bucket = self._bucket()
        self._sweep(bucket, time.time())
        if (not reusable or not self.keepalive
                or writer.is_closing()
                or len(bucket) >= self.max_idle
                or (self.max_recycle and use_count + 1
                    >= self.max_recycle)):
            self._close(writer)
            return
        bucket.append((reader, writer, time.time(), use_count + 1))

    def _close(self, writer) -> None:
        close_quietly(writer)

    def close(self) -> None:
        """May run on any thread (plugin exit): sockets parked on other
        loops are closed on their owning loop."""
        for loop, bucket in list(self._idle.items()):
            while bucket:
                _, writer, _, _ = bucket.pop()
                try:
                    running = asyncio.get_running_loop()
                except RuntimeError:
                    running = None
                if loop is running or loop.is_closed():
                    self._close(writer)
                else:
                    try:
                        loop.call_soon_threadsafe(self._close, writer)
                    except RuntimeError:
                        self._close(writer)
        self._idle.clear()


class UpstreamNode:
    __slots__ = ("name", "host", "port", "weight", "properties",
                 "breaker")

    def __init__(self, name: str, host: str, port: int,
                 weight: int = 1, properties=None):
        self.name = name
        self.host = host
        self.port = port
        self.weight = max(1, int(weight))
        self.properties = properties or {}
        # node health IS a circuit breaker (fbtpu-guard): one failure
        # opens it for the HA set's retry_window, ``available()``
        # re-admits it for a probe, an explicit mark_up closes it —
        # the same state machine that guards whole outputs in
        # core/guard.py, so node and output health read identically
        # on dashboards and in /api/v1/health
        from .guard import CircuitBreaker

        self.breaker = CircuitBreaker(name, failures=1, cooldown=10.0)


class UpstreamHA:
    """Weighted node set with failover (flb_upstream_ha.c).

    ``pick()`` is smooth weighted round-robin over healthy nodes —
    healthy meaning the node's breaker would admit a request
    (closed, or cooled down enough for a probe); ``mark_down(node)``
    records a failure (one failure opens the node's breaker for
    ``retry_window`` seconds), ``mark_up(node)`` force-closes it.
    When every node is down, picks proceed anyway (the caller surfaces
    the delivery error — parity with the reference, which never
    blackholes silently)."""

    def __init__(self, name: str, nodes: List[UpstreamNode],
                 retry_window: float = 10.0):
        self.name = name
        self.nodes = nodes
        self.retry_window = retry_window
        for n in nodes:
            n.breaker.cooldown = retry_window
        self._current = {n.name: 0 for n in nodes}

    def pick(self) -> Optional[UpstreamNode]:
        if not self.nodes:
            return None
        candidates = [n for n in self.nodes if n.breaker.available()]
        if not candidates:
            candidates = self.nodes  # all down: let the caller fail
        total = sum(n.weight for n in candidates)
        best = None
        for n in candidates:
            self._current[n.name] += n.weight
            if best is None or self._current[n.name] > \
                    self._current[best.name]:
                best = n
        self._current[best.name] -= total
        return best

    def mark_down(self, node: UpstreamNode) -> None:
        node.breaker.record_failure()

    def mark_up(self, node: UpstreamNode) -> None:
        node.breaker.reset()


def parse_upstream_file(path: str) -> UpstreamHA:
    """Load an upstream definition file — classic-INI [UPSTREAM] with
    `name`, followed by [NODE] sections carrying name/host/port and
    optional per-node properties (flb_upstream_node.c)."""
    from ..config_format import parse_classic

    cf = parse_classic(open(path).read())
    name = "upstream"
    nodes: List[UpstreamNode] = []
    for sec in cf.sections:
        if sec.name.lower() == "upstream":
            name = sec.get("name", name)
        elif sec.name.lower() == "node":
            props = {k.lower(): v for k, v in sec.properties}
            nodes.append(UpstreamNode(
                props.get("name", f"node{len(nodes)}"),
                props.get("host", "127.0.0.1"),
                int(props.get("port", 24224)),
                int(props.get("weight", 1)),
                props,
            ))
    if not nodes:
        raise ValueError(f"upstream file {path!r} defines no nodes")
    return UpstreamHA(name, nodes)
