"""Retry scheduler — capped full-jitter exponential backoff.

Reference: src/flb_scheduler.c:253-300 (backoff_full_jitter; random ms in
[0, min(cap, base * 2^attempt)]), base FLB_SCHED_BASE=5s and cap
FLB_SCHED_CAP=2000s (include/fluent-bit/flb_scheduler.h:29-30). Timers are
asyncio-based rather than timerfd.
"""

from __future__ import annotations

import random
from typing import Optional


def backoff_full_jitter(base: float, cap: float, attempt: int,
                        rng: Optional[random.Random] = None) -> float:
    """Delay in seconds for retry number ``attempt`` (1-based)."""
    attempt = max(1, attempt)
    exp = min(cap, base * (2 ** attempt))
    r = rng or random
    # reference waits at least 1s so retries never hot-loop
    return max(1.0, r.uniform(0, exp))


class Timer:
    """A permanent or oneshot timer handle (flb_sched_timer equivalent)."""

    def __init__(self, handle):
        self._handle = handle
        self.active = True

    def cancel(self) -> None:
        if self.active:
            self._handle.cancel()
            self.active = False
