"""Retry scheduler — capped full-jitter exponential backoff.

Reference: src/flb_scheduler.c:253-300 (backoff_full_jitter; random
seconds in [base, min(cap, base * 2^attempt)] plus one), base
FLB_SCHED_BASE=5s and cap FLB_SCHED_CAP=2000s
(include/fluent-bit/flb_scheduler.h:29-30). Timers are asyncio-based
rather than timerfd.
"""

from __future__ import annotations

import random
from typing import Optional


def backoff_full_jitter(base: float, cap: float, attempt: int,
                        rng: Optional[random.Random] = None) -> float:
    """Delay in seconds for retry number ``attempt`` (1-based).

    Two invariants, pinned by the seeded property suite in
    ``tests/test_guard.py`` because fbtpu-guard leans on them —
    breaker-driven retry storms are only bounded if they hold:

    - **never before base+1**: the delay is at least
      ``min(base, exp) + 1`` (the reference draws from [base, exp]
      then adds one second), so a timed-out/short-circuited flush can
      never hot-loop its re-dispatch;
    - **monotone cap**: the draw's envelope ``min(cap, base·2^n)`` is
      nondecreasing in the attempt number and the delay never exceeds
      ``cap + 1``.
    """
    attempt = max(1, attempt)
    exp = min(cap, base * (2 ** attempt))
    r = rng or random
    # reference draws from [base, exp] then adds one second so the first
    # retry never fires before base+1 (src/flb_scheduler.c:259-264)
    return r.uniform(min(base, exp), exp) + 1.0


class Timer:
    """A permanent or oneshot timer handle (flb_sched_timer equivalent)."""

    def __init__(self, handle):
        self._handle = handle
        self.active = True

    def cancel(self) -> None:
        if self.active:
            self._handle.cancel()
            self.active = False
