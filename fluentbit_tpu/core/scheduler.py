"""Retry scheduler — capped full-jitter exponential backoff, plus the
token-bucket pacing primitive the QoS plane meters ingest with.

Reference: src/flb_scheduler.c:253-300 (backoff_full_jitter; random
seconds in [base, min(cap, base * 2^attempt)] plus one), base
FLB_SCHED_BASE=5s and cap FLB_SCHED_CAP=2000s
(include/fluent-bit/flb_scheduler.h:29-30). Timers are asyncio-based
rather than timerfd. The token bucket has no reference equivalent —
the reference's only ingest throttle is the all-or-nothing
mem_buf_limit pause; fbtpu-qos (core/qos.py) needs graded per-tenant
admission instead.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional

from .lockorder import make_lock


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/second refill up to
    ``burst`` capacity; :meth:`try_take` admits a cost or refuses
    without blocking. Thread-safe (ingest calls arrive from collector
    threads, library pushes, and server inputs concurrently); the
    clock is injectable so quota behavior is testable on a fake clock
    without sleeping.
    """

    __slots__ = ("rate", "capacity", "tokens", "updated", "clock",
                 "_lock")

    def __init__(self, rate: float, burst: Optional[float] = None,
                 clock=time.monotonic):
        self.rate = float(rate)
        # default burst: one second of rate — a tenant that was idle
        # can absorb exactly one quota-second instantaneously
        self.capacity = float(burst if burst is not None else rate)
        self.tokens = self.capacity
        self.clock = clock
        self.updated = clock()
        self._lock = make_lock("TokenBucket._lock")

    def _refill(self, now: float) -> None:
        if now > self.updated:
            self.tokens = min(self.capacity,
                              self.tokens + (now - self.updated) * self.rate)
        self.updated = now

    def try_take(self, cost: float) -> bool:
        """Admit ``cost`` tokens now, or refuse (no partial take).

        A cost larger than the burst capacity is admitted once the
        bucket is as full as it can get, charging the FULL cost (the
        balance goes negative and later admissions wait out the debt).
        Without the debt rule an oversized append could never be
        admitted at all — deferred forever against a hint that keeps
        promising a finite wait (``delay_for`` clamps to capacity, so
        both sides use the same admit threshold). Long-run rate is
        unaffected: debt repays at exactly ``rate``.
        """
        with self._lock:
            self._refill(self.clock())
            if self.tokens >= min(cost, self.capacity):
                self.tokens -= cost
                return True
            return False

    def give_back(self, cost: float) -> None:
        """Return tokens from an admitted take whose append was then
        refused (e.g. the input vanished in a hot reload between
        admission and the locked pool write) — the caller never acked,
        so the tenant must not stay charged for bytes never ingested."""
        with self._lock:
            self.tokens = min(self.capacity, self.tokens + cost)

    def delay_for(self, cost: float) -> float:
        """Seconds until ``cost`` tokens will be available (0 when they
        already are) — the defer hint admission hands back so callers
        can pace retries instead of hot-looping."""
        with self._lock:
            self._refill(self.clock())
            missing = min(cost, self.capacity) - self.tokens
            if missing <= 0:
                return 0.0
            if self.rate <= 0:
                return float("inf")
            return missing / self.rate


def backoff_full_jitter(base: float, cap: float, attempt: int,
                        rng: Optional[random.Random] = None) -> float:
    """Delay in seconds for retry number ``attempt`` (1-based).

    Two invariants, pinned by the seeded property suite in
    ``tests/test_guard.py`` because fbtpu-guard leans on them —
    breaker-driven retry storms are only bounded if they hold:

    - **never before base+1**: the delay is at least
      ``min(base, exp) + 1`` (the reference draws from [base, exp]
      then adds one second), so a timed-out/short-circuited flush can
      never hot-loop its re-dispatch;
    - **monotone cap**: the draw's envelope ``min(cap, base·2^n)`` is
      nondecreasing in the attempt number and the delay never exceeds
      ``cap + 1``.
    """
    attempt = max(1, attempt)
    exp = min(cap, base * (2 ** attempt))
    r = rng or random
    # reference draws from [base, exp] then adds one second so the first
    # retry never fires before base+1 (src/flb_scheduler.c:259-264)
    return r.uniform(min(base, exp), exp) + 1.0


class Timer:
    """A permanent or oneshot timer handle (flb_sched_timer equivalent)."""

    def __init__(self, handle):
        self._handle = handle
        self.active = True

    def cancel(self) -> None:
        if self.active:
            self._handle.cancel()
            self.active = False
