"""flb_strptime equivalent — BSD-style strptime subset.

Reference: src/flb_strptime.c (a BSD strptime clone) and
flb_parser_time_lookup (src/flb_parser.c): the parser's time format is
split at ``%L`` (fractional seconds), each side parsed by strptime, the
digit run at the split parsed as subseconds; a format without a year gets
the current year prepended (old syslog records); without an explicit
timezone (%z) the parser's fixed ``time_offset`` applies (default UTC).

This is a from-scratch implementation of the same directive set over
Python strings; it returns the number of characters consumed so callers
can continue parsing (the %L split requires exactly that).
"""

from __future__ import annotations

import calendar
import time as _time
from dataclasses import dataclass, field
from typing import Optional, Tuple

_MONTHS = ["january", "february", "march", "april", "may", "june", "july",
           "august", "september", "october", "november", "december"]
_DAYS = ["sunday", "monday", "tuesday", "wednesday", "thursday", "friday",
         "saturday"]


@dataclass
class Tm:
    """Broken-down time being filled in (struct flb_tm)."""

    year: int = 1970
    mon: int = 1
    mday: int = 1
    hour: int = 0
    min: int = 0
    sec: int = 0
    yday: Optional[int] = None
    gmtoff: Optional[int] = None  # seconds east of UTC; None = not parsed
    epoch: Optional[float] = None  # %s short-circuit
    _pm: bool = False
    _hour12: Optional[int] = None
    _century: Optional[int] = None  # %C
    _yy: Optional[int] = None       # %y (composed with %C in finish)

    def finish(self) -> None:
        if self._hour12 is not None:
            h = self._hour12 % 12
            self.hour = h + 12 if self._pm else h
        if self._century is not None:
            yy = self._yy if self._yy is not None else self.year % 100
            self.year = self._century * 100 + yy
        elif self._yy is not None:
            self.year = 2000 + self._yy if self._yy < 69 else 1900 + self._yy

    def to_epoch(self, default_offset: int = 0) -> float:
        """Seconds since epoch; unparsed timezone → default_offset."""
        if self.epoch is not None:
            return self.epoch
        self.finish()
        if self.yday is not None and self.mon == 1 and self.mday == 1:
            base = calendar.timegm((self.year, 1, 1, self.hour, self.min,
                                    self.sec, 0, 1, 0))
            ts = base + (self.yday - 1) * 86400
        else:
            ts = calendar.timegm((self.year, self.mon, self.mday, self.hour,
                                  self.min, self.sec, 0, 1, 0))
        off = self.gmtoff if self.gmtoff is not None else default_offset
        return ts - off


def _digits(s: str, i: int, max_len: int) -> Tuple[Optional[int], int]:
    j = i
    while j < len(s) and j - i < max_len and s[j].isdigit():
        j += 1
    if j == i:
        return None, i
    return int(s[i:j]), j


def _name(s: str, i: int, names) -> Tuple[Optional[int], int]:
    low = s[i : i + 12].lower()
    for idx, n in enumerate(names):
        if low.startswith(n[:3]):
            # full name wins if present
            if low.startswith(n):
                return idx, i + len(n)
            return idx, i + 3
    return None, i


def _skip_ws(s: str, i: int) -> int:
    while i < len(s) and s[i].isspace():
        i += 1
    return i


def flb_strptime(s: str, fmt: str, tm: Tm) -> Optional[int]:
    """Parse ``s`` by ``fmt`` into ``tm``; returns chars consumed or None
    on mismatch (the C version returns the advanced pointer)."""
    i = 0
    f = 0
    n = len(s)
    nf = len(fmt)
    while f < nf:
        c = fmt[f]
        if c.isspace():
            # whitespace in format: skip any run of whitespace in input
            i = _skip_ws(s, i)
            f += 1
            continue
        if c != "%":
            if i >= n or s[i] != c:
                return None
            i += 1
            f += 1
            continue
        f += 1
        if f >= nf:
            return None
        d = fmt[f]
        f += 1
        if d == "%":
            if i >= n or s[i] != "%":
                return None
            i += 1
        elif d in ("n", "t"):
            i = _skip_ws(s, i)
        elif d in ("a", "A"):
            idx, i2 = _name(s, i, _DAYS)
            if idx is None:
                return None
            i = i2
        elif d in ("b", "B", "h"):
            idx, i2 = _name(s, i, _MONTHS)
            if idx is None:
                return None
            tm.mon = idx + 1
            i = i2
        elif d in ("d", "e"):
            if d == "e":
                i = _skip_ws(s, i)
            v, i = _digits(s, i, 2)
            if v is None or not (1 <= v <= 31):
                return None
            tm.mday = v
        elif d == "m":
            v, i = _digits(s, i, 2)
            if v is None or not (1 <= v <= 12):
                return None
            tm.mon = v
        elif d in ("H", "k"):
            if d == "k":
                i = _skip_ws(s, i)
            v, i = _digits(s, i, 2)
            if v is None or v > 23:
                return None
            tm.hour = v
        elif d in ("I", "l"):
            if d == "l":
                i = _skip_ws(s, i)
            v, i = _digits(s, i, 2)
            if v is None or not (1 <= v <= 12):
                return None
            tm._hour12 = v
        elif d == "M":
            v, i = _digits(s, i, 2)
            if v is None or v > 59:
                return None
            tm.min = v
        elif d == "S":
            v, i = _digits(s, i, 2)
            if v is None or v > 61:
                return None
            tm.sec = v
        elif d == "j":
            v, i = _digits(s, i, 3)
            if v is None or not (1 <= v <= 366):
                return None
            tm.yday = v
        elif d == "Y":
            v, i = _digits(s, i, 4)
            if v is None:
                return None
            tm.year = v
        elif d == "y":
            v, i = _digits(s, i, 2)
            if v is None:
                return None
            tm._yy = v  # century composed in finish() (%C%y support)
        elif d == "C":
            v, i = _digits(s, i, 2)
            if v is None:
                return None
            tm._century = v
        elif d == "s":
            v, i = _digits(s, i, 20)
            if v is None:
                return None
            tm.epoch = float(v)
        elif d == "p":
            low = s[i : i + 2].lower()
            if low == "am":
                tm._pm = False
            elif low == "pm":
                tm._pm = True
            else:
                return None
            i += 2
        elif d == "T":
            r = flb_strptime(s[i:], "%H:%M:%S", tm)
            if r is None:
                return None
            i += r
        elif d == "R":
            r = flb_strptime(s[i:], "%H:%M", tm)
            if r is None:
                return None
            i += r
        elif d == "D" or d == "x":
            r = flb_strptime(s[i:], "%m/%d/%y", tm)
            if r is None:
                return None
            i += r
        elif d == "z":
            if i < n and s[i] in "Zz":
                tm.gmtoff = 0
                i += 1
            elif i < n and s[i] in "+-":
                sign = -1 if s[i] == "-" else 1
                i += 1
                h, i = _digits(s, i, 2)
                if h is None:
                    return None
                if i < n and s[i] == ":":
                    i += 1
                m, i2 = _digits(s, i, 2)
                if m is None:
                    m = 0
                else:
                    i = i2
                tm.gmtoff = sign * (h * 3600 + m * 60)
            else:
                return None
        elif d == "Z":
            up = s[i : i + 3].upper()
            if up.startswith("UTC") or up.startswith("GMT"):
                tm.gmtoff = 0
                i += 3
            elif i < n and s[i] in "Zz":
                tm.gmtoff = 0
                i += 1
            else:
                j = i
                while j < n and s[j].isalpha():
                    j += 1
                if j == i:
                    return None
                i = j  # unknown zone name: consumed, offset unknown
        elif d in ("u", "w"):
            v, i = _digits(s, i, 1)
            if v is None:
                return None
        elif d in ("U", "W"):
            v, i = _digits(s, i, 2)
            if v is None:
                return None
        else:
            return None
    return i


def parse_subseconds(s: str, i: int) -> Tuple[Optional[float], int]:
    """The %L fragment: a dot/comma-optional digit run → fractional secs
    (reference parse_subseconds, src/flb_parser.c:1869)."""
    if i < len(s) and s[i] in ".,":
        i += 1
    j = i
    while j < len(s) and s[j].isdigit():
        j += 1
    if j == i:
        return None, i
    frac = int(s[i:j]) / (10.0 ** (j - i))
    return frac, j


def time_lookup(
    value: str,
    time_fmt: str,
    time_offset: int = 0,
    now: Optional[float] = None,
) -> Optional[float]:
    """flb_parser_time_lookup equivalent: parse ``value`` by ``time_fmt``
    (split at %L), returning epoch seconds (float, frac included) or None.

    A format without %Y/%y/%s gets the current UTC year prepended (the
    reference's old-syslog accommodation).
    """
    fmt = time_fmt
    s = value
    with_year = any(x in fmt for x in ("%Y", "%y", "%s", "%D", "%x", "%C"))
    if not with_year:
        t = _time.gmtime(now if now is not None else _time.time())
        s = f"{t.tm_year} {s}"
        fmt = "%Y " + fmt
    frac = 0.0
    tm = Tm()
    if "%L" in fmt:
        pre, post = fmt.split("%L", 1)
        consumed = flb_strptime(s, pre, tm)
        if consumed is None:
            return None
        fv, pos = parse_subseconds(s, consumed)
        if fv is None:
            return None
        frac = fv
        if post:
            rest = flb_strptime(s[pos:], post, tm)
            if rest is None:
                return None
    else:
        if flb_strptime(s, fmt, tm) is None:
            return None
    return tm.to_epoch(default_offset=time_offset) + frac


def parse_tzone_offset(s: str) -> Optional[int]:
    """'+0200' / '-05:30' / 'Z' → seconds east of UTC
    (flb_parser_tzone_offset, src/flb_parser.c)."""
    tm = Tm()
    if flb_strptime(s.strip(), "%z", tm) is None:
        return None
    return tm.gmtoff
