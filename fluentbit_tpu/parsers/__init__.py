"""Parsers subsystem — named parsers: regex / json / logfmt / ltsv.

Reference: src/flb_parser.c (registry + flb_parser_do dispatch,
:1784-1800), flb_parser_regex.c, flb_parser_json.c, flb_parser_logfmt.c,
flb_parser_ltsv.c, time handling via src/flb_strptime.c (see
.strptime). Parsers are created from [PARSER] config sections
(conf/parsers.conf) or programmatically, looked up by name, and applied
by filter_parser / in_tail / multiline.

``Parser.do(text)`` returns ``(fields_dict, timestamp_or_None)`` on
success or ``None`` on parse failure — the (out_buf, out_time) contract
of flb_parser_do.

Device note: for regex parsers whose pattern is DFA-expressible the
match decision can run vectorized on device (fluentbit_tpu.ops.grep) as
a prefilter; capture extraction runs on the CPU for matching records
(match-then-extract two-pass — the tagged-DFA single-pass is future
work).
"""

from __future__ import annotations

import json as _json
import logging
from typing import Any, Dict, List, Optional, Tuple

_log = logging.getLogger("flb.parser")

from ..core.config import parse_bool
from ..regex import FlbRegex
from .strptime import parse_tzone_offset, time_lookup

__all__ = ["Parser", "ParserError", "create_parser", "TYPE_CASTERS"]


class ParserError(ValueError):
    pass


def _cast_int(v: str):
    try:
        return int(float(v)) if "." in v else int(v, 10)
    except ValueError:
        return v


def _cast_float(v: str):
    try:
        return float(v)
    except ValueError:
        return v


def _cast_bool(v: str):
    s = v.strip().lower()
    if s in ("true", "on", "yes", "1"):
        return True
    if s in ("false", "off", "no", "0"):
        return False
    return v


def _cast_hex(v: str):
    try:
        return int(v, 16)
    except ValueError:
        return v


#: Types option casters (flb_parser_types_str_to_type; casting applied by
#: the regex/logfmt/ltsv parsers, never by json)
TYPE_CASTERS = {
    "integer": _cast_int,
    "float": _cast_float,
    "bool": _cast_bool,
    "hex": _cast_hex,
    "string": lambda v: v,
}


def parse_types_spec(spec: str) -> Dict[str, Any]:
    """'code:integer size:integer flag:bool' → {key: caster}."""
    out = {}
    for part in str(spec).split():
        if ":" not in part:
            raise ParserError(f"invalid Types entry {part!r}")
        key, tname = part.split(":", 1)
        caster = TYPE_CASTERS.get(tname.lower())
        if caster is None:
            raise ParserError(f"unknown type {tname!r} in Types")
        out[key] = caster
    return out


class Parser:
    """A named parser (struct flb_parser)."""

    def __init__(
        self,
        name: str,
        fmt: str,
        regex: Optional[str] = None,
        time_key: Optional[str] = None,
        time_format: Optional[str] = None,
        time_keep: bool = False,
        time_offset: Optional[str] = None,
        time_strict: bool = True,
        types: Optional[str] = None,
        skip_empty_values: bool = True,
    ):
        self.name = name
        self.fmt = fmt.lower()
        if self.fmt not in ("regex", "json", "logfmt", "ltsv"):
            raise ParserError(f"unknown parser format {fmt!r}")
        self.time_key = time_key or "time"
        self.time_format = time_format
        self.time_keep = time_keep
        self.time_strict = time_strict
        self.skip_empty_values = skip_empty_values
        self.time_offset = 0
        if time_offset:
            off = parse_tzone_offset(str(time_offset))
            if off is None:
                raise ParserError(f"invalid Time_Offset {time_offset!r}")
            self.time_offset = off
        self.types = parse_types_spec(types) if types else {}
        self.regex: Optional[FlbRegex] = None
        if self.fmt == "regex":
            if not regex:
                raise ParserError("regex parser requires a Regex")
            self.regex = FlbRegex(regex)

    # -- the flb_parser_do contract --

    def do(self, text: str) -> Optional[Tuple[Dict[str, Any], Optional[float]]]:
        if self.fmt == "regex":
            fields = self._do_regex(text)
        elif self.fmt == "json":
            fields = self._do_json(text)
        elif self.fmt == "logfmt":
            fields = self._do_logfmt(text)
        else:
            fields = self._do_ltsv(text)
        if fields is None:
            return None
        ts = self._extract_time(fields)
        return fields, ts

    def _extract_time(self, fields: Dict[str, Any]) -> Optional[float]:
        """Parse + (usually) pop the time field.

        Reference cb_results (src/flb_parser_regex.c:65-95): on lookup
        FAILURE the time field is dropped and the record still parses
        with no time override; on success it is dropped unless
        time_keep.
        """
        if not self.time_format or self.time_key not in fields:
            return None
        raw = fields[self.time_key]
        if not isinstance(raw, str):
            return None
        ts = time_lookup(raw, self.time_format, self.time_offset)
        if ts is None:
            # strict vs non-strict differ only in log level: either way
            # the field is dropped and the record parses with no time
            # override (src/flb_parser.c flb_parser_time_lookup +
            # flb_parser_regex.c cb_results)
            _log.log(
                30 if self.time_strict else 10,
                "[parser:%s] invalid time format %s for '%s'",
                self.name, self.time_format, raw,
            )
            fields.pop(self.time_key, None)
            return None
        if not self.time_keep:
            fields.pop(self.time_key, None)
        return ts

    def _apply_types(self, fields: Dict[str, Any]) -> Dict[str, Any]:
        if self.types:
            for k, caster in self.types.items():
                v = fields.get(k)
                if isinstance(v, str):
                    fields[k] = caster(v)
        return fields

    def _do_regex(self, text: str) -> Optional[Dict[str, Any]]:
        got = self.regex.parse_record(text)
        if got is None:
            return None
        fields: Dict[str, Any] = {}
        for k, v in got.items():
            if v == "" and self.skip_empty_values:
                continue
            fields[k] = v
        if not fields:
            return None  # zero extracted fields = parse failure
        return self._apply_types(fields)

    def _do_json(self, text: str) -> Optional[Dict[str, Any]]:
        try:
            obj = _json.loads(text)
        except Exception:
            return None
        if not isinstance(obj, dict):
            return None  # flb_parser_json_do requires a map
        return obj

    def _do_logfmt(self, text: str) -> Optional[Dict[str, Any]]:
        """logfmt: ident[=value] pairs, values bare or double-quoted
        (reference flb_parser_logfmt.c scanner semantics)."""
        fields: Dict[str, Any] = {}
        i = 0
        n = len(text)
        while i < n:
            while i < n and text[i] in " \t":
                i += 1
            if i >= n:
                break
            # key: up to '=' or whitespace
            k0 = i
            while i < n and text[i] not in "= \t":
                i += 1
            key = text[k0:i]
            value = ""
            if i < n and text[i] == "=":
                i += 1
                if i < n and text[i] == '"':
                    i += 1
                    buf = []
                    while i < n and text[i] != '"':
                        if text[i] == "\\" and i + 1 < n:
                            esc = text[i + 1]
                            buf.append(
                                {"n": "\n", "t": "\t", "r": "\r"}.get(esc, esc)
                            )
                            i += 2
                        else:
                            buf.append(text[i])
                            i += 1
                    i += 1  # closing quote
                    value = "".join(buf)
                else:
                    v0 = i
                    while i < n and text[i] not in " \t":
                        i += 1
                    value = text[v0:i]
            if key:
                fields[key] = value
        if not fields:
            return None
        return self._apply_types(fields)

    def _do_ltsv(self, text: str) -> Optional[Dict[str, Any]]:
        """LTSV: tab-separated label:value fields
        (reference flb_parser_ltsv.c)."""
        fields: Dict[str, Any] = {}
        for part in text.rstrip("\r\n").split("\t"):
            if not part:
                continue
            if ":" not in part:
                continue
            label, value = part.split(":", 1)
            fields[label] = value
        if not fields:
            return None
        return self._apply_types(fields)


def create_parser(name: str, **props) -> Parser:
    """Create from [PARSER]-section style properties (case-insensitive
    keys: Format, Regex, Time_Key, Time_Format, Time_Keep, Time_Offset,
    Types, Skip_Empty_Values)."""
    low = {k.lower(): v for k, v in props.items()}
    return Parser(
        name=name,
        fmt=low.get("format", "regex"),
        regex=low.get("regex"),
        time_key=low.get("time_key"),
        time_format=low.get("time_format"),
        time_keep=parse_bool(low["time_keep"]) if "time_keep" in low else False,
        time_offset=low.get("time_offset"),
        time_strict=parse_bool(low["time_strict"]) if "time_strict" in low else True,
        types=low.get("types"),
        skip_empty_values=parse_bool(low["skip_empty_values"])
        if "skip_empty_values" in low
        else True,
    )
