"""Build + load the fbtpu_codec C extension (native/fbtpu_codec.c).

Shares the hash-cached build scheme with fluentbit_tpu.native via
native.buildlib (incl. the prebuilt-artifact trust paths); silently
absent when the toolchain/headers are missing — callers keep the
pure-Python decoder. FBTPU_NO_NATIVE disables it together with the
data-plane .so.
"""

from __future__ import annotations

import logging
import os
import sysconfig
import threading

log = logging.getLogger("flb.native")

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(os.path.dirname(_HERE))
_SRC = os.path.join(_ROOT, "native", "fbtpu_codec.c")
_SO = os.path.join(_ROOT, "native", "build", "fbtpu_codec.so")

_lock = threading.Lock()
_mod = None
_tried = False


def load():
    """→ the initialized extension module, or None (pure-Python path).

    Lock-free fast path: encode_event calls this per record, so the
    settled states (loaded / declined) must not take the lock."""
    if _mod is not None or _tried:
        return _mod
    return _load_slow()


def _load_slow():
    global _mod, _tried
    with _lock:
        if _mod is not None or _tried:
            return _mod
        _tried = True
        if os.environ.get("FBTPU_NO_NATIVE"):
            return None
        include = sysconfig.get_paths().get("include")
        if not include or not os.path.exists(
                os.path.join(include, "Python.h")):
            # no headers: only a prebuilt artifact can serve
            if not os.path.exists(_SO):
                return None
        from ..native.buildlib import ensure_built

        cmd = ["gcc", "-O2", "-fPIC", "-shared", "-I", include or ".",
               _SRC, "-o", _SO]
        if not ensure_built(_SRC, _SO, cmd):
            return None
        try:
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                "fbtpu_codec", _SO)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
        except (ImportError, OSError) as e:
            log.warning("codec extension load failed: %s", e)
            return None
        from .events import LogEvent
        from .msgpack import EventTime

        mod._init(LogEvent, EventTime)
        _mod = mod
        return _mod
