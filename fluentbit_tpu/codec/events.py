"""Log event codec — Fluent Bit log event format V2.

A log event is msgpack ``[[timestamp, metadata-map], body-map]``
(reference: include/fluent-bit/flb_log_event.h:29-62). Legacy (Forward/V1)
events are ``[timestamp, body-map]``; the decoder accepts both and the
encoder emits V2 by default.

Group markers (reference include/fluent-bit/flb_log_event.h:48-49):
timestamp == -1 opens an OTel-style group (resource/scope metadata in the
header map), timestamp == -2 closes it.

The decoder exposes per-record raw byte spans so filters can re-emit
surviving records byte-identical (the grep contract,
plugins/filter_grep/grep.c:286-392).
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

from .msgpack import EventTime, Unpacker, packb

GROUP_START = -1
GROUP_END = -2


@dataclass
class LogEvent:
    """A decoded log event."""

    timestamp: Any  # EventTime | int | float (GROUP_START/GROUP_END for markers)
    body: Dict[str, Any] = field(default_factory=dict)
    metadata: Dict[str, Any] = field(default_factory=dict)
    # raw msgpack span of this record within the source buffer (if decoded)
    raw: Optional[bytes] = None

    @property
    def ts_float(self) -> float:
        ts = self.timestamp
        if isinstance(ts, EventTime):
            return float(ts)
        return float(ts)

    def is_group_start(self) -> bool:
        return _marker_value(self.timestamp) == GROUP_START

    def is_group_end(self) -> bool:
        return _marker_value(self.timestamp) == GROUP_END


def _marker_value(ts: Any) -> Optional[int]:
    if isinstance(ts, int):
        return ts
    if isinstance(ts, float) and ts in (-1.0, -2.0):
        return int(ts)
    return None


def now_event_time() -> EventTime:
    t = _time.time()
    return EventTime.from_float(t)


_EMPTY_META: Dict[str, Any] = {}


def encode_event(
    body: Dict[str, Any],
    timestamp: Any = None,
    metadata: Optional[Dict[str, Any]] = None,
) -> bytes:
    """Encode one V2 log event to msgpack bytes."""
    if timestamp is None:
        timestamp = now_event_time()
    from . import _native_codec

    mod = _native_codec.load()
    if mod is not None:
        try:
            return mod.pack_event(timestamp, metadata or _EMPTY_META,
                                  body)
        except mod.FallbackError:
            pass  # exotic payload type: the Python packer handles it
    return packb([[timestamp, metadata or {}], body])


def encode_events(events: List[Tuple[Any, Dict[str, Any]]]) -> bytes:
    """Encode (timestamp, body) pairs into a concatenated V2 buffer."""
    out = []
    for ts, body in events:
        out.append(encode_event(body, ts))
    return b"".join(out)


def decode_events(buf: bytes) -> List[LogEvent]:
    """Decode all log events in a concatenated msgpack buffer.

    Accepts V2 ``[[ts, meta], body]`` and legacy ``[ts, body]`` records.
    Each returned event carries its raw byte span (``event.raw``).

    Decoding runs in the fbtpu_codec C extension when available
    (semantic twin, ~10x; see native/fbtpu_codec.c); exotic buffers the
    extension declines (non-EventTime ext types) and any environment
    without the toolchain fall back to the pure-Python Unpacker below.
    """
    from . import _native_codec

    mod = _native_codec.load()
    if mod is not None:
        try:
            return mod.decode_events(buf)
        except mod.FallbackError:
            pass  # ExtType payload: the Python decoder handles it
    events: List[LogEvent] = []
    u = Unpacker(buf)
    pos = 0
    for obj in u:
        end = u.tell()
        raw = buf[pos:end]
        pos = end
        events.append(_to_event(obj, raw))
    return events


def iter_events(buf: bytes) -> Iterator[LogEvent]:
    """Iterate the buffer's events. NOTE: with the native codec loaded
    the whole buffer decodes eagerly before the first yield (chunks are
    bounded at ~2MB, and every in-tree caller consumes fully) — only
    the pure-Python fallback streams one record at a time."""
    from . import _native_codec

    mod = _native_codec.load()
    if mod is not None:
        try:
            yield from mod.decode_events(buf)
            return
        except mod.FallbackError:
            pass  # ExtType payload: the Python decoder handles it
    u = Unpacker(buf)
    pos = 0
    for obj in u:
        end = u.tell()
        raw = buf[pos:end]
        pos = end
        yield _to_event(obj, raw)


def _to_event(obj: Any, raw: Optional[bytes] = None) -> LogEvent:
    if not isinstance(obj, list) or not obj:
        raise ValueError(f"invalid log event: {obj!r}")
    header = obj[0]
    if isinstance(header, list):
        # V2: [[ts, metadata], body]
        ts = header[0] if header else 0
        meta = header[1] if len(header) > 1 and isinstance(header[1], dict) else {}
        body = obj[1] if len(obj) > 1 and isinstance(obj[1], dict) else {}
        return LogEvent(timestamp=ts, body=body, metadata=meta, raw=raw)
    # legacy: [ts, body]
    ts = header
    body = obj[1] if len(obj) > 1 and isinstance(obj[1], dict) else {}
    return LogEvent(timestamp=ts, body=body, metadata={}, raw=raw)


def reencode_event(ev: LogEvent) -> bytes:
    """Re-encode a (possibly modified) event as V2."""
    from . import _native_codec

    mod = _native_codec.load()
    if mod is not None:
        try:
            return mod.pack_event(ev.timestamp, ev.metadata, ev.body)
        except mod.FallbackError:
            pass
    return packb([[ev.timestamp, ev.metadata], ev.body])


def count_records(buf: bytes) -> int:
    """Count log records in a buffer (flb_mp_count_log_records equivalent,
    reference src/flb_mp.c)."""
    n = 0
    for _ in Unpacker(buf):
        n += 1
    return n


def fast_count_records(buf: bytes):
    """Native msgpack scanner when available (no Python-object decode);
    None on malformed input or when the native library is absent AND the
    Python fallback fails."""
    from .. import native

    if not isinstance(buf, bytes):
        buf = bytes(buf)  # a raw filter may hand back a memoryview
    n = native.count_records(buf)
    if n is not None:
        return n
    try:
        return count_records(buf)
    except (ValueError, RecursionError):
        # ValueError = malformed msgpack, RecursionError = hostile
        # nesting: both mean "not countable", the caller's decode path
        # decides. Anything ELSE is a real bug and must surface —
        # a broad swallow here once hid a transcoder regression as a
        # permanent silent fallback (fbtpu-lint decline-swallow).
        return None
