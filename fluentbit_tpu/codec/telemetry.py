"""Typed traces / metrics / profiles payloads + OTLP JSON codecs.

The ctraces / cprofiles equivalents (reference: lib/ctraces/ ~24k LoC,
lib/cprofiles/ ~44k LoC — both mirror the OTLP data model in C structs;
OTLP server plugins/in_opentelemetry/, exporter
plugins/out_opentelemetry/ 4640 LoC). The TPU build's typed model is a
normalized Python/msgpack structure that flows through chunks with
event_type "traces"/"profiles" exactly like metrics-as-data payloads:

- **Traces** — ``{"resourceSpans": [{"resource": {attrs}, "scopeSpans":
  [{"scope": {...}, "spans": [span...]}]}]}`` where span ids are raw
  bytes, timestamps are int nanoseconds, and attributes are plain dicts
  (the OTLP kvlist form exists only at the wire boundary).
- **Metrics** — OTLP metrics decode INTO the internal cmetrics-like
  snapshot (``core/metrics.py to_msgpack_obj`` shape: ``{"meta": ...,
  "metrics": [{name/type/labels/values}]}``) so every metrics-capable
  output (prometheus_exporter, stdout, forward) consumes them
  unchanged; the exporter re-encodes that shape as OTLP.
- **Profiles** — resource/scope attributes normalize to dicts; the
  pprof-style profile tables (sampleType/sample/locationTable/
  functionTable/stringTable...) pass through structurally with
  nanosecond fields coerced to ints.

Every decode_* returns ``(payload_dict, record_count)``; every
encode_* is its inverse, and round trips preserve span/resource/sample
fidelity (tests/test_otlp_signals.py).
"""

from __future__ import annotations

import base64
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------- AnyValue

def any_value_to_py(v: dict) -> Any:
    if not isinstance(v, dict):
        return v
    if "stringValue" in v:
        return v["stringValue"]
    if "intValue" in v:
        return int(v["intValue"])
    if "doubleValue" in v:
        return float(v["doubleValue"])
    if "boolValue" in v:
        return bool(v["boolValue"])
    if "arrayValue" in v:
        return [any_value_to_py(x)
                for x in v["arrayValue"].get("values", [])]
    if "kvlistValue" in v:
        return kvlist_to_dict(v["kvlistValue"].get("values", []))
    if "bytesValue" in v:
        try:
            return base64.b64decode(v["bytesValue"])
        except (ValueError, TypeError):
            return v["bytesValue"]
    return None


def kvlist_to_dict(kvs: List[dict]) -> Dict[str, Any]:
    return {kv.get("key", ""): any_value_to_py(kv.get("value", {}))
            for kv in kvs}


def py_to_any_value(v: Any) -> dict:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    if isinstance(v, (list, tuple)):
        return {"arrayValue": {"values": [py_to_any_value(x) for x in v]}}
    if isinstance(v, dict):
        return {"kvlistValue": {"values": dict_to_kvlist(v)}}
    if isinstance(v, bytes):
        # proto3 JSON mapping: bytes fields are base64 text
        return {"bytesValue": base64.b64encode(v).decode("ascii")}
    return {"stringValue": str(v)}


def dict_to_kvlist(d: Dict[str, Any]) -> List[dict]:
    return [{"key": k, "value": py_to_any_value(v)} for k, v in d.items()]


def _id_bytes(hex_or_b64: Optional[str]) -> bytes:
    """OTLP/JSON trace & span ids are hex per the protocol JSON mapping;
    tolerate base64 (some SDKs emit proto3-default encoding)."""
    if not hex_or_b64:
        return b""
    try:
        return bytes.fromhex(hex_or_b64)
    except ValueError:
        try:
            return base64.b64decode(hex_or_b64)
        except (ValueError, TypeError):
            return b""


def _id_hex(b) -> str:
    if isinstance(b, bytes):
        return b.hex()
    return str(b or "")


def _ns(v) -> int:
    try:
        return int(v or 0)
    except (TypeError, ValueError):
        return 0


# ---------------------------------------------------------- traces

def _decode_span(s: dict) -> dict:
    out = {
        "traceId": _id_bytes(s.get("traceId")),
        "spanId": _id_bytes(s.get("spanId")),
        "parentSpanId": _id_bytes(s.get("parentSpanId")),
        "name": s.get("name", ""),
        "kind": int(s.get("kind", 0) or 0),
        "startTimeUnixNano": _ns(s.get("startTimeUnixNano")),
        "endTimeUnixNano": _ns(s.get("endTimeUnixNano")),
        "attributes": kvlist_to_dict(s.get("attributes", [])),
    }
    if s.get("traceState"):
        out["traceState"] = s["traceState"]
    if s.get("droppedAttributesCount"):
        out["droppedAttributesCount"] = int(s["droppedAttributesCount"])
    evs = [{
        "timeUnixNano": _ns(e.get("timeUnixNano")),
        "name": e.get("name", ""),
        "attributes": kvlist_to_dict(e.get("attributes", [])),
    } for e in s.get("events", [])]
    if evs:
        out["events"] = evs
    links = [{
        "traceId": _id_bytes(ln.get("traceId")),
        "spanId": _id_bytes(ln.get("spanId")),
        "attributes": kvlist_to_dict(ln.get("attributes", [])),
    } for ln in s.get("links", [])]
    if links:
        out["links"] = links
    st = s.get("status")
    if st:
        out["status"] = {"code": int(st.get("code", 0) or 0),
                         "message": st.get("message", "")}
    return out


def _encode_span(s: dict) -> dict:
    out = {
        "traceId": _id_hex(s.get("traceId")),
        "spanId": _id_hex(s.get("spanId")),
        "name": s.get("name", ""),
        "kind": int(s.get("kind", 0)),
        "startTimeUnixNano": str(s.get("startTimeUnixNano", 0)),
        "endTimeUnixNano": str(s.get("endTimeUnixNano", 0)),
        "attributes": dict_to_kvlist(s.get("attributes", {})),
    }
    if s.get("parentSpanId"):
        out["parentSpanId"] = _id_hex(s["parentSpanId"])
    if s.get("traceState"):
        out["traceState"] = s["traceState"]
    if s.get("droppedAttributesCount"):
        out["droppedAttributesCount"] = s["droppedAttributesCount"]
    if s.get("events"):
        out["events"] = [{
            "timeUnixNano": str(e.get("timeUnixNano", 0)),
            "name": e.get("name", ""),
            "attributes": dict_to_kvlist(e.get("attributes", {})),
        } for e in s["events"]]
    if s.get("links"):
        out["links"] = [{
            "traceId": _id_hex(ln.get("traceId")),
            "spanId": _id_hex(ln.get("spanId")),
            "attributes": dict_to_kvlist(ln.get("attributes", {})),
        } for ln in s["links"]]
    if s.get("status"):
        st = {}
        if s["status"].get("code"):
            st["code"] = s["status"]["code"]
        if s["status"].get("message"):
            st["message"] = s["status"]["message"]
        out["status"] = st
    return out


def _scope_to_py(scope: dict) -> dict:
    out = {"name": (scope or {}).get("name", ""),
           "version": (scope or {}).get("version", "")}
    attrs = kvlist_to_dict((scope or {}).get("attributes", []))
    if attrs:
        out["attributes"] = attrs
    return out


def _scope_to_otlp(scope: dict) -> dict:
    out = {"name": scope.get("name", ""),
           "version": scope.get("version", "")}
    if scope.get("attributes"):
        out["attributes"] = dict_to_kvlist(scope["attributes"])
    return out


def decode_otlp_traces(payload: dict) -> Tuple[dict, int]:
    """ExportTraceServiceRequest JSON → typed payload + span count."""
    rs_out = []
    n = 0
    for rs in payload.get("resourceSpans", []):
        resource = kvlist_to_dict(
            (rs.get("resource") or {}).get("attributes", []))
        scopes = []
        for ss in rs.get("scopeSpans", []):
            spans = [_decode_span(s) for s in ss.get("spans", [])]
            n += len(spans)
            scopes.append({"scope": _scope_to_py(ss.get("scope")),
                           "spans": spans})
        rs_out.append({"resource": resource, "scopeSpans": scopes})
    return {"resourceSpans": rs_out}, n


def encode_otlp_traces(payloads: List[dict]) -> dict:
    """Typed payload(s) → ExportTraceServiceRequest JSON."""
    rs_out = []
    for payload in payloads:
        for rs in payload.get("resourceSpans", []):
            rs_out.append({
                "resource": {
                    "attributes": dict_to_kvlist(rs.get("resource", {}))},
                "scopeSpans": [{
                    "scope": _scope_to_otlp(ss.get("scope", {})),
                    "spans": [_encode_span(s)
                              for s in ss.get("spans", [])],
                } for ss in rs.get("scopeSpans", [])],
            })
    return {"resourceSpans": rs_out}


def count_spans(payload: dict) -> int:
    return sum(len(ss.get("spans", []))
               for rs in payload.get("resourceSpans", [])
               for ss in rs.get("scopeSpans", []))


def is_traces_payload(obj) -> bool:
    return isinstance(obj, dict) and "resourceSpans" in obj


# ---------------------------------------------------------- metrics

def decode_otlp_metrics(payload: dict) -> Tuple[List[dict], int]:
    """ExportMetricsServiceRequest JSON → internal cmetrics-like
    snapshots (core/metrics.py to_msgpack_obj shape), ONE PER RESOURCE
    so multi-resource requests keep their attribution (each snapshot's
    ``meta.resource`` travels with its metrics; metric chunks already
    hold sequences of snapshots). Gauge, sum (→ counter), and histogram
    instruments map; attributes become the label set."""
    payloads: List[dict] = []
    total = 0
    for rm in payload.get("resourceMetrics", []):
        resource = kvlist_to_dict(
            (rm.get("resource") or {}).get("attributes", []))
        metrics: List[dict] = []
        meta: Dict[str, Any] = (
            {"resource": resource} if resource else {})
        for sm in rm.get("scopeMetrics", []):
            for m in sm.get("metrics", []):
                name = m.get("name", "")
                desc = m.get("description", "")
                if "gauge" in m or "sum" in m:
                    kind = "gauge" if "gauge" in m else "counter"
                    dps = (m.get("gauge") or m.get("sum") or {}).get(
                        "dataPoints", [])
                    label_keys: List[str] = []
                    values = []
                    for dp in dps:
                        attrs = kvlist_to_dict(dp.get("attributes", []))
                        for k in attrs:
                            if k not in label_keys:
                                label_keys.append(k)
                        v = dp.get("asDouble")
                        if v is None:
                            v = int(dp.get("asInt", 0) or 0)
                        values.append({
                            "labels": [str(attrs.get(k, ""))
                                       for k in label_keys],
                            "value": v,
                            "ts": _ns(dp.get("timeUnixNano")),
                        })
                    # re-pad label vectors (a later point may introduce
                    # new keys)
                    for val in values:
                        val["labels"] += [""] * (len(label_keys)
                                                 - len(val["labels"]))
                    metrics.append({"name": name, "type": kind,
                                    "desc": desc, "labels": label_keys,
                                    "values": values})
                elif "histogram" in m:
                    dps = m["histogram"].get("dataPoints", [])
                    label_keys = []
                    hist = []
                    buckets: List[float] = []
                    for dp in dps:
                        attrs = kvlist_to_dict(dp.get("attributes", []))
                        for k in attrs:
                            if k not in label_keys:
                                label_keys.append(k)
                        bounds = [float(b) for b in
                                  dp.get("explicitBounds", [])]
                        if bounds and not buckets:
                            buckets = bounds
                        counts = [int(c) for c in
                                  dp.get("bucketCounts", [])]
                        hist.append({
                            "labels": [str(attrs.get(k, ""))
                                       for k in label_keys],
                            "counts": counts,
                            "sum": float(dp.get("sum", 0.0) or 0.0),
                        })
                    for h in hist:
                        h["labels"] += [""] * (len(label_keys)
                                               - len(h["labels"]))
                    metrics.append({"name": name, "type": "histogram",
                                    "desc": desc, "labels": label_keys,
                                    "buckets": buckets, "values": [],
                                    "hist": hist})
        if metrics:
            total += sum(len(m.get("values", [])) + len(m.get("hist", []))
                         for m in metrics)
            payloads.append({"meta": meta, "metrics": metrics})
    return payloads, total


def encode_otlp_metrics(payloads: List[dict]) -> dict:
    """Internal snapshot(s) → ExportMetricsServiceRequest JSON — one
    resourceMetrics entry per snapshot, so each keeps its own resource
    attribution."""
    rm_out = []
    for payload in payloads:
        otlp_metrics: List[dict] = []
        meta = payload.get("meta") or {}
        resource = meta.get("resource", {}) if isinstance(meta, dict) \
            else {}
        for m in payload.get("metrics", []):
            name = m.get("name", "")
            kind = m.get("type", "counter")
            keys = m.get("labels", [])
            entry: Dict[str, Any] = {"name": name,
                                     "description": m.get("desc", "")}
            if kind == "histogram":
                dps = []
                for h in m.get("hist", []):
                    dps.append({
                        "attributes": dict_to_kvlist(
                            dict(zip(keys, h.get("labels", [])))),
                        "bucketCounts": [str(c) for c in
                                         h.get("counts", [])],
                        "explicitBounds": list(m.get("buckets", [])),
                        "sum": h.get("sum", 0.0),
                        "count": str(sum(h.get("counts", []))),
                    })
                entry["histogram"] = {
                    "dataPoints": dps, "aggregationTemporality": 2}
            else:
                dps = []
                for val in m.get("values", []):
                    dp: Dict[str, Any] = {
                        "attributes": dict_to_kvlist(
                            dict(zip(keys, val.get("labels", [])))),
                    }
                    v = val.get("value", 0)
                    if isinstance(v, float) and not v.is_integer():
                        dp["asDouble"] = v
                    else:
                        dp["asInt"] = str(int(v))
                    if val.get("ts"):
                        dp["timeUnixNano"] = str(int(val["ts"]))
                    dps.append(dp)
                if kind == "counter":
                    entry["sum"] = {"dataPoints": dps,
                                    "aggregationTemporality": 2,
                                    "isMonotonic": True}
                else:
                    entry["gauge"] = {"dataPoints": dps}
            otlp_metrics.append(entry)
        rm_out.append({
            "resource": {"attributes": dict_to_kvlist(resource)},
            "scopeMetrics": [{"scope": {"name": "fluentbit_tpu"},
                              "metrics": otlp_metrics}],
        })
    return {"resourceMetrics": rm_out}


# ---------------------------------------------------------- profiles

_PROFILE_NS_FIELDS = ("timeNanos", "startTimeUnixNano",
                      "endTimeUnixNano", "durationNanos", "timeUnixNano")


def _normalize_profile(p: dict) -> dict:
    out = dict(p)
    for f in _PROFILE_NS_FIELDS:
        if f in out:
            out[f] = _ns(out[f])
    if out.get("profileId"):
        out["profileId"] = _id_bytes(out["profileId"]) or out["profileId"]
    if isinstance(out.get("attributes"), list):
        out["attributes"] = kvlist_to_dict(out["attributes"])
    return out


def _profile_to_otlp(p: dict) -> dict:
    out = dict(p)
    for f in _PROFILE_NS_FIELDS:
        if f in out:
            out[f] = str(out[f])
    if isinstance(out.get("profileId"), bytes):
        out["profileId"] = base64.b64encode(
            out["profileId"]).decode("ascii")
    if isinstance(out.get("attributes"), dict):
        out["attributes"] = dict_to_kvlist(out["attributes"])
    return out


def decode_otlp_profiles(payload: dict) -> Tuple[dict, int]:
    """ExportProfilesServiceRequest JSON (development/profiles signal)
    → typed payload + profile count. Resource/scope attributes become
    dicts; the pprof-style tables inside each profile pass through
    structurally (the cprofiles approach: same model, C structs)."""
    rp_out = []
    n = 0
    for rp in payload.get("resourceProfiles", []):
        resource = kvlist_to_dict(
            (rp.get("resource") or {}).get("attributes", []))
        scopes = []
        for sp in rp.get("scopeProfiles", []):
            profiles = [_normalize_profile(p)
                        for p in sp.get("profiles", [])]
            n += len(profiles)
            scopes.append({"scope": _scope_to_py(sp.get("scope")),
                           "profiles": profiles})
        rp_out.append({"resource": resource, "scopeProfiles": scopes})
    return {"resourceProfiles": rp_out}, n


def encode_otlp_profiles(payloads: List[dict]) -> dict:
    rp_out = []
    for payload in payloads:
        for rp in payload.get("resourceProfiles", []):
            rp_out.append({
                "resource": {
                    "attributes": dict_to_kvlist(rp.get("resource", {}))},
                "scopeProfiles": [{
                    "scope": _scope_to_otlp(sp.get("scope", {})),
                    "profiles": [_profile_to_otlp(p)
                                 for p in sp.get("profiles", [])],
                } for sp in rp.get("scopeProfiles", [])],
            })
    return {"resourceProfiles": rp_out}


def is_profiles_payload(obj) -> bool:
    return isinstance(obj, dict) and "resourceProfiles" in obj
