from .msgpack import packb, unpackb, unpack_all, Unpacker, ExtType, EventTime
from .events import (
    LogEvent,
    encode_event,
    encode_events,
    decode_events,
    iter_events,
    reencode_event,
    count_records,
    now_event_time,
    GROUP_START,
    GROUP_END,
)
from .chunk import Chunk, ChunkPool, CHUNK_TARGET_SIZE

__all__ = [
    "packb", "unpackb", "unpack_all", "Unpacker", "ExtType", "EventTime",
    "LogEvent", "encode_event", "encode_events", "decode_events", "iter_events",
    "reencode_event", "count_records", "now_event_time", "GROUP_START", "GROUP_END",
    "Chunk", "ChunkPool", "CHUNK_TARGET_SIZE",
]
