"""msgpack codec (self-contained, no external dependency).

Implements the full msgpack spec (nil/bool/int/float/str/bin/array/map/ext),
including the Fluentd ``EventTime`` extension (ext type 0, 8 bytes:
uint32 seconds + uint32 nanoseconds) used for event timestamps.

Reference parity: lib/msgpack-c in the reference tree; EventTime semantics per
plugins/out_forward/forward.c (Fluentd forward protocol) and
src/flb_time.c (flb_time_append_to_msgpack).

A C++ accelerated codec (native/msgpack.cpp) can shadow these entry points;
the pure-Python version is the semantic reference and the fallback.
"""

from __future__ import annotations

import struct
from typing import Any, Callable, Iterator, List, Tuple

__all__ = [
    "packb",
    "unpackb",
    "Unpacker",
    "ExtType",
    "EventTime",
    "OutOfData",
]


class ExtType:
    """msgpack extension value: (code:int, data:bytes)."""

    __slots__ = ("code", "data")

    def __init__(self, code: int, data: bytes):
        self.code = code
        self.data = data

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, ExtType)
            and self.code == other.code
            and self.data == other.data
        )

    def __hash__(self) -> int:
        return hash((self.code, self.data))

    def __repr__(self) -> str:  # pragma: no cover
        return f"ExtType(code={self.code}, data={self.data!r})"


class EventTime:
    """Fluentd EventTime: seconds + nanoseconds (msgpack ext type 0).

    Compared equal to other EventTime with the same (sec, nsec). Convertible
    to float (lossy) via float().
    """

    __slots__ = ("sec", "nsec")

    CODE = 0

    def __init__(self, sec: int, nsec: int = 0):
        self.sec = int(sec)
        self.nsec = int(nsec)

    @classmethod
    def from_float(cls, ts: float) -> "EventTime":
        sec = int(ts)
        nsec = int(round((ts - sec) * 1e9))
        if nsec >= 1_000_000_000:
            sec += 1
            nsec -= 1_000_000_000
        return cls(sec, nsec)

    def to_bytes(self) -> bytes:
        return struct.pack(">II", self.sec & 0xFFFFFFFF, self.nsec & 0xFFFFFFFF)

    @classmethod
    def from_bytes(cls, data: bytes) -> "EventTime":
        sec, nsec = struct.unpack(">II", data)
        return cls(sec, nsec)

    def __float__(self) -> float:
        return self.sec + self.nsec / 1e9

    def __eq__(self, other: object) -> bool:
        if isinstance(other, EventTime):
            return self.sec == other.sec and self.nsec == other.nsec
        if isinstance(other, (int, float)):
            return float(self) == float(other)
        return NotImplemented

    def __lt__(self, other: "EventTime") -> bool:
        return (self.sec, self.nsec) < (other.sec, other.nsec)

    def __hash__(self) -> int:
        return hash((self.sec, self.nsec))

    def __repr__(self) -> str:  # pragma: no cover
        return f"EventTime({self.sec}, {self.nsec})"


class OutOfData(Exception):
    """Raised when the buffer ends mid-object."""


# ---------------------------------------------------------------------------
# Packing
# ---------------------------------------------------------------------------

_pack_into = struct.pack


def _pack(obj: Any, out: List[bytes]) -> None:
    t = type(obj)
    if obj is None:
        out.append(b"\xc0")
    elif t is bool:
        out.append(b"\xc3" if obj else b"\xc2")
    elif t is int:
        if obj >= 0:
            if obj < 0x80:
                out.append(bytes((obj,)))
            elif obj <= 0xFF:
                out.append(b"\xcc" + bytes((obj,)))
            elif obj <= 0xFFFF:
                out.append(_pack_into(">BH", 0xCD, obj))
            elif obj <= 0xFFFFFFFF:
                out.append(_pack_into(">BI", 0xCE, obj))
            elif obj <= 0xFFFFFFFFFFFFFFFF:
                out.append(_pack_into(">BQ", 0xCF, obj))
            else:
                raise OverflowError("int too large for msgpack")
        else:
            if obj >= -32:
                out.append(_pack_into("b", obj))
            elif obj >= -128:
                out.append(_pack_into(">Bb", 0xD0, obj))
            elif obj >= -32768:
                out.append(_pack_into(">Bh", 0xD1, obj))
            elif obj >= -2147483648:
                out.append(_pack_into(">Bi", 0xD2, obj))
            elif obj >= -9223372036854775808:
                out.append(_pack_into(">Bq", 0xD3, obj))
            else:
                raise OverflowError("int too small for msgpack")
    elif t is float:
        out.append(_pack_into(">Bd", 0xCB, obj))
    elif t is str:
        b = obj.encode("utf-8")
        n = len(b)
        if n < 32:
            out.append(bytes((0xA0 | n,)))
        elif n <= 0xFF:
            out.append(_pack_into(">BB", 0xD9, n))
        elif n <= 0xFFFF:
            out.append(_pack_into(">BH", 0xDA, n))
        else:
            out.append(_pack_into(">BI", 0xDB, n))
        out.append(b)
    elif t is bytes or t is bytearray or t is memoryview:
        b = bytes(obj)
        n = len(b)
        if n <= 0xFF:
            out.append(_pack_into(">BB", 0xC4, n))
        elif n <= 0xFFFF:
            out.append(_pack_into(">BH", 0xC5, n))
        else:
            out.append(_pack_into(">BI", 0xC6, n))
        out.append(b)
    elif t is list or t is tuple:
        n = len(obj)
        if n < 16:
            out.append(bytes((0x90 | n,)))
        elif n <= 0xFFFF:
            out.append(_pack_into(">BH", 0xDC, n))
        else:
            out.append(_pack_into(">BI", 0xDD, n))
        for item in obj:
            _pack(item, out)
    elif t is dict:
        n = len(obj)
        if n < 16:
            out.append(bytes((0x80 | n,)))
        elif n <= 0xFFFF:
            out.append(_pack_into(">BH", 0xDE, n))
        else:
            out.append(_pack_into(">BI", 0xDF, n))
        for k, v in obj.items():
            _pack(k, out)
            _pack(v, out)
    elif t is EventTime:
        # fixext8, type 0
        out.append(b"\xd7\x00" + obj.to_bytes())
    elif t is ExtType:
        data = obj.data
        n = len(data)
        code = obj.code & 0xFF
        if n == 1:
            out.append(bytes((0xD4, code)))
        elif n == 2:
            out.append(bytes((0xD5, code)))
        elif n == 4:
            out.append(bytes((0xD6, code)))
        elif n == 8:
            out.append(bytes((0xD7, code)))
        elif n == 16:
            out.append(bytes((0xD8, code)))
        elif n <= 0xFF:
            out.append(_pack_into(">BBB", 0xC7, n, code))
        elif n <= 0xFFFF:
            out.append(_pack_into(">BHB", 0xC8, n, code))
        else:
            out.append(_pack_into(">BIB", 0xC9, n, code))
        out.append(data)
    elif isinstance(obj, (int, float, str, bytes, list, tuple, dict)):
        # subclasses (e.g. enum.IntEnum, numpy scalars via __index__)
        if isinstance(obj, bool):
            out.append(b"\xc3" if obj else b"\xc2")
        elif isinstance(obj, int):
            _pack(int(obj), out)
        elif isinstance(obj, float):
            _pack(float(obj), out)
        elif isinstance(obj, str):
            _pack(str(obj), out)
        elif isinstance(obj, bytes):
            _pack(bytes(obj), out)
        elif isinstance(obj, (list, tuple)):
            _pack(list(obj), out)
        else:
            _pack(dict(obj), out)
    else:
        # numpy integer/float scalars without being subclasses
        if hasattr(obj, "item"):
            _pack(obj.item(), out)
            return
        raise TypeError(f"cannot pack object of type {t!r}")


def packb(obj: Any) -> bytes:
    """Serialize ``obj`` to msgpack bytes."""
    out: List[bytes] = []
    _pack(obj, out)
    return b"".join(out)


# ---------------------------------------------------------------------------
# Unpacking
# ---------------------------------------------------------------------------

_U8 = struct.Struct(">B")
_U16 = struct.Struct(">H")
_U32 = struct.Struct(">I")
_U64 = struct.Struct(">Q")
_I8 = struct.Struct(">b")
_I16 = struct.Struct(">h")
_I32 = struct.Struct(">i")
_I64 = struct.Struct(">q")
_F32 = struct.Struct(">f")
_F64 = struct.Struct(">d")


def _default_ext_hook(code: int, data: bytes) -> Any:
    if code == EventTime.CODE and len(data) == 8:
        return EventTime.from_bytes(data)
    return ExtType(code, data)


class Unpacker:
    """Streaming unpacker over a bytes-like buffer.

    Usage::

        u = Unpacker(buf)
        for obj in u: ...

    ``tell()`` reports the byte offset of the next object, which the chunk
    layer uses to slice raw per-record msgpack regions out of a chunk.
    """

    def __init__(self, buf: bytes = b"", ext_hook: Callable[[int, bytes], Any] = _default_ext_hook):
        self._buf = memoryview(bytes(buf)) if not isinstance(buf, (bytes, memoryview)) else memoryview(buf)
        self._pos = 0
        self._ext_hook = ext_hook

    def feed(self, data: bytes) -> None:
        remaining = bytes(self._buf[self._pos:]) + bytes(data)
        self._buf = memoryview(remaining)
        self._pos = 0

    def tell(self) -> int:
        return self._pos

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        if self._pos >= len(self._buf):
            raise StopIteration
        start = self._pos
        try:
            return self._unpack_one()
        except OutOfData:
            self._pos = start
            raise StopIteration

    def unpack(self) -> Any:
        """Unpack a single object; raises OutOfData if incomplete."""
        return self._unpack_one()

    # -- internals --

    def _need(self, n: int) -> memoryview:
        if self._pos + n > len(self._buf):
            raise OutOfData()
        mv = self._buf[self._pos : self._pos + n]
        self._pos += n
        return mv

    def _unpack_one(self) -> Any:
        b = self._need(1)[0]
        if b < 0x80:
            return b
        if b >= 0xE0:
            return b - 0x100
        if 0x80 <= b <= 0x8F:
            return self._unpack_map(b & 0x0F)
        if 0x90 <= b <= 0x9F:
            return self._unpack_array(b & 0x0F)
        if 0xA0 <= b <= 0xBF:
            return str(self._need(b & 0x1F), "utf-8", "replace")
        if b == 0xC0:
            return None
        if b == 0xC2:
            return False
        if b == 0xC3:
            return True
        if b == 0xC4:
            return bytes(self._need(self._need(1)[0]))
        if b == 0xC5:
            return bytes(self._need(_U16.unpack(self._need(2))[0]))
        if b == 0xC6:
            return bytes(self._need(_U32.unpack(self._need(4))[0]))
        if b == 0xC7:
            n = self._need(1)[0]
            code = _I8.unpack(self._need(1))[0]
            return self._ext_hook(code, bytes(self._need(n)))
        if b == 0xC8:
            n = _U16.unpack(self._need(2))[0]
            code = _I8.unpack(self._need(1))[0]
            return self._ext_hook(code, bytes(self._need(n)))
        if b == 0xC9:
            n = _U32.unpack(self._need(4))[0]
            code = _I8.unpack(self._need(1))[0]
            return self._ext_hook(code, bytes(self._need(n)))
        if b == 0xCA:
            return _F32.unpack(self._need(4))[0]
        if b == 0xCB:
            return _F64.unpack(self._need(8))[0]
        if b == 0xCC:
            return self._need(1)[0]
        if b == 0xCD:
            return _U16.unpack(self._need(2))[0]
        if b == 0xCE:
            return _U32.unpack(self._need(4))[0]
        if b == 0xCF:
            return _U64.unpack(self._need(8))[0]
        if b == 0xD0:
            return _I8.unpack(self._need(1))[0]
        if b == 0xD1:
            return _I16.unpack(self._need(2))[0]
        if b == 0xD2:
            return _I32.unpack(self._need(4))[0]
        if b == 0xD3:
            return _I64.unpack(self._need(8))[0]
        if 0xD4 <= b <= 0xD8:
            n = 1 << (b - 0xD4)
            code = _I8.unpack(self._need(1))[0]
            return self._ext_hook(code, bytes(self._need(n)))
        if b == 0xD9:
            return str(self._need(self._need(1)[0]), "utf-8", "replace")
        if b == 0xDA:
            return str(self._need(_U16.unpack(self._need(2))[0]), "utf-8", "replace")
        if b == 0xDB:
            return str(self._need(_U32.unpack(self._need(4))[0]), "utf-8", "replace")
        if b == 0xDC:
            return self._unpack_array(_U16.unpack(self._need(2))[0])
        if b == 0xDD:
            return self._unpack_array(_U32.unpack(self._need(4))[0])
        if b == 0xDE:
            return self._unpack_map(_U16.unpack(self._need(2))[0])
        if b == 0xDF:
            return self._unpack_map(_U32.unpack(self._need(4))[0])
        raise ValueError(f"invalid msgpack byte 0x{b:02x}")

    def _unpack_array(self, n: int) -> list:
        return [self._unpack_one() for _ in range(n)]

    def _unpack_map(self, n: int) -> dict:
        out = {}
        for _ in range(n):
            k = self._unpack_one()
            if isinstance(k, (dict, list)):
                k = repr(k)  # unhashable keys: degrade gracefully
            out[k] = self._unpack_one()
        return out


def unpackb(buf: bytes) -> Any:
    """Deserialize a single msgpack object from ``buf``."""
    u = Unpacker(buf)
    obj = u.unpack()
    return obj


def unpack_all(buf: bytes) -> List[Any]:
    """Deserialize all concatenated msgpack objects in ``buf``."""
    return list(Unpacker(buf))
