"""Tagged chunks — the unit of buffering, routing and flushing.

Reference semantics (src/flb_input_chunk.c): each input owns a pool of
chunks keyed by tag; appends go to the active chunk for that tag until it
reaches the ~2MB target size (FLB_INPUT_CHUNK_FS_MAX_SIZE class constants),
at which point it is "locked" (src/flb_input_chunk.c:3135) and a new chunk
is opened. Dispatch walks ready chunks and creates one task per chunk.

This module is pure data — storage backends (memory/filesystem, CRC32
persistence) live in fluentbit_tpu.core.storage.
"""

from __future__ import annotations

import itertools
import time
from typing import Dict, Iterator, List, Optional

from ..core import copywitness as _cw
from .events import LogEvent, decode_events

# Reference: chunks are locked once above ~2MB so flushes stay bounded.
CHUNK_TARGET_SIZE = 2 * 1024 * 1024

_chunk_ids = itertools.count(1)

# Event types carried by a chunk (reference: FLB_INPUT_LOGS/METRICS/TRACES/
# PROFILES/BLOBS in include/fluent-bit/flb_input.h).
EVENT_TYPE_LOGS = "logs"
EVENT_TYPE_METRICS = "metrics"
EVENT_TYPE_TRACES = "traces"
EVENT_TYPE_PROFILES = "profiles"
EVENT_TYPE_BLOBS = "blobs"


class Chunk:
    """A tagged, append-only buffer of concatenated msgpack events."""

    __slots__ = (
        "id",
        "tag",
        "event_type",
        "_parts",
        "_size",
        "records",
        "created",
        "locked",
        "routes_mask",
        "route_names",
        "in_name",
        "qos_tenant",
        "priority",
    )

    def __init__(self, tag: str, event_type: str = EVENT_TYPE_LOGS, in_name: str = ""):
        self.id = next(_chunk_ids)
        self.tag = tag
        self.event_type = event_type
        # appended spans are kept as a part list and joined lazily at
        # get_bytes(): append is O(1) instead of a bytearray grow-copy
        # — on the 2MB/chunk hot path that removes one full copy of
        # every ingested byte (src/flb_input_chunk.c appends into
        # chunkio-mapped memory for the same reason)
        self._parts: List[bytes] = []
        self._size = 0
        self.records = 0
        self.created = time.time()
        self.locked = False
        self.routes_mask = 0
        # recovered conditional chunks carry route NAMES (bit positions
        # are meaningless across a config change/restart)
        self.route_names = None
        self.in_name = in_name
        # fbtpu-qos stamps (core/qos.py): tenant + priority class are
        # assigned at first dispatch enqueue and survive shed/readmit
        # cycles; priority additionally survives a restart (storage
        # persists it in the header pad byte)
        self.qos_tenant = None
        self.priority = None

    @property
    def size(self) -> int:
        return self._size

    @property
    def buf(self) -> bytes:
        """Joined view (kept for storage recovery + tests)."""
        return self.get_bytes()

    @buf.setter
    def buf(self, payload) -> None:
        # bytes(bytes_obj) adopts without copying — only non-bytes
        # payloads (replay handing a bytearray, tests) materialize
        if _cw.witness_enabled() and not isinstance(payload, bytes):
            _cw.count("chunk.buf.materialize", len(payload))
        self._parts = [bytes(payload)]
        self._size = len(self._parts[0])

    def append(self, data: bytes, n_records: int) -> None:
        if self.locked:
            raise RuntimeError("append to locked chunk")
        # the ONE owned copy of the ingest path: appended spans may be
        # views of reused arenas (native.grep_filter) or caller buffers,
        # so the chunk must own its bytes; bytes-in adopts copy-free
        if _cw.witness_enabled() and not isinstance(data, bytes):
            _cw.count("chunk.append.materialize", len(data))
        self._parts.append(bytes(data))
        self._size += len(data)
        self.records += n_records
        if self._size >= CHUNK_TARGET_SIZE:
            self.locked = True

    def get_bytes(self) -> bytes:
        # appends may race on the threaded raw-ingest path (reader
        # holds a different lock). Reading `locked` BEFORE the parts
        # snapshot makes the cache safe: append() publishes its part
        # before setting locked, so locked-at-entry implies the
        # snapshot is complete and final.
        locked_first = self.locked
        parts = list(self._parts)
        if len(parts) == 1:
            return parts[0]
        joined = b"".join(parts)
        if locked_first:
            self._parts = [joined]
        return joined

    def decode(self) -> List[LogEvent]:
        return decode_events(self.get_bytes())

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"Chunk(id={self.id}, tag={self.tag!r}, type={self.event_type}, "
            f"size={self.size}, records={self.records})"
        )


class ChunkPool:
    """Per-input chunk pool keyed by (event_type, tag).

    Reference: ht_log_chunks hashtable per input (src/flb_input_log.c:1524);
    input_chunk_get selects/creates the active chunk
    (src/flb_input_chunk.c:3000).
    """

    def __init__(self, in_name: str = ""):
        self.in_name = in_name
        self._active: Dict[tuple, Chunk] = {}
        self._ready: List[Chunk] = []
        self.total_bytes = 0
        # fan-in QoS stamp: (tenant, priority) the NEXT appends belong
        # to. The forward server sets it around input_log_append so a
        # relayed chunk carries the edge tenant named on the wire, not
        # the aggregator input's own tenant — and it joins the chunk
        # key, so records of different remote tenants never merge into
        # one chunk (a chunk has exactly one qos_tenant slot).
        self.stamp = None

    def append(self, tag: str, data: bytes, n_records: int,
               event_type: str = EVENT_TYPE_LOGS,
               routes_mask: int = 0) -> Chunk:
        # routes_mask joins the chunk key: conditionally-routed record
        # groups must never merge into a chunk with different routes
        # (reference split_and_append_route_payloads,
        # src/flb_input_log.c:1495)
        key = (event_type, tag, routes_mask, self.stamp)
        chunk = self._active.get(key)
        if chunk is None or chunk.locked:
            if chunk is not None and chunk.locked:
                self._ready.append(chunk)
            chunk = Chunk(tag, event_type, self.in_name)
            chunk.routes_mask = routes_mask
            if self.stamp is not None:
                chunk.qos_tenant, chunk.priority = self.stamp
            self._active[key] = chunk
        chunk.append(data, n_records)
        self.total_bytes += len(data)
        if chunk.locked:
            self._ready.append(chunk)
            del self._active[key]
        return chunk

    def evict_oldest(self, bytes_needed: int):
        """memrb eviction (src/flb_input_chunk.c:2936-2966): drop the
        OLDEST buffered chunks until ``bytes_needed`` is freed; returns
        the dropped chunks so the caller can count them in metrics."""
        dropped = []
        freed = 0
        while freed < bytes_needed and self._ready:
            c = self._ready.pop(0)
            freed += c.size
            self.total_bytes -= c.size
            dropped.append(c)
        if freed < bytes_needed:
            for key in sorted(self._active,
                              key=lambda k: self._active[k].created):
                if freed >= bytes_needed:
                    break
                c = self._active.pop(key)
                freed += c.size
                self.total_bytes -= c.size
                dropped.append(c)
        return dropped

    def rotate_conditional(self) -> None:
        """Close every ACTIVE conditionally-routed chunk (hot reload:
        the outputs list is about to change, and the active map keys
        on the ingest-time routes_mask — a post-swap append computing
        the same mask value against the NEW outputs must not merge
        into a chunk whose persisted route_names still name the old
        generation). Closed chunks flush under their stamped names;
        fresh appends open fresh chunks with fresh names."""
        for key in [k for k, c in self._active.items()
                    if c.routes_mask]:
            c = self._active.pop(key)
            if c.records > 0:
                c.locked = True
                self._ready.append(c)

    def drain(self) -> List[Chunk]:
        """Take all flushable chunks (locked + currently active non-empty)."""
        out = list(self._ready)
        self._ready.clear()
        for key in list(self._active):
            c = self._active.pop(key)
            if c.records > 0:
                c.locked = True
                out.append(c)
        for c in out:
            self.total_bytes -= c.size
        if not self._active and not self._ready:
            self.total_bytes = 0
        return out

    def iter_pending(self) -> Iterator[Chunk]:
        yield from self._ready
        yield from self._active.values()

    @property
    def pending_bytes(self) -> int:
        return self.total_bytes

    @property
    def pending_chunks(self) -> int:
        return len(self._ready) + sum(1 for c in self._active.values() if c.records)
