"""NFA construction + subset-construction DFA over byte classes.

The TPU regex execution model (replacing Onigmo, lib/onigmo — the thing
the north star re-expresses as a vectorized automaton kernel):

- Thompson NFA over a 258-symbol alphabet: bytes 0..255, EOL (end of
  input), BOS (begin of input).
- Ruby-syntax zero-width anchors become *constraint epsilon edges*:
  ``^`` crossable only when the previously consumed symbol ∈ {BOS, \\n},
  ``$`` crossable only when the next symbol ∈ {EOL, \\n}, \\A/\\z/\\Z
  analogous. This gives exact ONIG_SYNTAX_RUBY line-anchor semantics
  (src/flb_regex.c:146) without lookaround machinery.
- Unanchored search is a scan self-loop state with an epsilon into the
  pattern (RE2-style), so one pass answers "match anywhere".
- The accept NFA state is absorbing (self-loop on every symbol): a DFA
  run needs NO per-position accept check — feed bytes then EOL(s);
  matched ⟺ final state == ACC. Padding positions map to the EOL class,
  which makes fixed-shape ``[B, L]`` batches trivially correct on device.
- Subset construction compresses 258 symbols into equivalence classes;
  the kernel table is ``trans[S, C] : int32`` + ``class_map[257] : uint8``
  (entry 256 = EOL class, used for padding).

DFA state ids: 0 = DEAD (absorbing reject), 1 = ACC (absorbing accept),
2 = start (after BOS folded in).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Tuple

import numpy as np

from .parser import (
    ALL_BYTES,
    Alt,
    Anchor,
    Group,
    Lit,
    Node,
    ParsedRegex,
    Rep,
    Seq,
    UnsupportedRegex,
    parse,
)

EOL = 256
BOS = 257
EOL_BIT = 1 << EOL
BOS_BIT = 1 << BOS
NL_BIT = 1 << 10
ALL_SYMS = (1 << 258) - 1

DEAD = 0
ACC = 1
START = 2


class _NFA:
    """Mutable NFA being built. Edge kinds:
    byte edges: consume a symbol in mask; eps edges: zero-width, with an
    optional ('prev'|'next', mask) constraint."""

    def __init__(self) -> None:
        self.byte_edges: List[List[Tuple[int, int]]] = []  # state -> [(mask, dst)]
        self.eps_edges: List[List[Tuple[Optional[str], int, int]]] = []  # (kind, mask, dst)

    def new_state(self) -> int:
        self.byte_edges.append([])
        self.eps_edges.append([])
        return len(self.byte_edges) - 1

    def add_byte(self, src: int, mask: int, dst: int) -> None:
        self.byte_edges[src].append((mask, dst))

    def add_eps(self, src: int, dst: int, kind: Optional[str] = None, mask: int = 0) -> None:
        self.eps_edges[src].append((kind, mask, dst))


def _build(nfa: _NFA, node: Node, start: int) -> int:
    """Thompson construction; returns the fragment's end state."""
    if isinstance(node, Lit):
        end = nfa.new_state()
        nfa.add_byte(start, node.mask, end)
        return end
    if isinstance(node, Seq):
        cur = start
        for item in node.items:
            cur = _build(nfa, item, cur)
        return cur
    if isinstance(node, Group):
        return _build(nfa, node.node, start)
    if isinstance(node, Alt):
        end = nfa.new_state()
        for item in node.items:
            b_start = nfa.new_state()
            nfa.add_eps(start, b_start)
            b_end = _build(nfa, item, b_start)
            nfa.add_eps(b_end, end)
        return end
    if isinstance(node, Rep):
        cur = start
        for _ in range(node.min):
            cur = _build(nfa, node.node, cur)
        if node.max is None:
            # star/plus tail: loop state
            loop = nfa.new_state()
            nfa.add_eps(cur, loop)
            inner_start = nfa.new_state()
            nfa.add_eps(loop, inner_start)
            inner_end = _build(nfa, node.node, inner_start)
            nfa.add_eps(inner_end, loop)
            return loop
        else:
            # up to (max-min) optional copies
            ends = [cur]
            for _ in range(node.max - node.min):
                cur = _build(nfa, node.node, cur)
                ends.append(cur)
            end = nfa.new_state()
            for e in ends:
                nfa.add_eps(e, end)
            return end
    if isinstance(node, Anchor):
        end = nfa.new_state()
        if node.kind == "bol":
            nfa.add_eps(start, end, "prev", BOS_BIT | NL_BIT)
        elif node.kind == "bos":
            nfa.add_eps(start, end, "prev", BOS_BIT)
        elif node.kind == "eol":
            nfa.add_eps(start, end, "next", EOL_BIT | NL_BIT)
        elif node.kind == "eos":
            nfa.add_eps(start, end, "next", EOL_BIT)
        elif node.kind == "eos_nl":
            # \Z: end of string, or before a final newline
            nfa.add_eps(start, end, "next", EOL_BIT)
            mid = nfa.new_state()
            nfa.add_eps(start, mid, "next", NL_BIT)
            mid2 = nfa.new_state()
            nfa.add_byte(mid, NL_BIT, mid2)
            nfa.add_eps(mid2, end, "next", EOL_BIT)
        else:
            raise UnsupportedRegex(f"anchor {node.kind}")
        return end
    raise TypeError(f"unknown AST node {node!r}")


@dataclass
class DFA:
    """Compiled table-driven DFA (the kernel input).

    trans[S, C] int32, class_map[257] uint8 (index 256 = EOL class, used
    for padded positions), start id, ACC==1 absorbing accept, DEAD==0.
    """

    trans: np.ndarray
    class_map: np.ndarray
    start: int
    n_states: int
    n_classes: int
    pattern: str

    @property
    def eol_class(self) -> int:
        return int(self.class_map[EOL])

    def match_bytes(self, data: bytes) -> bool:
        """CPU reference matcher (search semantics, like flb_regex_match)."""
        state = self.start
        trans = self.trans
        cmap = self.class_map
        for b in data:
            state = trans[state, cmap[b]]
            if state <= ACC:  # DEAD or ACC — both absorbing
                return state == ACC
        state = trans[state, cmap[EOL]]
        return state == ACC

    def match_batch_np(self, batch: np.ndarray, lengths: np.ndarray) -> np.ndarray:
        """Vectorized numpy matcher over [B, L] uint8 padded batch
        (test oracle for the device kernel)."""
        B, L = batch.shape
        cls = self.class_map[batch]  # [B, L]
        pad = np.arange(L)[None, :] >= lengths[:, None]
        cls[pad] = self.eol_class
        state = np.full((B,), self.start, dtype=np.int32)
        trans = self.trans
        for i in range(L):
            state = trans[state, cls[:, i]]
        state = trans[state, np.full((B,), self.eol_class)]
        # negative lengths mark invalid rows (missing field -1 / overflow
        # -2) which must never match — same guard as the device kernel
        return (state == ACC) & (lengths >= 0)


def compose_supersteps(trans: np.ndarray, k: int) -> np.ndarray:
    """Pre-compose a [S, C] table to k-byte super-steps: [S, C^k] with
    T_k[s, c1*C^(k-1) + ... + ck] = T[...T[T[s, c1], c2]..., ck].

    The single source of the super-step index order — both the device
    kernel (ops/grep.py GrepProgram) and the native C++ twin
    (native/__init__.py GrepTables) build their tables here, keeping the
    bit-exact contract between them in one place."""
    S, C = trans.shape
    out = trans
    for _ in range(k - 1):
        # out[s, w] = state after word w; extend by one byte:
        # new[s, w*C + c] = trans[out[s, w], c]
        out = trans[out.reshape(-1)].reshape(S, -1)
    return out


def _minimize(trans: np.ndarray, start: int) -> Tuple[np.ndarray, int]:
    """Moore partition refinement. Subset construction leaves many
    equivalent states (every optional trailing group of a pattern forks
    the subsets), which (a) bloats the kernel tables S-fold — the
    parallel-in-time device kernel does S× work per position — and
    (b) hides the self-loop structure the native accel scan needs: a
    `[^ ]*` skeleton state only LOOKS like a self-loop after its clones
    are merged. Language is unchanged, so all verdict paths stay
    bit-identical.

    Keeps the DEAD=0 / ACC=1 absorbing-id contract: any state from
    which ACC is unreachable merges into DEAD; ACC (the only accepting
    state, absorbing) stays a singleton partition."""
    S, C = trans.shape
    # initial partition: accepting (ACC) vs rest
    part = np.zeros(S, dtype=np.int64)
    part[ACC] = 1
    n_blocks = 2
    while True:
        # signature: own block + successor blocks per class
        sig = np.empty((S, C + 1), dtype=np.int64)
        sig[:, 0] = part
        sig[:, 1:] = part[trans]
        _, new = np.unique(sig, axis=0, return_inverse=True)
        n_new = int(new.max()) + 1
        if n_new == n_blocks:  # refinement only splits: no growth = fixed point
            break
        part, n_blocks = new, n_new
    # renumber blocks: DEAD's block -> 0, ACC's block -> 1, rest 2..
    remap = np.full(int(part.max()) + 1, -1, dtype=np.int64)
    remap[part[DEAD]] = DEAD
    remap[part[ACC]] = ACC
    nxt = 2
    for b in part:
        if remap[b] < 0:
            remap[b] = nxt
            nxt += 1
    new_ids = remap[part]
    n_new = nxt
    new_trans = np.zeros((n_new, C), dtype=np.int32)
    # one representative per block suffices (blocks are equivalence classes)
    seen = np.zeros(n_new, dtype=bool)
    for s in range(S):
        ns = new_ids[s]
        if not seen[ns]:
            seen[ns] = True
            new_trans[ns] = new_ids[trans[s]]
    return new_trans, int(new_ids[start])


def compile_dfa(pattern, ignorecase: bool = False, dot_all: bool = False,
                max_states: int = 4096) -> DFA:
    """Compile a pattern (str or ParsedRegex) to a scan DFA.

    Raises UnsupportedRegex for non-DFA-expressible constructs; callers
    fall back to the CPU engine (the same split the north star requires).
    """
    if isinstance(pattern, ParsedRegex):
        parsed = pattern
    else:
        parsed = parse(pattern, ignorecase=ignorecase, dot_all=dot_all)

    nfa = _NFA()
    pre = nfa.new_state()         # consumes the virtual BOS symbol
    scan = nfa.new_state()        # unanchored search loop
    nfa.add_byte(pre, BOS_BIT, scan)
    nfa.add_byte(scan, ALL_BYTES, scan)
    p_start = nfa.new_state()
    nfa.add_eps(scan, p_start)
    p_end = _build(nfa, parsed.root, p_start)
    accept = nfa.new_state()
    nfa.add_eps(p_end, accept)
    # absorbing accept: self-loop on every symbol incl. EOL/BOS
    nfa.add_byte(accept, ALL_SYMS, accept)

    n = len(nfa.byte_edges)

    # ---- symbol equivalence classes ----
    # refine {0..257} by every mask used anywhere (byte edges + constraints)
    masks = set()
    for st in range(n):
        for m, _ in nfa.byte_edges[st]:
            masks.add(m & ALL_SYMS)
        for kind, m, _ in nfa.eps_edges[st]:
            if kind is not None:
                masks.add(m & ALL_SYMS)
    masks.add(EOL_BIT)
    masks.add(BOS_BIT)
    sig_map: Dict[Tuple[bool, ...], int] = {}
    sym_class = np.zeros(258, dtype=np.int32)
    mask_list = sorted(masks)
    for sym in range(258):
        sig = tuple(bool(m >> sym & 1) for m in mask_list)
        cid = sig_map.setdefault(sig, len(sig_map))
        sym_class[sym] = cid
    n_classes = len(sig_map)
    # one representative symbol per class
    rep: List[int] = [0] * n_classes
    for sym in range(257, -1, -1):
        rep[sym_class[sym]] = sym

    # ---- closures ----
    def closure_plain(states: FrozenSet[int]) -> FrozenSet[int]:
        out = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for kind, m, dst in nfa.eps_edges[s]:
                if kind is None and dst not in out:
                    out.add(dst)
                    stack.append(dst)
        return frozenset(out)

    def closure_after(states: set, sym: int) -> FrozenSet[int]:
        """Cross plain eps + prev-constraint eps (prev symbol = sym)."""
        out = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for kind, m, dst in nfa.eps_edges[s]:
                if kind == "next":
                    continue
                if kind == "prev" and not (m >> sym & 1):
                    continue
                if dst not in out:
                    out.add(dst)
                    stack.append(dst)
        return frozenset(out)

    def pre_closure(states: FrozenSet[int], sym: int) -> set:
        """Cross plain eps + next-constraint eps (next symbol = sym)."""
        out = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for kind, m, dst in nfa.eps_edges[s]:
                if kind == "prev":
                    continue
                if kind == "next" and not (m >> sym & 1):
                    continue
                if dst not in out:
                    out.add(dst)
                    stack.append(dst)
        return out

    def move(states: FrozenSet[int], sym: int) -> FrozenSet[int]:
        src = pre_closure(states, sym)
        stepped = set()
        for s in src:
            for m, dst in nfa.byte_edges[s]:
                if m >> sym & 1:
                    stepped.add(dst)
        return closure_after(stepped, sym)

    # ---- subset construction ----
    init = closure_plain(frozenset([pre]))
    start_set = move(init, BOS)  # fold BOS into the start state

    def canon(states: FrozenSet[int]) -> object:
        if accept in states:
            return "ACC"
        if not states:
            return "DEAD"
        return states

    dfa_ids: Dict[object, int] = {"DEAD": DEAD, "ACC": ACC}
    table: List[List[int]] = [[DEAD] * n_classes, [ACC] * n_classes]
    worklist: List[FrozenSet[int]] = []

    def get_id(states: FrozenSet[int]) -> int:
        key = canon(states)
        if key in dfa_ids:
            return dfa_ids[key]
        sid = len(table)
        if sid > max_states:
            raise UnsupportedRegex(
                f"DFA exceeds {max_states} states for pattern {parsed.pattern!r}"
            )
        dfa_ids[key] = sid
        table.append([DEAD] * n_classes)
        worklist.append(states)
        return sid

    start_id = get_id(start_set)
    while worklist:
        states = worklist.pop()
        sid = dfa_ids[canon(states)]
        for cid in range(n_classes):
            sym = rep[cid]
            if sym == BOS:
                continue  # BOS never appears mid-stream
            table[sid][cid] = get_id(move(states, sym))

    trans = np.asarray(table, dtype=np.int32)
    trans, start_id = _minimize(trans, start_id)
    class_map = sym_class[:257].astype(np.uint8)
    return DFA(
        trans=trans,
        class_map=class_map,
        start=start_id,
        n_states=trans.shape[0],
        n_classes=n_classes,
        pattern=parsed.pattern,
    )
