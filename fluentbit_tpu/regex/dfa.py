"""NFA construction + subset-construction DFA over byte classes.

The TPU regex execution model (replacing Onigmo, lib/onigmo — the thing
the north star re-expresses as a vectorized automaton kernel):

- Thompson NFA over a 258-symbol alphabet: bytes 0..255, EOL (end of
  input), BOS (begin of input).
- Ruby-syntax zero-width anchors become *constraint epsilon edges*:
  ``^`` crossable only when the previously consumed symbol ∈ {BOS, \\n},
  ``$`` crossable only when the next symbol ∈ {EOL, \\n}, \\A/\\z/\\Z
  analogous. This gives exact ONIG_SYNTAX_RUBY line-anchor semantics
  (src/flb_regex.c:146) without lookaround machinery.
- Unanchored search is a scan self-loop state with an epsilon into the
  pattern (RE2-style), so one pass answers "match anywhere".
- The accept NFA state is absorbing (self-loop on every symbol): a DFA
  run needs NO per-position accept check — feed bytes then EOL(s);
  matched ⟺ final state == ACC. Padding positions map to the EOL class,
  which makes fixed-shape ``[B, L]`` batches trivially correct on device.
- Subset construction compresses 258 symbols into equivalence classes;
  the kernel table is ``trans[S, C] : int32`` + ``class_map[257] : uint8``
  (entry 256 = EOL class, used for padding).

DFA state ids: 0 = DEAD (absorbing reject), 1 = ACC (absorbing accept),
2 = start (after BOS folded in).
"""

from __future__ import annotations

import os
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Tuple

import numpy as np

from .parser import (
    ALL_BYTES,
    Alt,
    Anchor,
    Group,
    Lit,
    Node,
    ParsedRegex,
    Rep,
    Seq,
    UnsupportedRegex,
    parse,
)

EOL = 256
BOS = 257
EOL_BIT = 1 << EOL
BOS_BIT = 1 << BOS
NL_BIT = 1 << 10
ALL_SYMS = (1 << 258) - 1

DEAD = 0
ACC = 1
START = 2


class _NFA:
    """Mutable NFA being built. Edge kinds:
    byte edges: consume a symbol in mask; eps edges: zero-width, with an
    optional ('prev'|'next', mask) constraint."""

    def __init__(self) -> None:
        self.byte_edges: List[List[Tuple[int, int]]] = []  # state -> [(mask, dst)]
        self.eps_edges: List[List[Tuple[Optional[str], int, int]]] = []  # (kind, mask, dst)

    def new_state(self) -> int:
        self.byte_edges.append([])
        self.eps_edges.append([])
        return len(self.byte_edges) - 1

    def add_byte(self, src: int, mask: int, dst: int) -> None:
        self.byte_edges[src].append((mask, dst))

    def add_eps(self, src: int, dst: int, kind: Optional[str] = None, mask: int = 0) -> None:
        self.eps_edges[src].append((kind, mask, dst))


def _build(nfa: _NFA, node: Node, start: int) -> int:
    """Thompson construction; returns the fragment's end state."""
    if isinstance(node, Lit):
        end = nfa.new_state()
        nfa.add_byte(start, node.mask, end)
        return end
    if isinstance(node, Seq):
        cur = start
        for item in node.items:
            cur = _build(nfa, item, cur)
        return cur
    if isinstance(node, Group):
        return _build(nfa, node.node, start)
    if isinstance(node, Alt):
        end = nfa.new_state()
        for item in node.items:
            b_start = nfa.new_state()
            nfa.add_eps(start, b_start)
            b_end = _build(nfa, item, b_start)
            nfa.add_eps(b_end, end)
        return end
    if isinstance(node, Rep):
        cur = start
        for _ in range(node.min):
            cur = _build(nfa, node.node, cur)
        if node.max is None:
            # star/plus tail: loop state
            loop = nfa.new_state()
            nfa.add_eps(cur, loop)
            inner_start = nfa.new_state()
            nfa.add_eps(loop, inner_start)
            inner_end = _build(nfa, node.node, inner_start)
            nfa.add_eps(inner_end, loop)
            return loop
        else:
            # up to (max-min) optional copies
            ends = [cur]
            for _ in range(node.max - node.min):
                cur = _build(nfa, node.node, cur)
                ends.append(cur)
            end = nfa.new_state()
            for e in ends:
                nfa.add_eps(e, end)
            return end
    if isinstance(node, Anchor):
        end = nfa.new_state()
        if node.kind == "bol":
            nfa.add_eps(start, end, "prev", BOS_BIT | NL_BIT)
        elif node.kind == "bos":
            nfa.add_eps(start, end, "prev", BOS_BIT)
        elif node.kind == "eol":
            nfa.add_eps(start, end, "next", EOL_BIT | NL_BIT)
        elif node.kind == "eos":
            nfa.add_eps(start, end, "next", EOL_BIT)
        elif node.kind == "eos_nl":
            # \Z: end of string, or before a final newline
            nfa.add_eps(start, end, "next", EOL_BIT)
            mid = nfa.new_state()
            nfa.add_eps(start, mid, "next", NL_BIT)
            mid2 = nfa.new_state()
            nfa.add_byte(mid, NL_BIT, mid2)
            nfa.add_eps(mid2, end, "next", EOL_BIT)
        else:
            raise UnsupportedRegex(f"anchor {node.kind}")
        return end
    raise TypeError(f"unknown AST node {node!r}")


@dataclass(frozen=True)
class ShrinkStats:
    """What the compile-path reduction pass did to this DFA (the
    fbtpu-shrink audit trail GrepProgram/GrepTables/bench report).

    ``s_raw``/``c_raw`` are the subset-construction shape, ``s``/``c``
    the shipped table's. ``minimized`` False means the pass was
    explicitly disabled (``FBTPU_DFA_MIN=0`` / ``minimize=False`` — the
    bench differential and the property tests' unminimized oracle).
    ``approx_of``/``approx_depth`` are set only on approximate
    reductions (:func:`approx_reduce`): the exact machine's state count
    and the prefix depth the collapse kept."""

    s_raw: int
    c_raw: int
    s: int
    c: int
    minimized: bool
    approx_of: Optional[int] = None
    approx_depth: Optional[int] = None

    @property
    def states_eliminated(self) -> int:
        return max(self.s_raw - self.s, 0)

    @property
    def classes_eliminated(self) -> int:
        return max(self.c_raw - self.c, 0)


@dataclass
class DFA:
    """Compiled table-driven DFA (the kernel input).

    trans[S, C] int32, class_map[257] uint8 (index 256 = EOL class, used
    for padded positions), start id, ACC==1 absorbing accept, DEAD==0.
    """

    trans: np.ndarray
    class_map: np.ndarray
    start: int
    n_states: int
    n_classes: int
    pattern: str
    #: reduction audit trail (None only for hand-built tables — the
    #: grep-unminimized-dfa lint rule pins compile_dfa as the one
    #: constructor on the kernel path)
    shrink: Optional[ShrinkStats] = None

    @property
    def eol_class(self) -> int:
        return int(self.class_map[EOL])

    def match_bytes(self, data: bytes) -> bool:
        """CPU reference matcher (search semantics, like flb_regex_match)."""
        state = self.start
        trans = self.trans
        cmap = self.class_map
        for b in data:
            state = trans[state, cmap[b]]
            if state <= ACC:  # DEAD or ACC — both absorbing
                return state == ACC
        state = trans[state, cmap[EOL]]
        return state == ACC

    def match_batch_np(self, batch: np.ndarray, lengths: np.ndarray) -> np.ndarray:
        """Vectorized numpy matcher over [B, L] uint8 padded batch
        (test oracle for the device kernel)."""
        B, L = batch.shape
        cls = self.class_map[batch]  # [B, L]
        pad = np.arange(L)[None, :] >= lengths[:, None]
        cls[pad] = self.eol_class
        state = np.full((B,), self.start, dtype=np.int32)
        trans = self.trans
        for i in range(L):
            state = trans[state, cls[:, i]]
        state = trans[state, np.full((B,), self.eol_class)]
        # negative lengths mark invalid rows (missing field -1 / overflow
        # -2) which must never match — same guard as the device kernel
        return (state == ACC) & (lengths >= 0)


def compose_supersteps(trans: np.ndarray, k: int) -> np.ndarray:
    """Pre-compose a [S, C] table to k-byte super-steps: [S, C^k] with
    T_k[s, c1*C^(k-1) + ... + ck] = T[...T[T[s, c1], c2]..., ck].

    The single source of the super-step index order — both the device
    kernel (ops/grep.py GrepProgram) and the native C++ twin
    (native/__init__.py GrepTables) build their tables here, keeping the
    bit-exact contract between them in one place."""
    S, C = trans.shape
    out = trans
    for _ in range(k - 1):
        # out[s, w] = state after word w; extend by one byte:
        # new[s, w*C + c] = trans[out[s, w], c]
        out = trans[out.reshape(-1)].reshape(S, -1)
    return out


def _renumber(trans: np.ndarray, start: int,
              part: np.ndarray) -> Tuple[np.ndarray, int]:
    """Collapse a state partition to a fresh table, keeping the
    DEAD=0 / ACC=1 absorbing-id contract (first-seen order for the
    rest, so equal inputs renumber deterministically)."""
    S, C = trans.shape
    remap = np.full(int(part.max()) + 1, -1, dtype=np.int64)
    remap[part[DEAD]] = DEAD
    remap[part[ACC]] = ACC
    nxt = 2
    for b in part:
        if remap[b] < 0:
            remap[b] = nxt
            nxt += 1
    new_ids = remap[part]
    new_trans = np.zeros((nxt, C), dtype=np.int32)
    # one representative per block suffices (blocks are equivalence classes)
    seen = np.zeros(nxt, dtype=bool)
    for s in range(S):
        ns = new_ids[s]
        if not seen[ns]:
            seen[ns] = True
            new_trans[ns] = new_ids[trans[s]]
    return new_trans, int(new_ids[start])


def _moore_minimize(trans: np.ndarray, start: int) -> Tuple[np.ndarray, int]:
    """Moore partition refinement — the simple O(S²·C)-ish fixpoint.

    Kept as the independent minimality ORACLE the property tests check
    Hopcroft against (two implementations of the coarsest congruence
    must agree on the block count), and as the reducer approx_reduce's
    search loop calls where the collapsed machines are already tiny."""
    S, C = trans.shape
    # initial partition: accepting (ACC) vs rest
    part = np.zeros(S, dtype=np.int64)
    part[ACC] = 1
    n_blocks = 2
    while True:
        # signature: own block + successor blocks per class
        sig = np.empty((S, C + 1), dtype=np.int64)
        sig[:, 0] = part
        sig[:, 1:] = part[trans]
        _, new = np.unique(sig, axis=0, return_inverse=True)
        n_new = int(new.max()) + 1
        if n_new == n_blocks:  # refinement only splits: no growth = fixed point
            break
        part, n_blocks = new, n_new
    return _renumber(trans, start, part)


def _hopcroft_minimize(trans: np.ndarray, start: int
                       ) -> Tuple[np.ndarray, int]:
    """Hopcroft partition refinement over the [S, C] table.

    Subset construction leaves many equivalent states (every optional
    trailing group of a pattern forks the subsets), which (a) bloats
    the kernel tables S-fold — the parallel-in-time device kernel does
    S× work per position — and (b) hides the self-loop structure the
    native accel scan needs: a `[^ ]*` skeleton state only LOOKS like a
    self-loop after its clones are merged. Language is unchanged, so
    all verdict paths stay bit-identical.

    Classic smaller-half worklist (splitters are (block, class) pairs;
    a split enqueues the smaller fragment), with numpy doing the
    per-splitter preimage scan — O(C·S log S) splitter work instead of
    Moore's full-table fixpoint rounds, which is what keeps hot-reload
    recompiles of big parser DFAs (S≈1k) cheap.

    Keeps the DEAD=0 / ACC=1 contract: any state from which ACC is
    unreachable is never split from DEAD's block (both die on every
    suffix), so dead subtrees merge into DEAD; ACC (the only accepting
    state, absorbing) stays a singleton partition."""
    S, C = trans.shape
    block = np.zeros(S, dtype=np.int64)
    block[ACC] = 1
    members: Dict[int, np.ndarray] = {
        0: np.flatnonzero(block == 0),
        1: np.asarray([ACC], dtype=np.int64),
    }
    nb = 2
    # {ACC} is the smaller half of the initial split for every class
    work = deque((1, c) for c in range(C))
    in_work = {(1, c) for c in range(C)}
    while work:
        key = work.popleft()
        in_work.discard(key)
        a, c = key
        in_a = np.zeros(S, dtype=bool)
        in_a[members[a]] = True
        x = in_a[trans[:, c]]  # states whose c-step lands in block a
        for b in np.unique(block[x]):
            bm = members[int(b)]
            sel = x[bm]
            if sel.all() or not sel.any():
                continue
            b1, b2 = bm[sel], bm[~sel]
            if len(b1) <= len(b2):
                small, large = b1, b2
            else:
                small, large = b2, b1
            new_id = nb
            nb += 1
            block[small] = new_id
            members[int(b)] = large
            members[new_id] = small
            for cc in range(C):
                if (int(b), cc) in in_work:
                    # pending splitter stays valid for the shrunk block;
                    # the new fragment must also be processed
                    work.append((new_id, cc))
                    in_work.add((new_id, cc))
                elif (new_id, cc) not in in_work:
                    # smaller-half rule: either fragment refines the
                    # same, and new_id IS the smaller half by
                    # construction — the cheaper preimage scan
                    work.append((new_id, cc))
                    in_work.add((new_id, cc))
    return _renumber(trans, start, block)


def _prune_unreachable(trans: np.ndarray, start: int
                       ) -> Tuple[np.ndarray, int]:
    """Drop states unreachable from {start, DEAD, ACC} (dead-state
    pruning). Subset construction never emits them, but the approximate
    collapse does — a state whose every predecessor was redirected to
    ACC would otherwise survive minimization as its own block."""
    S, C = trans.shape
    reach = np.zeros(S, dtype=bool)
    reach[[DEAD, ACC, start]] = True
    frontier = np.asarray([start], dtype=np.int64)
    while frontier.size:
        nxt = np.unique(trans[frontier].reshape(-1))
        frontier = nxt[~reach[nxt]]
        reach[frontier] = True
    if reach.all():
        return trans, start
    remap = np.full(S, -1, dtype=np.int64)
    keep = np.flatnonzero(reach)
    remap[keep] = np.arange(len(keep))
    # DEAD/ACC sit at indices 0/1 of `keep` (reach pinned them), so the
    # id contract survives renumbering
    return remap[trans[keep]].astype(np.int32), int(remap[start])


def _remerge_classes(trans: np.ndarray, class_map: np.ndarray
                     ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Byte-class remerge after state minimization: classes whose
    transition COLUMNS became identical under the smaller state set
    collapse into one, and classes no byte/EOL maps to (the BOS column
    — consumed when the start state folded BOS in) drop entirely.
    Shrinks C, which compounds through every C^k super-step table (the
    stride budget is S × C^(k+1)).

    ``class_map`` is the 257-entry byte→class table; returns
    (trans[S, C'], class_map', C')."""
    used = np.unique(class_map)
    remap = np.full(trans.shape[1], -1, dtype=np.int64)
    col_ids: Dict[bytes, int] = {}
    rep_cols: List[int] = []
    for c in used:
        key = trans[:, c].tobytes()
        new_id = col_ids.setdefault(key, len(rep_cols))
        if new_id == len(rep_cols):
            rep_cols.append(int(c))
        remap[c] = new_id
    new_trans = np.ascontiguousarray(trans[:, rep_cols], dtype=np.int32)
    new_map = remap[class_map].astype(np.uint8)
    return new_trans, new_map, len(rep_cols)


def _shrink_tables(trans: np.ndarray, start: int, class_map: np.ndarray
                   ) -> Tuple[np.ndarray, int, np.ndarray, int]:
    """The full reduction pass: prune → Hopcroft → class remerge."""
    trans, start = _prune_unreachable(trans, start)
    trans, start = _hopcroft_minimize(trans, start)
    trans, class_map, n_classes = _remerge_classes(trans, class_map)
    return trans, start, class_map, n_classes


def minimize_enabled() -> bool:
    """The FBTPU_DFA_MIN kill switch (default on). Exists for the
    bench's minimization-on/off differential and for pinning the
    unminimized oracle in tests — production paths never set it."""
    return os.environ.get("FBTPU_DFA_MIN", "1").lower() not in (
        "0", "off", "false")


def approx_env_states(default: int = 64) -> Optional[int]:
    """Parse the ``FBTPU_DFA_APPROX`` opt-in: unset/``0``/``off`` →
    None (approximate mode stays off — the default), a bare truthy
    value (``1``/``on``) → the caller's default state target, an
    integer > 1 → that state target."""
    v = os.environ.get("FBTPU_DFA_APPROX", "").strip().lower()
    if v in ("", "0", "off", "false"):
        return None
    try:
        n = int(v)
        return n if n > 1 else default
    except ValueError:
        return default


def approx_reduce(dfa: DFA, max_states: int = 64) -> Optional[DFA]:
    """Over-approximate reduction (arXiv 1710.08647's self-loop/collapse
    shape): states deeper than a prefix depth d collapse into the
    absorbing ACC, then the collapsed machine is pruned, exact-minimized
    and class-remerged. Every transition is redirected *toward* accept
    and never away, so L(exact) ⊆ L(approx) — a False from the reduced
    machine is definitive, which is what makes it sound as a first-pass
    mask in front of an exact recheck (the filter_parser(regex)
    mask→recheck shape).

    Binary-searches the largest d whose reduced machine fits
    ``max_states`` (more prefix retained = fewer false admits). Returns
    None when the exact DFA already fits (approximation would only add
    false positives) or when even d=1 cannot fit the budget."""
    if dfa.n_states <= max_states:
        return None
    trans = dfa.trans
    S, C = trans.shape
    # BFS depth from start over the byte/EOL classes
    depth = np.full(S, np.iinfo(np.int64).max, dtype=np.int64)
    depth[dfa.start] = 0
    frontier = np.asarray([dfa.start], dtype=np.int64)
    d = 0
    while frontier.size:
        d += 1
        nxt = np.unique(trans[frontier].reshape(-1))
        frontier = nxt[depth[nxt] > d]
        depth[frontier] = d
    max_depth = int(depth[depth < np.iinfo(np.int64).max].max())

    def collapse(dcap: int):
        part = np.arange(S, dtype=np.int64)
        deep = depth > dcap
        deep[[DEAD, ACC]] = False  # DEAD→ACC would admit everything
        part[deep] = ACC
        t = part[trans].astype(np.int32)
        st = int(part[dfa.start])
        t, st = _prune_unreachable(t, st)
        t, st = _moore_minimize(t, st)  # collapsed machines are tiny
        t, cmap, n_cls = _remerge_classes(t, dfa.class_map)
        return t, st, cmap, n_cls

    lo, hi, best = 1, max_depth, None
    while lo <= hi:
        mid = (lo + hi) // 2
        t, st, cmap, n_cls = collapse(mid)
        if t.shape[0] <= max_states:
            best = (mid, t, st, cmap, n_cls)
            lo = mid + 1
        else:
            hi = mid - 1
    if best is None:
        return None
    dcap, t, st, cmap, n_cls = best
    base = dfa.shrink
    return DFA(
        trans=t,
        class_map=cmap,
        start=st,
        n_states=t.shape[0],
        n_classes=n_cls,
        pattern=dfa.pattern,
        shrink=ShrinkStats(
            s_raw=base.s_raw if base else dfa.n_states,
            c_raw=base.c_raw if base else dfa.n_classes,
            s=t.shape[0],
            c=n_cls,
            minimized=True,
            approx_of=dfa.n_states,
            approx_depth=dcap,
        ),
    )


def compile_dfa(pattern, ignorecase: bool = False, dot_all: bool = False,
                max_states: int = 4096,
                minimize: Optional[bool] = None) -> DFA:
    """Compile a pattern (str or ParsedRegex) to a scan DFA.

    Raises UnsupportedRegex for non-DFA-expressible constructs; callers
    fall back to the CPU engine (the same split the north star requires).

    Every DFA leaving here has passed the fbtpu-shrink reduction pass —
    unreachable-state pruning, Hopcroft minimization, byte-class
    remerging — unless ``minimize=False`` (or ``FBTPU_DFA_MIN=0``)
    explicitly pins the raw subset table for a differential (bench's
    on/off stage, the property tests' oracle). The language is
    unchanged either way; only table shape differs.
    """
    if isinstance(pattern, ParsedRegex):
        parsed = pattern
    else:
        parsed = parse(pattern, ignorecase=ignorecase, dot_all=dot_all)

    nfa = _NFA()
    pre = nfa.new_state()         # consumes the virtual BOS symbol
    scan = nfa.new_state()        # unanchored search loop
    nfa.add_byte(pre, BOS_BIT, scan)
    nfa.add_byte(scan, ALL_BYTES, scan)
    p_start = nfa.new_state()
    nfa.add_eps(scan, p_start)
    p_end = _build(nfa, parsed.root, p_start)
    accept = nfa.new_state()
    nfa.add_eps(p_end, accept)
    # absorbing accept: self-loop on every symbol incl. EOL/BOS
    nfa.add_byte(accept, ALL_SYMS, accept)

    n = len(nfa.byte_edges)

    # ---- symbol equivalence classes ----
    # refine {0..257} by every mask used anywhere (byte edges + constraints)
    masks = set()
    for st in range(n):
        for m, _ in nfa.byte_edges[st]:
            masks.add(m & ALL_SYMS)
        for kind, m, _ in nfa.eps_edges[st]:
            if kind is not None:
                masks.add(m & ALL_SYMS)
    masks.add(EOL_BIT)
    masks.add(BOS_BIT)
    sig_map: Dict[Tuple[bool, ...], int] = {}
    sym_class = np.zeros(258, dtype=np.int32)
    mask_list = sorted(masks)
    for sym in range(258):
        sig = tuple(bool(m >> sym & 1) for m in mask_list)
        cid = sig_map.setdefault(sig, len(sig_map))
        sym_class[sym] = cid
    n_classes = len(sig_map)
    # one representative symbol per class
    rep: List[int] = [0] * n_classes
    for sym in range(257, -1, -1):
        rep[sym_class[sym]] = sym

    # ---- closures ----
    def closure_plain(states: FrozenSet[int]) -> FrozenSet[int]:
        out = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for kind, m, dst in nfa.eps_edges[s]:
                if kind is None and dst not in out:
                    out.add(dst)
                    stack.append(dst)
        return frozenset(out)

    def closure_after(states: set, sym: int) -> FrozenSet[int]:
        """Cross plain eps + prev-constraint eps (prev symbol = sym)."""
        out = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for kind, m, dst in nfa.eps_edges[s]:
                if kind == "next":
                    continue
                if kind == "prev" and not (m >> sym & 1):
                    continue
                if dst not in out:
                    out.add(dst)
                    stack.append(dst)
        return frozenset(out)

    def pre_closure(states: FrozenSet[int], sym: int) -> set:
        """Cross plain eps + next-constraint eps (next symbol = sym)."""
        out = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for kind, m, dst in nfa.eps_edges[s]:
                if kind == "prev":
                    continue
                if kind == "next" and not (m >> sym & 1):
                    continue
                if dst not in out:
                    out.add(dst)
                    stack.append(dst)
        return out

    def move(states: FrozenSet[int], sym: int) -> FrozenSet[int]:
        src = pre_closure(states, sym)
        stepped = set()
        for s in src:
            for m, dst in nfa.byte_edges[s]:
                if m >> sym & 1:
                    stepped.add(dst)
        return closure_after(stepped, sym)

    # ---- subset construction ----
    init = closure_plain(frozenset([pre]))
    start_set = move(init, BOS)  # fold BOS into the start state

    def canon(states: FrozenSet[int]) -> object:
        if accept in states:
            return "ACC"
        if not states:
            return "DEAD"
        return states

    dfa_ids: Dict[object, int] = {"DEAD": DEAD, "ACC": ACC}
    table: List[List[int]] = [[DEAD] * n_classes, [ACC] * n_classes]
    worklist: List[FrozenSet[int]] = []

    def get_id(states: FrozenSet[int]) -> int:
        key = canon(states)
        if key in dfa_ids:
            return dfa_ids[key]
        sid = len(table)
        if sid > max_states:
            raise UnsupportedRegex(
                f"DFA exceeds {max_states} states for pattern {parsed.pattern!r}"
            )
        dfa_ids[key] = sid
        table.append([DEAD] * n_classes)
        worklist.append(states)
        return sid

    start_id = get_id(start_set)
    while worklist:
        states = worklist.pop()
        sid = dfa_ids[canon(states)]
        for cid in range(n_classes):
            sym = rep[cid]
            if sym == BOS:
                continue  # BOS never appears mid-stream
            table[sid][cid] = get_id(move(states, sym))

    trans = np.asarray(table, dtype=np.int32)
    class_map = sym_class[:257].astype(np.uint8)
    s_raw, c_raw = trans.shape[0], n_classes
    if minimize is None:
        minimize = minimize_enabled()
    if minimize:
        trans, start_id, class_map, n_classes = _shrink_tables(
            trans, start_id, class_map)
    return DFA(
        trans=trans,
        class_map=class_map,
        start=start_id,
        n_states=trans.shape[0],
        n_classes=n_classes,
        pattern=parsed.pattern,
        shrink=ShrinkStats(s_raw=s_raw, c_raw=c_raw, s=trans.shape[0],
                           c=n_classes, minimized=bool(minimize)),
    )
