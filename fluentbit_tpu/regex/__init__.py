"""Regex engine — Onigmo-equivalent matching for the TPU build.

Two execution tiers, same semantics (ONIG_SYNTAX_RUBY, UTF-8 bytes):

- ``compile_dfa`` → table-driven scan DFA for device execution
  (fluentbit_tpu.ops.grep) and fast CPU batch matching.
- ``FlbRegex`` → the user-facing wrapper (flb_regex_create/do/match
  equivalent, src/flb_regex.c): DFA when possible, Python ``re`` fallback
  (translated to Ruby semantics) for patterns with backrefs/lookaround,
  plus named-capture extraction for the parser path.
"""

from __future__ import annotations

import re as _pyre
from typing import Dict, Optional

from .parser import (ALL_BYTES, _POSIX_CLASSES, ParsedRegex,
                     UnsupportedRegex, parse)
from .dfa import DFA, compile_dfa

__all__ = ["FlbRegex", "DFA", "compile_dfa", "parse", "UnsupportedRegex",
           "ParsedRegex", "to_python_regex"]


def _class_content(mask: int) -> str:
    """Render a 256-bit byte mask as Python character-class content."""
    out = []
    b = 0
    while b < 256:
        if mask >> b & 1:
            start = b
            while b < 256 and mask >> b & 1:
                b += 1
            end = b - 1
            # a run reaching 0xFF means "any non-ASCII byte"; in decoded
            # text that is any astral/BMP char (incl. surrogateescape)
            hi = "\\U0010ffff" if end == 0xFF else "\\x%02x" % end
            if start == end:
                out.append("\\x%02x" % start)
            else:
                out.append("\\x%02x-%s" % (start, hi))
        else:
            b += 1
    return "".join(out)


def _posix_content(name: str) -> str:
    neg = name.startswith("^")
    mask = _POSIX_CLASSES.get(name[1:] if neg else name)
    if mask is None:
        raise UnsupportedRegex(f"unknown POSIX class [:{name}:]")
    return _class_content(ALL_BYTES & ~mask if neg else mask)


def to_python_regex(pattern: str) -> str:
    """Translate Ruby-syntax pattern to Python re syntax.

    - ``(?<name>`` → ``(?P<name>``   (keep lookbehind ``(?<=`` / ``(?<!``)
    - ``\\Z`` (Ruby: end-or-before-final-newline) → ``(?=\\n?\\Z)``
    - ``\\z`` → ``\\Z``
    - ``\\h``/``\\H`` (hex digit) → character classes
    - ``\\e`` (escape char, Ruby-only) → ``\\x1b``
    - POSIX classes ``[[:alpha:]]`` → expanded ranges
    """
    out = []
    i = 0
    n = len(pattern)
    in_class = False
    class_start = -1  # position just after '[' (or '[^')
    while i < n:
        c = pattern[i]
        if c == "\\" and i + 1 < n:
            nxt = pattern[i + 1]
            if in_class:
                # inside a class: \h expands to its ranges; anchors are
                # not special in classes
                if nxt == "h":
                    out.append("0-9a-fA-F")
                elif nxt == "H":
                    # non-hex-digit as explicit ranges (valid inside a class,
                    # unlike a nested [^...])
                    out.append("\\x00-\\x2f\\x3a-\\x40\\x47-\\x60\\x67-\\uffff")
                elif nxt == "e":
                    out.append("\\x1b")
                else:
                    out.append(c + nxt)
            elif nxt == "z":
                out.append(r"\Z")
            elif nxt == "Z":
                out.append(r"(?=\n?\Z)")
            elif nxt == "h":
                out.append("[0-9a-fA-F]")
            elif nxt == "H":
                out.append("[^0-9a-fA-F]")
            elif nxt == "e":
                out.append("\\x1b")
            else:
                out.append(c + nxt)
            i += 2
            continue
        if in_class:
            if c == "[" and pattern.startswith("[:", i):
                j = pattern.find(":]", i + 2)
                # a name spanning ']' means the '[:' was literal class
                # content, not a POSIX class (e.g. "[a[:b]")
                if j > 0 and "]" not in pattern[i + 2 : j]:
                    out.append(_posix_content(pattern[i + 2 : j]))
                    i = j + 2
                    continue
            if c == "]" and i > class_start:
                in_class = False
            out.append(c)
            i += 1
            continue
        if c == "[":
            in_class = True
            out.append(c)
            i += 1
            if i < n and pattern[i] == "^":
                out.append("^")
                i += 1
            class_start = i  # a ']' at this exact position is literal
            continue
        if pattern.startswith("(?<", i) and not (
            pattern.startswith("(?<=", i) or pattern.startswith("(?<!", i)
        ):
            out.append("(?P<")
            i += 3
            continue
        if pattern.startswith("(?'", i):
            j = pattern.index("'", i + 3)
            out.append("(?P<" + pattern[i + 3 : j] + ">")
            i = j + 1
            continue
        out.append(c)
        i += 1
    return "".join(out)


class FlbRegex:
    """flb_regex equivalent: compile once, match/parse many.

    Ruby ^/$ are line anchors → the Python fallback compiles with
    re.MULTILINE (exactly the ONIG_OPTION_NONE default of
    src/flb_regex.c:146).
    """

    def __init__(self, pattern: str, ignorecase: bool = False):
        self.pattern = pattern
        self.ignorecase = ignorecase
        self.dfa: Optional[DFA] = None
        self.parsed: Optional[ParsedRegex] = None
        try:
            self.parsed = parse(pattern, ignorecase=ignorecase)
            self.dfa = compile_dfa(self.parsed)
        except UnsupportedRegex:
            pass
        # the Python fallback is compiled lazily: a DFA-capable pattern may
        # use Ruby-valid constructs Python rejects, and must still work
        self._py_cached = None
        if self.dfa is None:
            self._py()  # no engine can run it → raise at construction

    def _py(self):
        if self._py_cached is None:
            flags = _pyre.MULTILINE
            if self.ignorecase:
                flags |= _pyre.IGNORECASE
            self._py_cached = _pyre.compile(to_python_regex(self.pattern), flags)
        return self._py_cached

    @property
    def dfa_capable(self) -> bool:
        return self.dfa is not None

    def match(self, text) -> bool:
        """Search semantics (flb_regex_match): True if found anywhere."""
        if isinstance(text, str):
            data = text.encode("utf-8")
        else:
            data = bytes(text)
        if self.dfa is not None:
            return self.dfa.match_bytes(data)
        return self._py().search(data.decode("utf-8", "surrogateescape")) is not None

    def search_captures(self, text):
        """Search returning the capture tuple ``($0, $1, ...)`` — group 0
        is the whole match (flb_ra_regex_match's flb_regex_search result,
        consumed by rewrite_tag tag templates). None when no match.

        Ruby capture numbering: when a pattern contains named groups,
        unnamed groups do not capture — $1.. are the named groups in
        order of appearance (ONIG_SYNTAX_RUBY behavior).
        """
        if isinstance(text, bytes):
            text = text.decode("utf-8", "surrogateescape")
        py = self._py()
        m = py.search(text)
        if m is None:
            return None
        if py.groupindex:
            ordered = sorted(py.groupindex.items(), key=lambda kv: kv[1])
            return (m.group(0),) + tuple(m.group(i) for _, i in ordered)
        return (m.group(0),) + m.groups()

    def parse_record(self, text) -> Optional[Dict[str, str]]:
        """Named-capture extraction (flb_regex_parse with callback per
        named group). Returns None when the pattern does not match."""
        if isinstance(text, bytes):
            text = text.decode("utf-8", "surrogateescape")
        m = self._py().search(text)
        if m is None:
            return None
        return {k: v for k, v in m.groupdict().items() if v is not None}
