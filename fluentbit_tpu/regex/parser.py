"""Regex parser — Onigmo/Ruby-syntax subset → AST.

The reference compiles patterns with ONIG_SYNTAX_RUBY + ONIG_ENCODING_UTF8
(src/flb_regex.c:143-146). Ruby semantics implemented here:

- ``^``/``$`` are LINE anchors (match at string start/end and after/before
  a newline), ``\\A``/``\\z``/``\\Z`` are string anchors.
- ``.`` matches any byte except ``\\n`` (multiline option makes it match all).
- char classes, ranges, negation, escapes (\\d \\w \\s \\h and negations),
  quantifiers ``* + ? {m} {m,} {m,n}`` with lazy/possessive variants
  (language-equivalent for boolean matching), groups ``(...)``,
  ``(?:...)``, named ``(?<name>...)``/``(?'name')``, alternation.

Matching is byte-level over UTF-8: multi-byte literals expand to byte
sequences; negated classes cover bytes 0x80-0xFF so ``[^ ]`` correctly
consumes each byte of multi-byte characters. Counted quantifiers over
``.`` count bytes, not characters, for non-ASCII input (documented
divergence; the DFA-ineligible checker flags patterns where it matters).

Unsupported constructs (backreferences, lookaround, recursion,
\\p{...} unicode properties) raise UnsupportedRegex — callers fall back
to a CPU regex engine, mirroring how the north star keeps a CPU fallback
path for non-vectorizable patterns.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union

ALL_BYTES = (1 << 256) - 1
NEWLINE_MASK = 1 << 10  # '\n'
DOT_MASK = ALL_BYTES & ~NEWLINE_MASK


class UnsupportedRegex(Exception):
    """Pattern uses a construct the DFA compiler cannot express."""


# -- AST --

@dataclass
class Lit:
    """One byte drawn from a 256-bit mask."""
    mask: int


@dataclass
class Seq:
    items: List["Node"]


@dataclass
class Alt:
    items: List["Node"]


@dataclass
class Rep:
    node: "Node"
    min: int
    max: Optional[int]  # None = unbounded
    lazy: bool = False


@dataclass
class Group:
    node: "Node"
    index: int  # 0 = non-capturing
    name: Optional[str] = None


@dataclass
class Anchor:
    # 'bol' ^, 'eol' $, 'bos' \A, 'eos' \z, 'eos_nl' \Z, 'wordb' \b (unsupported)
    kind: str


Node = Union[Lit, Seq, Alt, Rep, Group, Anchor]


def _mask_of(chars: str) -> int:
    m = 0
    for c in chars:
        m |= 1 << ord(c)
    return m


_D = _mask_of("0123456789")
_W = _D | _mask_of("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ_")
_S = _mask_of(" \t\n\r\f\v")
_H = _D | _mask_of("abcdefABCDEF")

_CLASS_ESCAPES = {
    "d": _D, "D": ALL_BYTES & ~_D,
    "w": _W, "W": ALL_BYTES & ~_W,
    "s": _S, "S": ALL_BYTES & ~_S,
    "h": _H, "H": ALL_BYTES & ~_H,
}

_CHAR_ESCAPES = {
    "t": 9, "n": 10, "r": 13, "f": 12, "v": 11, "a": 7, "e": 27, "0": 0,
}


def _range_mask(lo: int, hi: int) -> int:
    return ((1 << (hi + 1)) - 1) & ~((1 << lo) - 1)


#: POSIX bracket classes ``[:name:]`` (ASCII ranges — consistent with the
#: ASCII interpretation this engine uses for \\w/\\d/\\s; Onigmo syntax).
_POSIX_CLASSES = {
    "alnum": _D | _mask_of("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ"),
    "alpha": _mask_of("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ"),
    "ascii": _range_mask(0x00, 0x7F),
    "blank": _mask_of(" \t"),
    "cntrl": _range_mask(0x00, 0x1F) | (1 << 0x7F),
    "digit": _D,
    "graph": _range_mask(0x21, 0x7E),
    "lower": _mask_of("abcdefghijklmnopqrstuvwxyz"),
    "print": _range_mask(0x20, 0x7E),
    "punct": _range_mask(0x21, 0x2F) | _range_mask(0x3A, 0x40)
             | _range_mask(0x5B, 0x60) | _range_mask(0x7B, 0x7E),
    "space": _S,
    "upper": _mask_of("ABCDEFGHIJKLMNOPQRSTUVWXYZ"),
    "word": _W,
    "xdigit": _H,
}


class _Parser:
    def __init__(self, pattern: str, ignorecase: bool = False,
                 dot_all: bool = False):
        # operate on the UTF-8 byte encoding of the pattern so multi-byte
        # literals become byte sequences naturally
        self.pat = pattern
        self.pos = 0
        self.n = len(pattern)
        self.group_count = 0
        self.ignorecase = ignorecase
        self.dot_all = dot_all

    # -- cursor helpers --

    def peek(self) -> Optional[str]:
        return self.pat[self.pos] if self.pos < self.n else None

    def next(self) -> str:
        c = self.pat[self.pos]
        self.pos += 1
        return c

    def eat(self, c: str) -> bool:
        if self.peek() == c:
            self.pos += 1
            return True
        return False

    def error(self, msg: str) -> Exception:
        return ValueError(f"regex parse error at {self.pos}: {msg} in {self.pat!r}")

    # -- grammar --

    def parse(self) -> Node:
        node = self.parse_alt()
        if self.pos != self.n:
            raise self.error(f"unexpected {self.peek()!r}")
        return node

    def parse_alt(self) -> Node:
        branches = [self.parse_seq()]
        while self.eat("|"):
            branches.append(self.parse_seq())
        if len(branches) == 1:
            return branches[0]
        return Alt(branches)

    def parse_seq(self) -> Node:
        items: List[Node] = []
        while True:
            c = self.peek()
            if c is None or c in "|)":
                break
            items.append(self.parse_quant())
        if len(items) == 1:
            return items[0]
        return Seq(items)

    def parse_quant(self) -> Node:
        atom = self.parse_atom()
        while True:
            c = self.peek()
            if c == "*":
                self.next()
                atom = Rep(atom, 0, None, self._lazy())
            elif c == "+":
                self.next()
                atom = Rep(atom, 1, None, self._lazy())
            elif c == "?":
                self.next()
                atom = Rep(atom, 0, 1, self._lazy())
            elif c == "{":
                save = self.pos
                rep = self._try_braces(atom)
                if rep is None:
                    self.pos = save
                    break
                atom = rep
            else:
                break
        return atom

    def _lazy(self) -> bool:
        if self.peek() == "?":
            self.next()
            return True
        if self.peek() == "+":  # possessive — same language
            self.next()
        return False

    def _try_braces(self, atom: Node) -> Optional[Rep]:
        assert self.next() == "{"
        start = self.pos
        digits1 = ""
        while self.peek() and self.peek().isdigit():
            digits1 += self.next()
        lo: Optional[int] = int(digits1) if digits1 else None
        hi: Optional[int] = lo
        if self.eat(","):
            digits2 = ""
            while self.peek() and self.peek().isdigit():
                digits2 += self.next()
            hi = int(digits2) if digits2 else None
            if lo is None:
                lo = 0
        if not self.eat("}") or lo is None:
            return None  # literal '{'
        if hi is not None and (hi > 256 or lo > 256):
            raise UnsupportedRegex(f"counted repetition too large: {{{lo},{hi}}}")
        if hi is not None and hi < lo:
            raise self.error(f"bad repetition {{{lo},{hi}}}")
        return Rep(atom, lo, hi, self._lazy())

    def parse_atom(self) -> Node:
        c = self.next()
        if c == "(":
            return self.parse_group()
        if c == "[":
            return Lit(self._maybe_fold(self.parse_class()))
        if c == ".":
            return Lit(ALL_BYTES if self.dot_all else DOT_MASK)
        if c == "^":
            return Anchor("bol")
        if c == "$":
            return Anchor("eol")
        if c == "\\":
            return self.parse_escape()
        if c in "*+?":
            raise self.error(f"nothing to repeat {c!r}")
        return self._literal_char(c)

    def _literal_char(self, c: str) -> Node:
        data = c.encode("utf-8")
        if len(data) == 1:
            return Lit(self._maybe_fold(1 << data[0]))
        return Seq([Lit(1 << b) for b in data])

    def _maybe_fold(self, mask: int) -> int:
        if not self.ignorecase:
            return mask
        folded = mask
        for lo_c, up_c in zip(range(97, 123), range(65, 91)):
            if mask >> lo_c & 1:
                folded |= 1 << up_c
            if mask >> up_c & 1:
                folded |= 1 << lo_c
        return folded

    def parse_group(self) -> Node:
        name: Optional[str] = None
        capture = True
        if self.eat("?"):
            c = self.peek()
            if c == ":":
                self.next()
                capture = False
            elif c == "<":
                self.next()
                nxt = self.peek()
                if nxt in ("=", "!"):
                    raise UnsupportedRegex("lookbehind is not DFA-expressible")
                name = self._parse_name(">")
            elif c == "'":
                self.next()
                name = self._parse_name("'")
            elif c == "P":
                self.next()
                if not self.eat("<"):
                    raise self.error("expected (?P<name>")
                name = self._parse_name(">")
            elif c in ("=", "!"):
                raise UnsupportedRegex("lookahead is not DFA-expressible")
            elif c == "#":
                # comment group
                while self.peek() not in (None, ")"):
                    self.next()
                if not self.eat(")"):
                    raise self.error("unterminated comment group")
                return Seq([])
            else:
                raise UnsupportedRegex(f"unsupported group (?{c}")
        node = self.parse_alt()
        if not self.eat(")"):
            raise self.error("unterminated group")
        if capture:
            self.group_count += 1
            return Group(node, self.group_count, name)
        return Group(node, 0, None)

    def _parse_name(self, term: str) -> str:
        name = ""
        while self.peek() not in (None, term):
            name += self.next()
        if not self.eat(term):
            raise self.error("unterminated group name")
        return name

    def parse_escape(self) -> Node:
        c = self.peek()
        if c is None:
            raise self.error("trailing backslash")
        self.next()
        if c in _CLASS_ESCAPES:
            return Lit(_CLASS_ESCAPES[c])
        if c in _CHAR_ESCAPES:
            return Lit(1 << _CHAR_ESCAPES[c])
        if c == "x":
            return Lit(self._maybe_fold(1 << self._hex2()))
        if c == "A":
            return Anchor("bos")
        if c == "z":
            return Anchor("eos")
        if c == "Z":
            return Anchor("eos_nl")
        if c in ("b", "B"):
            raise UnsupportedRegex("word boundary \\b is not supported")
        if c in ("p", "P"):
            raise UnsupportedRegex("unicode property \\p{...} is not supported")
        if c == "G" or c == "K":
            raise UnsupportedRegex(f"\\{c} is not supported")
        if c.isdigit():
            raise UnsupportedRegex("backreferences are not DFA-expressible")
        if c == "k":
            raise UnsupportedRegex("named backreferences are not DFA-expressible")
        # escaped literal (punctuation, or any other char)
        return self._literal_char(c)

    def _hex2(self) -> int:
        h = ""
        while len(h) < 2 and self.peek() and self.peek() in "0123456789abcdefABCDEF":
            h += self.next()
        if not h:
            raise self.error("bad \\x escape")
        return int(h, 16)

    def parse_class(self) -> int:
        negate = self.eat("^")
        mask = 0
        first = True
        while True:
            c = self.peek()
            if c is None:
                raise self.error("unterminated character class")
            if c == "]" and not first:
                self.next()
                break
            first = False
            # POSIX bracket class [:name:] / [:^name:] (Onigmo syntax)
            if c == "[" and self.pos + 1 < self.n and self.pat[self.pos + 1] == ":":
                mask |= self._parse_posix_class()
                continue
            self.next()
            if c == "\\":
                e = self.next()
                if e in _CLASS_ESCAPES:
                    mask |= _CLASS_ESCAPES[e]
                    continue
                if e in _CHAR_ESCAPES:
                    lo_b = _CHAR_ESCAPES[e]
                elif e == "x":
                    lo_b = self._hex2()
                elif e in ("p", "P"):
                    raise UnsupportedRegex("\\p in class is not supported")
                else:
                    data = e.encode("utf-8")
                    if len(data) > 1:
                        raise UnsupportedRegex("non-ASCII literal in character class")
                    lo_b = data[0]
            else:
                data = c.encode("utf-8")
                if len(data) > 1:
                    raise UnsupportedRegex("non-ASCII literal in character class")
                lo_b = data[0]
            # range?
            if self.peek() == "-" and self.pos + 1 < self.n and self.pat[self.pos + 1] != "]":
                self.next()  # '-'
                hc = self.next()
                if hc == "\\":
                    he = self.next()
                    if he in _CHAR_ESCAPES:
                        hi_b = _CHAR_ESCAPES[he]
                    elif he == "x":
                        hi_b = self._hex2()
                    else:
                        data = he.encode("utf-8")
                        if len(data) > 1:
                            raise UnsupportedRegex("non-ASCII range bound")
                        hi_b = data[0]
                else:
                    data = hc.encode("utf-8")
                    if len(data) > 1:
                        raise UnsupportedRegex("non-ASCII range bound")
                    hi_b = data[0]
                if hi_b < lo_b:
                    raise self.error(f"bad range {lo_b}-{hi_b}")
                for b in range(lo_b, hi_b + 1):
                    mask |= 1 << b
            else:
                mask |= 1 << lo_b
        if negate:
            mask = ALL_BYTES & ~mask
        return mask

    def _parse_posix_class(self) -> int:
        """``[:name:]`` / ``[:^name:]`` inside a class; cursor at ``[``."""
        save = self.pos
        self.next()  # '['
        self.next()  # ':'
        neg = self.eat("^")
        name = ""
        while self.peek() is not None and self.peek() not in (":", "]"):
            name += self.next()
        if self.peek() == ":" and self.pos + 1 < self.n and self.pat[self.pos + 1] == "]":
            self.next()
            self.next()
            m = _POSIX_CLASSES.get(name)
            if m is None:
                raise UnsupportedRegex(f"unknown POSIX class [:{name}:]")
            return ALL_BYTES & ~m if neg else m
        # not actually a POSIX class (e.g. "[a[:b]"): rewind, treat '[' literal
        self.pos = save
        self.next()
        return 1 << ord("[")


@dataclass
class ParsedRegex:
    root: Node
    n_groups: int
    group_names: dict  # index -> name
    pattern: str


def parse(pattern: str, ignorecase: bool = False, dot_all: bool = False) -> ParsedRegex:
    p = _Parser(pattern, ignorecase=ignorecase, dot_all=dot_all)
    root = p.parse()
    names: dict = {}

    def walk(n: Node) -> None:
        if isinstance(n, Group):
            if n.name and n.index:
                names[n.index] = n.name
            walk(n.node)
        elif isinstance(n, (Seq, Alt)):
            for it in n.items:
                walk(it)
        elif isinstance(n, Rep):
            walk(n.node)

    walk(root)
    return ParsedRegex(root, p.group_count, names, pattern)
