"""Numpy/JAX dtype-narrowing rule.

``dtype-narrowing``: int64→int32 (or narrower) conversions applied to
byte-offset / position / cumulative-sum math on data-path modules.
Chunk byte offsets, span starts, and prefix sums over record lengths
are the quantities that actually cross 2 GiB in a production pipeline;
``.astype(np.int32)`` on them truncates SILENTLY (numpy wraps, no
error) and the verdict/index math downstream then gathers the wrong
spans — the worst kind of exactness bug because small test corpora
never trip it.

Flagged:

- ``<expr>.astype(int32-ish)`` / ``np.array(<expr>, dtype=int32-ish)``
  / ``np.asarray(<expr>, dtype=int32-ish)`` where ``<expr>`` references
  offset-flavored names (``offset``/``offsets``/``pos``/``position``/
  ``span``/``spans``/``cursor``);
- ``np.cumsum(..., dtype=int32-ish)`` / ``<expr>.cumsum(dtype=...)``
  unconditionally — a cumulative sum with a narrowed accumulator is
  offset math by construction.

Bounded quantities (verdict masks, per-record lengths capped by
``tpu_max_record_len``, DFA state ids) stay legal: the rule keys off
the *names* feeding the conversion, not the dtype alone. Suppress a
deliberate narrow with ``# fbtpu-lint: allow(dtype-narrowing)`` and a
justification (e.g. a bounded domain proof).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from . import Finding, Module, Rule
from .silent import DATA_PATH_PREFIXES

__all__ = ["DtypeNarrowingRule"]

#: dtypes narrower than the int64 the offset math is computed in
_NARROW = {"int32", "uint32", "int16", "uint16", "int8", "uint8"}

#: name fragments that mark a value as byte-offset / position math
_OFFSETY = ("offset", "position", "span", "cursor")
_OFFSETY_EXACT = {"pos", "off", "offs", "starts", "ends"}


def _dtype_name(node: ast.AST) -> Optional[str]:
    """``np.int32`` / ``jnp.uint16`` / ``"int32"`` → the dtype name."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _names(expr: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.Attribute):
            out.add(node.attr)
    return out


def _offsety(names: Set[str]) -> Optional[str]:
    for n in names:
        low = n.lower()
        if low in _OFFSETY_EXACT or any(f in low for f in _OFFSETY):
            return n
    return None


def _narrow_dtype_arg(call: ast.Call) -> Optional[str]:
    """The narrow dtype a call requests, via keyword or sole arg."""
    for kw in call.keywords:
        if kw.arg == "dtype":
            d = _dtype_name(kw.value)
            if d in _NARROW:
                return d
    return None


class DtypeNarrowingRule(Rule):
    name = "dtype-narrowing"
    description = ("int64→int32 truncation in offset/index math "
                   "(astype/array/cumsum with a narrow dtype on "
                   "offset-flavored values)")
    severity = "warning"

    def check(self, module: Module) -> List[Finding]:
        if not any(p in module.path for p in DATA_PATH_PREFIXES):
            return []
        out: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            t = None
            if isinstance(node.func, ast.Attribute):
                t = node.func.attr
            elif isinstance(node.func, ast.Name):
                t = node.func.id
            f = None
            if t == "astype" and isinstance(node.func, ast.Attribute):
                d = None
                if node.args:
                    d = _dtype_name(node.args[0])
                d = d if d in _NARROW else _narrow_dtype_arg(node)
                if d is not None:
                    src = _offsety(_names(node.func.value))
                    if src is not None:
                        f = self.finding(
                            module, node,
                            f"`.astype({d})` on offset-flavored value "
                            f"`{src}`: byte offsets cross int32 past "
                            f"2 GiB and numpy truncates silently — "
                            f"keep offset math in int64")
            elif t in ("array", "asarray"):
                d = _narrow_dtype_arg(node)
                if d is not None and node.args:
                    src = _offsety(_names(node.args[0]))
                    if src is not None:
                        f = self.finding(
                            module, node,
                            f"`{t}(..., dtype={d})` on offset-flavored "
                            f"value `{src}` truncates silently past "
                            f"2 GiB — keep offset math in int64")
            elif t == "cumsum":
                d = _narrow_dtype_arg(node)
                if d is not None:
                    f = self.finding(
                        module, node,
                        f"`cumsum(dtype={d})`: a prefix sum with a "
                        f"narrowed accumulator is offset math by "
                        f"construction and wraps silently past 2 GiB — "
                        f"accumulate in int64")
            if f is not None:
                out.append(f)
        out.sort(key=lambda x: (x.line, x.col))
        return out
