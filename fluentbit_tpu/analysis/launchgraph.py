"""fbtpu-xray: the interprocedural device launch-graph and PCIe
transfer-budget analyzer.

The measured wall is launches-per-PCIe-crossing (ROADMAP item 1): every
filter stage is its own jit/pjit launch with its own staging, and the
verdict comes home as a mask the host scatters. Nothing in the tree
could *see* or *gate* that cost — this module makes it reviewable. It
walks, from each ``FilterPlugin.process_batch`` / ``filter_raw`` and
the flux absorb entry, the call closure down to every
``DeviceLane.run``/``begin``/dispatch/jit/pjit/shard_map site (the
tail-call + self-method inlining of ``analysis/batch.py`` plus the
name-closure of ``devlane.py``) and emits a per-tag **device launch
graph**: launches per staged segment, host→device and device→host byte
crossings sized symbolically from the ``[R, B, L]`` staging shapes, the
static donate/alias set cross-checked against
``ops.mesh.aliasable_donations``, host scatter passes, and
replicated-table bytes.

The model the walker implements (kept honest by the tier-1 parity test
against the ``device.dispatch`` failpoint / lane launch counters on the
simulated 8-device mesh):

- one ``lane.run(launch, fallback)`` / ``lane.begin(launch, fallback)``
  is ONE watched launch; dispatch calls inside the closure defs handed
  to the lane are absorbed into it (the worker forces there — that is
  the sanctioned sync point, not a hazard);
- a bare dispatch call (``dispatch_mesh``/``sharded_*``/``device_*`` or
  ``.dispatch``/``.match`` on a ``*program*``/``*prefilter*`` chain) is
  one unguarded launch;
- ``kernels.guarded_segment_counts`` wraps its own lane launch
  (cross-module knowledge, one name);
- the callback handed to ``core.chunk_batch.double_buffered`` runs once
  per staged segment, so its launches ARE the chain's
  launches-per-segment; loops over groups count their body once and the
  sites carry ``in_loop`` (×G multiplicity is data-dependent);
- branches contribute the maximum over alternatives, and a branch that
  returns does not chain into the statements after the ``if``.

On top of the graph, five rules (suppress with
``# fbtpu-lint: allow(<rule>)`` + justification; shipped debt is
baselined in ``analysis/launch_budget.json`` under the PR-3
``(path, rule, message)`` key scheme):

- ``device-multi-launch-chain`` — an entry's chain reaches more than
  one device launch per staged segment (the fusion target is one).
- ``device-undonated-buffer`` — a staged buffer enters a pjit launch
  outside the donate set: ``donate="off"``/``False`` at a mesh dispatch
  site (error), or the structural ``[R, B, L]`` u8 batch gap — no
  aliasable u8 output exists, so the byte matrix crosses PCIe
  un-donated every segment (warning; PR-8's known gap, gated by the
  budget file until a same-aval survivor-bytes output lands).
- ``device-host-roundtrip`` — a chain that launches on device AND
  re-walks host bytes with the verdict (``native.compact`` scatter):
  the mask came home just to re-index the chunk.
- ``device-sync-in-staging-loop`` — ``np.asarray``/
  ``block_until_ready``/``device_get`` forcing a dispatch result inside
  the double-buffered dispatch callback, the stage generator, or a
  ``segment_bounds`` loop — defeats the staging overlap. Forcing inside
  the lane closure (worker-side) or the ``collect`` callback (one
  segment behind) is the sanctioned pattern and does not fire.
- ``stage-redundant-copy`` — ``.copy()`` on arrays staged by the
  arena-returning ``native.stage_field`` where the caller-buffer
  ``native.stage_field_into`` applies (stage straight into the
  transfer matrix; the mesh path already does).
"""

from __future__ import annotations

import ast
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from . import Finding, Module, Rule
from .registry import BUDGET_PARAMS, LAUNCH_ENTRIES

__all__ = [
    "LaunchGraphRules", "build_launch_graph", "graph_to_dot",
    "budget_snapshot", "compare_budget", "donation_crosscheck",
    "table_bytes", "EXAMPLE_TABLES",
]

#: Engine-facing device planes (same boundary as devlane/qos: ops/ is
#: the kernel layer the lanes wrap, not a chain entry).
SCOPES = ("fluentbit_tpu/plugins/", "fluentbit_tpu/flux/")

#: One DeviceLane.run/begin == one watched launch (``begin`` bumps the
#: lane's ``launches`` stat; the ``device.dispatch`` failpoint fires on
#: the worker — the counters the parity test reads).
LANE_LAUNCH = frozenset({"run", "begin"})

#: Helpers that wrap their own lane launch (flux/kernels.py).
GUARDED_LAUNCH_FNS = frozenset({"guarded_segment_counts"})

#: Raw jit/pjit/shard_map dispatch terminals, by launch kind.
KIND_BY_NAME = {
    "dispatch_mesh": "grep-mesh", "match_mesh": "grep-mesh",
    "match_sharded": "grep-mesh",
    "sharded_segment_counts": "flux-segment-counts",
    "guarded_segment_counts": "flux-segment-counts",
    "sharded_hll_registers": "flux-hll", "sharded_hll_update": "flux-hll",
    "device_registers": "flux-hll",
    "sharded_cms_table": "flux-cms", "sharded_cms_update": "flux-cms",
    "device_table": "flux-cms",
    "sharded_fused_absorb": "flux-fused", "fused_absorb": "flux-fused",
}
DISPATCH_NAMES = frozenset(KIND_BY_NAME) - GUARDED_LAUNCH_FNS

#: ``.dispatch(``/``.match(`` count as a launch only on a chain whose
#: names mention a compiled program (``self._program.dispatch``,
#: ``self._prefilter.match``).
PROGRAM_ATTRS = frozenset({"dispatch", "match"})
PROGRAM_RECV = ("program", "prefilter")

MESH_DISPATCH_SITES = frozenset({"dispatch_mesh", "match_mesh"})

#: Host-side force points (the sync rule's terminals).
SYNC_NAMES = frozenset({"asarray", "block_until_ready", "device_get"})

#: Host scatter: the verdict re-indexes the chunk bytes.
SCATTER_NAMES = frozenset({"compact"})

#: Arena-view stager (the redundant-copy rule's taint source) and its
#: caller-buffer replacement.
ARENA_STAGER = "stage_field"

SEGMENT_ITERS = frozenset({"segment_bounds"})
PIPELINE_FN = "double_buffered"

_SEVERITY = {
    "device-multi-launch-chain": "warning",
    "device-undonated-buffer": "warning",
    "device-host-roundtrip": "warning",
    "device-sync-in-staging-loop": "error",
    "stage-redundant-copy": "error",
}

#: Per-launch-kind transfer shapes (bytes, symbolic in the canonical
#: parameter names of ``registry.BUDGET_PARAMS``): the ``[R, B, L]``
#: staging algebra of ops/grep (mesh: mask i32 aliases the donated
#: lengths buffer — ``ops.mesh.aliasable_donations`` is the
#: cross-check; the u8 batch never has an aliasable output) and the
#: flux sketch planes (registers/tables ride along per launch until the
#: fusion PR keeps them device-resident across segments).
TRANSFER_SHAPES: Dict[str, Dict[str, List[Tuple[str, str, str, bool]]]] = {
    "grep-mesh": {
        "h2d": [("batch", "R*Bp*L", "uint8", False),
                ("lengths", "4*R*Bp", "int32", True)],
        "d2h": [("mask", "4*R*Bp", "int32", False)],
    },
    "grep-jit": {
        "h2d": [("batch", "R*Bp*L", "uint8", False),
                ("lengths", "4*R*Bp", "int32", False)],
        "d2h": [("mask", "R*Bp", "bool", False)],
    },
    "flux-segment-counts": {
        "h2d": [("seg", "8*B", "int64", False),
                ("ones", "4*B", "int32", False)],
        "d2h": [("counts", "4*G", "int32", False)],
    },
    "flux-hll": {
        "h2d": [("batch", "B*L", "uint8", False),
                ("lengths", "4*B", "int32", False),
                ("registers", "M_hll", "uint8", False)],
        "d2h": [("registers", "M_hll", "uint8", False)],
    },
    "flux-cms": {
        "h2d": [("batch", "B*L", "uint8", False),
                ("lengths", "4*B", "int32", False),
                ("table", "8*M_cms", "int64", False)],
        "d2h": [("table", "8*M_cms", "int64", False)],
    },
    # the ONE-launch fused flux absorb (counts + HLL stack + count-min
    # — the cashed fbtpu-fuseplan merge): everything the three unfused
    # programs staged, once, with the freshly-stacked [Gp, m] register
    # snapshot the only donated input (it aliases its output exactly;
    # the table snapshot must survive for the host-twin fallback)
    "flux-fused": {
        "h2d": [("seg", "4*Bp", "int32", False),
                ("valid", "4*Bp", "int32", False),
                ("batch", "Bp*L", "uint8", False),
                ("lengths", "4*Bp", "int32", False),
                ("registers", "Gp*M_hll", "uint8", True),
                ("comp", "Bp*L", "uint8", False),
                ("comp_len", "4*Bp", "int32", False),
                ("table", "8*M_cms", "int64", False)],
        "d2h": [("counts", "4*Gp", "int32", False),
                ("registers", "Gp*M_hll", "uint8", False),
                ("table", "8*M_cms", "int64", False)],
    },
}

#: Worked-example DFA matrices for the table-bytes accounting (the
#: rewrite_tag / log_to_metrics satellites share filter_grep's rule
#: machinery, so their native GrepTables footprint is the same
#: ``S × C`` i32 algebra — sized here post-shrink, the only honest
#: number after the PR-10 reducer).
APACHE2 = (
    r'^(?<host>[^ ]*) [^ ]* (?<user>[^ ]*) \[(?<time>[^\]]*)\] '
    r'"(?<method>\S+)(?: +(?<path>[^ ]*) +\S*)?" '
    r'(?<code>[^ ]*) (?<size>[^ ]*)'
    r'(?: "(?<referer>[^\"]*)" "(?<agent>.*)")?$'
)
EXAMPLE_TABLES = {
    "filter_grep[apache2]": (APACHE2,),
    "filter_rewrite_tag[apache2]": (APACHE2,),
    "filter_log_to_metrics[5xx]": (r"50[0-9]",),
}


def _terminal(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _chain_names(node) -> Set[str]:
    out: Set[str] = set()
    while True:
        if isinstance(node, ast.Attribute):
            out.add(node.attr)
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        else:
            break
    if isinstance(node, ast.Name):
        out.add(node.id)
    return out


def _is_program_call(call: ast.Call) -> bool:
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr in PROGRAM_ATTRS):
        return False
    chain = " ".join(_chain_names(f.value)).lower()
    return any(frag in chain for frag in PROGRAM_RECV)


def _is_lane_call(call: ast.Call) -> bool:
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr in LANE_LAUNCH):
        return False
    chain = " ".join(_chain_names(f.value)).lower()
    return "lane" in chain


def _walk_no_nested(node: ast.AST) -> Iterable[ast.AST]:
    """ast.walk that does not descend into nested function/class defs
    or lambdas (their bodies run later, under their own context)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)


def _local_defs(fn: ast.AST) -> Dict[str, List[ast.FunctionDef]]:
    """Function-local nested defs by name, wherever they sit in the
    body (branch-local ``def launch(...)`` variants included — grep's
    dispatch callback defines one per mesh arm), without descending
    into the nested defs themselves."""
    out: Dict[str, List[ast.FunctionDef]] = {}
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(n.name, []).append(n)
            continue
        if isinstance(n, (ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))
    return out


def _contains_dispatch(node: ast.AST) -> bool:
    """Any device-dispatch-ish call in the subtree (nested defs
    included — classifying a launch closure wants the full body)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            t = _terminal(sub.func)
            if t in KIND_BY_NAME or _is_program_call(sub) \
                    or _is_lane_call(sub):
                return True
    return False


def _closure_kind(defs: List[ast.AST]) -> Tuple[str, bool]:
    """Classify a lane launch by the dispatch terminals inside its
    closure defs → (kind, lane_guarded)."""
    kinds: List[str] = []
    for d in defs:
        for sub in ast.walk(d):
            if isinstance(sub, ast.Call):
                t = _terminal(sub.func)
                if t in KIND_BY_NAME:
                    kinds.append(KIND_BY_NAME[t])
                elif _is_program_call(sub):
                    kinds.append("grep-jit")
    # mesh beats the unsharded fallback branch inside the same closure;
    # the fused absorb beats its constituent kinds (a closure that
    # dispatches the fused program IS one fused launch)
    for pref in ("flux-fused", "grep-mesh", "flux-segment-counts",
                 "flux-hll", "flux-cms", "grep-jit"):
        if pref in kinds:
            return pref, True
    return "device", True


class _Site:
    __slots__ = ("line", "col", "kind", "what", "lane", "in_loop")

    def __init__(self, line, col, kind, what, lane, in_loop):
        self.line, self.col = line, col
        self.kind, self.what = kind, what
        self.lane, self.in_loop = lane, in_loop

    def as_dict(self) -> Dict[str, Any]:
        return {"line": self.line, "kind": self.kind, "what": self.what,
                "lane": self.lane, "in_loop": self.in_loop}


class _Ctx:
    """Walk context: loop nesting, per-segment staging scope, the
    lexical scope chain of nested defs, inline depth, plus the names
    bound from segment_bounds / dispatch calls in the current function
    (the segment-loop and pending-device-value taint sets)."""

    __slots__ = ("in_loop", "per_segment", "scopes", "depth",
                 "seg_names", "pending")

    def __init__(self, in_loop=False, per_segment=False, scopes=None,
                 depth=0):
        self.in_loop = in_loop
        self.per_segment = per_segment
        self.scopes = scopes if scopes is not None else []
        self.depth = depth
        self.seg_names: Set[str] = set()
        self.pending: Set[str] = set()

    def child(self, **kw) -> "_Ctx":
        c = _Ctx(self.in_loop, self.per_segment, list(self.scopes),
                 self.depth)
        c.seg_names = set(self.seg_names)
        c.pending = set(self.pending)
        for k, v in kw.items():
            setattr(c, k, v)
        return c

    def lookup(self, name: str) -> List[ast.FunctionDef]:
        for scope in reversed(self.scopes):
            if name in scope:
                return scope[name]
        return []


class _EntryWalk:
    """One entry's closure walk: max-path launch count + site/scatter/
    sync collection. Methods of the owning class and module-level
    functions inline by name (cycle-guarded, depth-capped like
    analysis/batch.py)."""

    def __init__(self, module: Module, methods: Dict[str, ast.FunctionDef],
                 functions: Dict[str, ast.FunctionDef]):
        self.module = module
        self.methods = methods
        self.functions = functions
        self.sites: Dict[Tuple[int, int], _Site] = {}
        self.scatters: Dict[Tuple[int, int], ast.Call] = {}
        self.sync_hits: Dict[Tuple[int, int], Tuple[ast.Call, str]] = {}
        self.staged = False
        self._inlining: Set[str] = set()

    # -- entry ---------------------------------------------------------

    def run(self, fn: ast.FunctionDef) -> int:
        count, _term = self._fn_body(fn, _Ctx())
        return count

    def _fn_body(self, fn: ast.FunctionDef, ctx: _Ctx) -> Tuple[int, bool]:
        scope = _local_defs(fn)
        # names bound from segment_bounds(...): loops over them are the
        # staged segment loop (filter_grep: bounds = segment_bounds(..))
        seg_names = set()
        pending_names = set()
        for sub in _walk_no_nested(fn):
            if isinstance(sub, ast.Assign) \
                    and isinstance(sub.value, ast.Call):
                t = _terminal(sub.value.func)
                names = {tgt.id for tgt in sub.targets
                         if isinstance(tgt, ast.Name)}
                if t in SEGMENT_ITERS:
                    seg_names |= names
                if t is not None and (t in KIND_BY_NAME
                                      or _is_lane_call(sub.value)
                                      or _is_program_call(sub.value)):
                    pending_names |= names
        sub_ctx = ctx.child(scopes=ctx.scopes + [scope])
        sub_ctx.seg_names = seg_names
        sub_ctx.pending = pending_names
        return self._stmts(fn.body, sub_ctx)

    # -- statements (right-to-left suffix counting: a branch that
    #    returns does not chain into the statements after the if) ------

    def _stmts(self, stmts: List[ast.stmt], ctx: _Ctx) -> Tuple[int, bool]:
        suffix = 0
        terminated = False
        for stmt in reversed(stmts):
            if isinstance(stmt, (ast.Return, ast.Raise)):
                val = stmt.value if isinstance(stmt, ast.Return) \
                    else getattr(stmt, "exc", None)
                suffix = self._expr(val, ctx) if val is not None else 0
                terminated = True
            elif isinstance(stmt, ast.If):
                t = self._expr(stmt.test, ctx)
                b, bt = self._stmts(stmt.body, ctx)
                e, et = self._stmts(stmt.orelse, ctx)
                through_b = b if bt else b + suffix
                through_e = e if et else e + suffix
                suffix = t + max(through_b, through_e)
                # both branches returning/raising → nothing after this
                # if runs; otherwise the block's fall-through status is
                # whatever the trailing statements already decided
                terminated = terminated or (bt and et)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                it = self._expr(stmt.iter, ctx)
                seg_loop = self._is_segment_loop(stmt, ctx)
                body_ctx = ctx.child(
                    in_loop=True,
                    per_segment=ctx.per_segment or seg_loop)
                b, _ = self._stmts(stmt.body, body_ctx)
                o, _ = self._stmts(stmt.orelse, ctx)
                suffix += it + b + o
            elif isinstance(stmt, ast.While):
                t = self._expr(stmt.test, ctx)
                body_ctx = ctx.child(in_loop=True)
                b, _ = self._stmts(stmt.body, body_ctx)
                suffix += t + b
            elif isinstance(stmt, ast.Try):
                b, bt = self._stmts(stmt.body, ctx)
                h = 0
                for handler in stmt.handlers:
                    hc, _ = self._stmts(handler.body, ctx)
                    h = max(h, hc)
                o, _ = self._stmts(stmt.orelse, ctx)
                f, _ = self._stmts(stmt.finalbody, ctx)
                suffix += b + h + o + f
                del bt  # handlers may continue: no termination claim
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                w = sum(self._expr(i.context_expr, ctx)
                        for i in stmt.items)
                b, bt = self._stmts(stmt.body, ctx)
                suffix += w + b
                terminated = terminated or bt
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                continue  # runs later, under its own call context
            else:
                suffix += self._expr(stmt, ctx)
        return suffix, terminated

    def _is_segment_loop(self, loop: ast.For, ctx: _Ctx) -> bool:
        seg_names = ctx.seg_names
        for sub in ast.walk(loop.iter):
            if isinstance(sub, ast.Call) \
                    and _terminal(sub.func) in SEGMENT_ITERS:
                return True
            if isinstance(sub, ast.Name) and sub.id in seg_names:
                return True
        return False

    # -- expressions ---------------------------------------------------

    def _expr(self, node: Optional[ast.AST], ctx: _Ctx) -> int:
        if node is None:
            return 0
        count = 0
        for sub in _walk_no_nested(node):
            if isinstance(sub, ast.Call):
                count += self._call(sub, ctx)
        return count

    def _call(self, call: ast.Call, ctx: _Ctx) -> int:
        t = _terminal(call.func)
        # lane guard: ONE watched launch; closure defs are absorbed
        if _is_lane_call(call):
            defs = self._closure_defs(call, ctx)
            kind, _ = _closure_kind(defs)
            self._site(call, kind, f"lane.{t}", lane=True, ctx=ctx)
            return 1
        if t in GUARDED_LAUNCH_FNS:
            self._site(call, KIND_BY_NAME[t], t, lane=True, ctx=ctx)
            return 1
        if t in DISPATCH_NAMES:
            self._site(call, KIND_BY_NAME[t], t, lane=False, ctx=ctx)
            return 1
        if _is_program_call(call):
            self._site(call, "grep-jit", f"<program>.{t}", lane=False,
                       ctx=ctx)
            return 1
        if t in SYNC_NAMES:
            self._sync(call, t, ctx)
            return sum(self._expr(a, ctx) for a in call.args)
        if t in SCATTER_NAMES:
            self.scatters[(call.lineno, call.col_offset)] = call
            return sum(self._expr(a, ctx) for a in call.args)
        if t == PIPELINE_FN:
            return self._pipeline(call, ctx)
        # interprocedural edges: self.<m>() / same-module fn / a nested
        # def invoked by name (the stages() generator pattern)
        target = self._callee(call, ctx)
        if target is not None:
            inlined = self._inline(target, ctx)
            return inlined + sum(self._expr(a, ctx) for a in call.args)
        return 0

    def _callee(self, call: ast.Call,
                ctx: _Ctx) -> Optional[ast.FunctionDef]:
        f = call.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == "self":
            return self.methods.get(f.attr)
        if isinstance(f, ast.Name):
            local = ctx.lookup(f.id)
            if local:
                return local[0]  # nested def called in place
            return self.functions.get(f.id)
        return None

    def _inline(self, fn: ast.FunctionDef, ctx: _Ctx,
                per_segment: Optional[bool] = None) -> int:
        if ctx.depth >= 6 or fn.name in self._inlining:
            return 0
        self._inlining.add(fn.name)
        try:
            sub = ctx.child(depth=ctx.depth + 1)
            if per_segment is not None:
                sub.per_segment = per_segment
            count, _ = self._fn_body(fn, sub)
            return count
        finally:
            self._inlining.discard(fn.name)

    def _pipeline(self, call: ast.Call, ctx: _Ctx) -> int:
        """double_buffered(stage_iter, dispatch, collect): the dispatch
        callback runs once per staged segment — its launches ARE the
        per-segment launches; the stage generator is staging context;
        collect is the sanctioned force point (one segment behind)."""
        self.staged = True
        count = 0
        args = list(call.args)
        # arg 0: generator — usually a call to a nested def
        if args:
            gen = args[0]
            gen_fn = None
            if isinstance(gen, ast.Call):
                gen_fn = self._callee(gen, ctx)
            elif isinstance(gen, ast.Name):
                gen_fn = next(iter(ctx.lookup(gen.id)), None)
            if gen_fn is not None:
                count += self._inline(gen_fn, ctx, per_segment=True)
        if len(args) > 1 and isinstance(args[1], ast.Name):
            for cb in ctx.lookup(args[1].id):
                count += self._inline(cb, ctx, per_segment=True)
        # arg 2 (collect): forcing there is the pattern — not walked
        # as per-segment hazard context, but launches still count
        if len(args) > 2 and isinstance(args[2], ast.Name):
            for cb in ctx.lookup(args[2].id):
                count += self._inline(cb, ctx, per_segment=False)
        return count

    def _closure_defs(self, call: ast.Call, ctx: _Ctx) -> List[ast.AST]:
        out: List[ast.AST] = []
        for arg in list(call.args) + [k.value for k in call.keywords]:
            if isinstance(arg, ast.Name):
                out.extend(ctx.lookup(arg.id))
            elif isinstance(arg, ast.Lambda):
                out.append(arg)
        return out

    def _site(self, call: ast.Call, kind: str, what: str, lane: bool,
              ctx: _Ctx) -> None:
        key = (call.lineno, call.col_offset)
        if key not in self.sites:
            self.sites[key] = _Site(call.lineno, call.col_offset, kind,
                                    what, lane, ctx.in_loop)

    def _sync(self, call: ast.Call, t: str, ctx: _Ctx) -> None:
        if not ctx.per_segment:
            return
        pending = ctx.pending
        hazard = t == "block_until_ready"
        if not hazard:
            for arg in call.args:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Call) and (
                            _terminal(sub.func) in KIND_BY_NAME
                            or _is_program_call(sub)
                            or _is_lane_call(sub)):
                        hazard = True
                    if isinstance(sub, ast.Name) and sub.id in pending:
                        hazard = True
        if hazard:
            self.sync_hits.setdefault(
                (call.lineno, call.col_offset), (call, t))


# -- per-module scan ----------------------------------------------------

class _ModuleScan:
    """All entries of one module → chains + rule findings."""

    def __init__(self, module: Module):
        self.module = module
        self.functions: Dict[str, ast.FunctionDef] = {}
        self.classes: List[ast.ClassDef] = []
        nested: Set[ast.AST] = set()
        for node in module.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                self.classes.append(node)
        del nested

    def chains(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for cls in self.classes:
            methods = {
                n.name: n for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            for entry in LAUNCH_ENTRIES:
                fn = methods.get(entry)
                if fn is None:
                    continue
                walk = _EntryWalk(self.module, methods, self.functions)
                launches = walk.run(fn)
                out.append({
                    "module": self.module.path,
                    "cls": cls.name,
                    "entry": entry,
                    "line": fn.lineno,
                    "launches_per_segment": launches,
                    "sites": [s.as_dict() for s in
                              sorted(walk.sites.values(),
                                     key=lambda s: (s.line, s.col))],
                    "scatter_sites": sorted(
                        ln for ln, _ in walk.scatters),
                    "scatter_passes": len(walk.scatters),
                    "staged": walk.staged,
                    "sync_hits": [
                        (c.lineno, c.col_offset, t)
                        for (c, t) in walk.sync_hits.values()],
                })
        return out


class LaunchGraphRules(Rule):
    name = "launch-graph"  # umbrella; findings carry precise rules
    description = ("fbtpu-xray launch-graph rules: launches per staged "
                   "segment, donation gaps, verdict round-trips, "
                   "overlap-defeating syncs, redundant arena copies")

    RULE_NAMES = ("device-multi-launch-chain", "device-undonated-buffer",
                  "device-host-roundtrip", "device-sync-in-staging-loop",
                  "stage-redundant-copy")

    def check(self, module: Module) -> List[Finding]:
        if not any(s in module.path for s in SCOPES):
            return []
        out: List[Finding] = []
        scan = _ModuleScan(module)
        flagged: Set[Tuple[int, str]] = set()

        def emit(line: int, col: int, rule: str, message: str,
                 severity: Optional[str] = None) -> None:
            if (line, rule) in flagged or module.allowed(rule, line):
                return
            flagged.add((line, rule))
            out.append(Finding(module.path, line, col, rule, message,
                               severity or _SEVERITY[rule]))

        for chain in scan.chains():
            n = chain["launches_per_segment"]
            if n > 1:
                whats = ", ".join(
                    s["what"] + ("×G" if s["in_loop"] else "")
                    for s in chain["sites"])
                emit(chain["line"], 0, "device-multi-launch-chain",
                     f"`{chain['cls']}.{chain['entry']}` reaches {n} "
                     f"device launches per staged segment ({whats}): "
                     f"each pays its own staging + PCIe crossing — the "
                     f"fusion target is ONE launch per segment "
                     f"(ROADMAP item 1)")
            if n >= 1 and chain["scatter_passes"]:
                for line in chain["scatter_sites"]:
                    emit(line, 0, "device-host-roundtrip",
                         f"device verdict from "
                         f"`{chain['cls']}.{chain['entry']}` returns to "
                         f"host as a mask, then `compact` re-walks the "
                         f"chunk bytes to scatter survivors: the bytes "
                         f"cross PCIe just to be re-indexed — a fused "
                         f"program returning compacted survivor bytes "
                         f"kills this pass")
            for line, col, t in chain["sync_hits"]:
                emit(line, col, "device-sync-in-staging-loop",
                     f"`{t}` forces a dispatch result inside the "
                     f"double-buffered segment loop of "
                     f"`{chain['cls']}.{chain['entry']}`: the host "
                     f"blocks mid-pipeline and the next segment's "
                     f"staging no longer overlaps the in-flight launch "
                     f"— force inside the lane closure (worker-side) "
                     f"or the collect callback instead")
        self._undonated(module, emit)
        self._arena_copies(module, emit)
        out.sort(key=lambda f: (f.line, f.col, f.rule))
        return out

    # -- site-level rules ---------------------------------------------

    def _undonated(self, module: Module, emit) -> None:
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and _terminal(node.func) in MESH_DISPATCH_SITES):
                continue
            donate_off = any(
                kw.arg == "donate" and isinstance(kw.value, ast.Constant)
                and kw.value.value in ("off", False)
                for kw in node.keywords)
            if donate_off:
                emit(node.lineno, node.col_offset,
                     "device-undonated-buffer",
                     "mesh dispatch with donation disabled: every "
                     "staged buffer (batch u8 [R,B,L] AND lengths i32 "
                     "[R,B]) crosses host→device un-aliased each "
                     "segment — use the auto donate set "
                     "(ops.mesh.aliasable_donations)", severity="error")
            else:
                emit(node.lineno, node.col_offset,
                     "device-undonated-buffer",
                     "staged u8 batch [R,B,L] enters the pjit launch "
                     "outside the donate set: no aliasable u8 output "
                     "exists (only lengths i32 aliases the mask), so "
                     "R*Bp*L bytes cross host→device un-donated every "
                     "segment — a fused same-aval survivor-bytes "
                     "output would make it donatable (ROADMAP item 1)")

    def _arena_copies(self, module: Module, emit) -> None:
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            tainted: Set[str] = set()
            stmts = sorted(
                (s for s in ast.walk(node) if isinstance(s, ast.Assign)),
                key=lambda s: s.lineno)
            for s in stmts:
                names = self._target_names(s.targets)
                if isinstance(s.value, ast.Call) \
                        and _terminal(s.value.func) == ARENA_STAGER:
                    tainted |= names
                elif isinstance(s.value, ast.Name) \
                        and s.value.id in tainted:
                    tainted |= names
                elif isinstance(s.value, ast.Tuple) and any(
                        isinstance(e, ast.Call)
                        and _terminal(e.func) == ARENA_STAGER
                        for e in s.value.elts):
                    tainted |= names
            if not tainted:
                continue
            for sub in ast.walk(node):
                if not (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "copy"
                        and not sub.args):
                    continue
                base = sub.func.value
                while isinstance(base, ast.Subscript):
                    base = base.value
                if isinstance(base, ast.Name) and base.id in tainted:
                    emit(sub.lineno, sub.col_offset,
                         "stage-redundant-copy",
                         f"`.copy()` on the arena view "
                         f"`{base.id}` staged by native.stage_field: "
                         f"the per-thread arena forces a copy-out that "
                         f"native.stage_field_into avoids by staging "
                         f"straight into the caller's transfer matrix "
                         f"(the mesh path already does)")

    def _target_names(self, targets) -> Set[str]:
        names: Set[str] = set()
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                names.add(tgt.id)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                for e in tgt.elts:
                    if isinstance(e, ast.Name):
                        names.add(e.id)
        return names


# -- the graph / budget API --------------------------------------------

def _package_root() -> str:
    import os

    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _eval_bytes(expr: str, env: Dict[str, int]) -> int:
    return int(eval(expr, {"__builtins__": {}}, dict(env)))  # noqa: S307


def canonical_env(params: Optional[Dict[str, int]] = None
                  ) -> Dict[str, int]:
    """The canonical evaluation point for the symbolic byte algebra —
    ``registry.BUDGET_PARAMS`` plus the derived padded batch (the
    committed ``launch_budget.json`` is evaluated here, so the gate
    compares like with like)."""
    from ..ops.batch import bucket_size

    env = dict(BUDGET_PARAMS)
    if params:
        env.update(params)
    env.setdefault("B", env["seg"])
    env.setdefault("Bp", bucket_size(env["seg"], max_len=env["L"],
                                     multiple_of=env["n_dev"]))
    # the fused absorb's padded segment table (flux/kernels:
    # _pad_segments(G) — power of two, floor 8): the [Gp, m] register
    # stack and counts table ride the fused launch at this size
    gp = 8
    while gp < env["G"]:
        gp *= 2
    env.setdefault("Gp", gp)
    return env


def donation_crosscheck(n_dev: Optional[int] = None, R: int = 2,
                        L: int = 512) -> Dict[str, Any]:
    """Cross-check the static donate/alias expectation (lengths i32
    [R,B] ↔ mask i32 [R,B] aliases; batch u8 [R,B,L] never does)
    against ``ops.mesh.aliasable_donations`` on a live mesh — exactly
    the specs ``ops.grep._mesh_handle`` donates from. Returns
    ``checked=False`` (expectation only) when jax or a multi-device
    mesh is unavailable."""
    out = {"checked": False, "batch_donated": False,
           "lengths_donated": True, "variant": "batch"}
    try:
        import jax
        from jax.sharding import PartitionSpec as P

        import numpy as np

        from ..ops.mesh import aliasable_donations, build_mesh

        devs = len(jax.devices())
        if devs < 2:
            return out
        mesh = build_mesh(min(n_dev or devs, devs))
        axis = mesh.axis_names[0]
        Bc = mesh.devices.size * 8
        cand = aliasable_donations(
            mesh,
            in_specs=[((R, Bc, L), np.uint8, P(None, axis, None), True),
                      ((R, Bc), np.int32, P(None, axis), True)],
            out_specs=[((R, Bc), np.int32, P(None, axis))],
        )
        out.update(checked=True, batch_donated=0 in cand,
                   lengths_donated=1 in cand)
    except Exception:
        pass
    return out


def table_bytes(patterns, n_dev: int = 1) -> Dict[str, Any]:
    """Post-shrink DFA matrix footprint for a rule set: the ``S × C``
    i32 transition tables + class maps the native GrepTables /
    GrepProgram build from ``FlbRegex.dfa`` (always through the PR-10
    ``compile_dfa`` reducer), replicated ``n_dev`` times on a mesh.
    The carried-over rewrite_tag / log_to_metrics accounting rides on
    this: their matrices share the same compile path, so their budget
    entries are sized (and shrink-audited) here."""
    from ..regex.dfa import compile_dfa

    per_rule = []
    total = 0
    for pat in patterns:
        dfa = compile_dfa(pat)
        nbytes = dfa.n_states * dfa.n_classes * 4 + 257
        st = dfa.shrink
        per_rule.append({
            "pattern": pat[:48], "states": dfa.n_states,
            "classes": dfa.n_classes, "bytes": nbytes,
            "states_eliminated":
                0 if st is None else st.states_eliminated,
            "classes_eliminated":
                0 if st is None else st.classes_eliminated,
        })
        total += nbytes
    return {"rules": per_rule, "bytes": total,
            "replicated_bytes": total * n_dev}


def _chain_transfers(chain: Dict[str, Any],
                     env: Dict[str, int]) -> Dict[str, Any]:
    h2d: List[Dict[str, Any]] = []
    d2h: List[Dict[str, Any]] = []
    seen: Set[Tuple[str, str]] = set()
    for site in chain["sites"]:
        shapes = TRANSFER_SHAPES.get(site["kind"])
        if shapes is None:
            continue
        for direction, rows in (("h2d", shapes["h2d"]),
                                ("d2h", shapes["d2h"])):
            for name, expr, dtype, donated in rows:
                key = (site["kind"], f"{direction}:{name}")
                if key in seen:
                    continue
                seen.add(key)
                row = {"buffer": name, "bytes": expr, "dtype": dtype,
                       "donated": donated, "kind": site["kind"],
                       "bytes_canonical": _eval_bytes(expr, env),
                       "per_group": site["in_loop"]}
                (h2d if direction == "h2d" else d2h).append(row)
    undonated = sum(r["bytes_canonical"] for r in h2d
                    if not r["donated"])
    return {
        "h2d": h2d, "d2h": d2h,
        "h2d_bytes_canonical": sum(r["bytes_canonical"] for r in h2d),
        "d2h_bytes_canonical": sum(r["bytes_canonical"] for r in d2h),
        "undonated_h2d_bytes_canonical": undonated,
    }


def build_launch_graph(root: Optional[str] = None,
                       params: Optional[Dict[str, int]] = None
                       ) -> Dict[str, Any]:
    """Scan the shipped device planes and emit the per-tag launch
    graph. A tag's filter chain composes these per-plugin chains in
    config order; per chain: launches per staged segment, the launch
    sites (kind, lane guard, ×G loop multiplicity), symbolic +
    canonical transfer bytes, host scatter passes, and the example DFA
    table footprints."""
    import os

    from . import iter_py_files, Module

    pkg = root or _package_root()
    env = canonical_env(params)
    chains: Dict[str, Any] = {}
    scopes = [os.path.join(pkg, "plugins"), os.path.join(pkg, "flux")]
    for scope in scopes:
        if not os.path.isdir(scope):
            continue
        for path in iter_py_files([scope]):
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
            rel = os.path.relpath(path, os.path.dirname(pkg))
            module = Module(rel, source)
            if module.tree is None:
                continue
            for chain in _ModuleScan(module).chains():
                cid = f"{chain['module']}::{chain['cls']}." \
                      f"{chain['entry']}"
                chain["transfers"] = _chain_transfers(chain, env)
                chains[cid] = chain
    tables = {
        name: table_bytes(pats, n_dev=env["n_dev"])
        for name, pats in EXAMPLE_TABLES.items()
    }
    try:
        # fbtpu-speccheck: predicted per-leaf PartitionSpecs + donation
        # set of every shipped device program (kernel deps may be
        # absent on a lint-only host — the graph still builds)
        from .speccheck import shardings_snapshot

        shardings = shardings_snapshot()
    except Exception:  # pragma: no cover - jax-less host
        shardings = {}
    return {
        "version": 1,
        "params": env,
        "chains": dict(sorted(chains.items())),
        "donation": donation_crosscheck(n_dev=env["n_dev"], R=env["R"],
                                        L=env["L"]),
        "tables": tables,
        "shardings": shardings,
    }


def budget_snapshot(graph: Dict[str, Any]) -> Dict[str, Any]:
    """The regression-gated subset of the graph: launches per segment
    and un-donated host→device bytes per chain (plus scatter passes).
    The committed ``analysis/launch_budget.json`` holds this snapshot —
    the item-1 fusion PR lands by SHRINKING it, and any PR that grows a
    number here fails the gate until the budget file says so."""
    chains = {}
    for cid, chain in graph["chains"].items():
        # 0-launch chains never cross PCIe — their host compacts are
        # not roundtrips, so they carry no device budget to gate
        if chain["launches_per_segment"] == 0:
            continue
        chains[cid] = {
            "launches_per_segment": chain["launches_per_segment"],
            "undonated_h2d_bytes":
                chain["transfers"]["undonated_h2d_bytes_canonical"],
            "d2h_bytes": chain["transfers"]["d2h_bytes_canonical"],
            "scatter_passes": chain["scatter_passes"],
        }
    return {"params": {k: int(v) for k, v in graph["params"].items()},
            "chains": chains,
            "shardings": graph.get("shardings", {})}


def compare_budget(current: Dict[str, Any],
                   baseline: Dict[str, Any]
                   ) -> Tuple[List[str], List[str]]:
    """Compare a budget snapshot against the committed baseline →
    (regressions, notes). Any growth in launches-per-segment,
    un-donated bytes, or scatter passes — or a device chain the
    baseline has never seen — is a regression; improvements are notes
    (regenerate the budget file to claim them)."""
    regressions: List[str] = []
    notes: List[str] = []
    base_chains = baseline.get("chains", {})
    gate_keys = ("launches_per_segment", "undonated_h2d_bytes",
                 "scatter_passes")
    for cid, cur in current.get("chains", {}).items():
        base = base_chains.get(cid)
        if base is None:
            regressions.append(
                f"{cid}: new device chain not in launch_budget.json "
                f"({cur['launches_per_segment']} launches/segment) — "
                f"baseline it deliberately or fuse it")
            continue
        for key in gate_keys:
            b, c = int(base.get(key, 0)), int(cur.get(key, 0))
            if c > b:
                regressions.append(
                    f"{cid}: {key} grew {b} → {c} (the budget file "
                    f"gates this — a fusion PR shrinks it, nothing "
                    f"grows it silently)")
            elif c < b:
                notes.append(
                    f"{cid}: {key} improved {b} → {c}; regenerate "
                    f"launch_budget.json (--write-budget) to claim it")
    for cid in base_chains:
        if cid not in current.get("chains", {}):
            notes.append(f"{cid}: chain no longer reaches the device "
                         f"plane; regenerate launch_budget.json")
    _compare_shardings(current, baseline, regressions, notes)
    return regressions, notes


def _compare_shardings(current: Dict[str, Any], baseline: Dict[str, Any],
                       regressions: List[str], notes: List[str]) -> None:
    """fbtpu-speccheck leaf-spec regression: a table/input/output leaf
    whose predicted PartitionSpec (or a program's predicted donation
    set) differs from the committed snapshot fails the gate — a
    sharding refactor must re-baseline deliberately (--write-budget).
    A baseline written before the shardings block existed gates
    nothing (old synthetic baselines in tests stay valid); a current
    snapshot can also be empty on a kernel-less host — skip then too,
    never fail on missing machinery."""
    base_sh = baseline.get("shardings")
    cur_sh = current.get("shardings")
    if not base_sh or not cur_sh:
        return
    for pname, cur in cur_sh.items():
        base = base_sh.get(pname)
        if base is None:
            regressions.append(
                f"{pname}: new device program not in "
                f"launch_budget.json shardings — baseline its "
                f"predicted specs deliberately (--write-budget)")
            continue
        for group in ("tables", "inputs", "outputs"):
            bleaves = base.get(group, {})
            for leaf, spec in cur.get(group, {}).items():
                if leaf not in bleaves:
                    regressions.append(
                        f"{pname}: {group} leaf `{leaf}` not in the "
                        f"committed shardings snapshot — re-baseline "
                        f"(--write-budget)")
                elif bleaves[leaf] != spec:
                    regressions.append(
                        f"{pname}: {group} leaf `{leaf}` sharding "
                        f"changed {bleaves[leaf]!r} → {spec!r}: a "
                        f"layout change re-shards resident state at "
                        f"the next dispatch — re-baseline "
                        f"deliberately (--write-budget)")
            for leaf in bleaves:
                if leaf not in cur.get(group, {}):
                    notes.append(
                        f"{pname}: {group} leaf `{leaf}` left the "
                        f"program; regenerate launch_budget.json")
        if base.get("donate_predicted") is not None \
                and base["donate_predicted"] != cur.get(
                    "donate_predicted"):
            regressions.append(
                f"{pname}: predicted donation set changed "
                f"{base['donate_predicted']!r} → "
                f"{cur.get('donate_predicted')!r} — an input stopped "
                f"(or started) aliasing its output; re-baseline "
                f"deliberately (--write-budget)")
    for pname in base_sh:
        if pname not in cur_sh:
            notes.append(f"{pname}: program left the shipped set; "
                         f"regenerate launch_budget.json")


def graph_to_dot(graph: Dict[str, Any]) -> str:
    """Graphviz rendering: entry → launch sites (kind, lane guard,
    canonical bytes) → host sinks (scatter passes)."""
    lines = ["digraph launchgraph {", "  rankdir=LR;",
             '  node [shape=box, fontname="monospace"];']
    for cid, chain in graph["chains"].items():
        if not chain["sites"] and not chain["scatter_passes"]:
            continue
        ent = f'"{cid}"'
        n = chain["launches_per_segment"]
        lines.append(
            f'  {ent} [label="{chain["cls"]}.{chain["entry"]}\\n'
            f'{n} launch(es)/segment", style=bold];')
        for site in chain["sites"]:
            sid = f'"{cid}#L{site["line"]}"'
            guard = "lane" if site["lane"] else "UNGUARDED"
            mult = " ×G" if site["in_loop"] else ""
            lines.append(
                f'  {sid} [label="{site["what"]}{mult}\\n'
                f'{site["kind"]} [{guard}]"];')
            lines.append(f"  {ent} -> {sid};")
        if chain["scatter_passes"]:
            hid = f'"{cid}#scatter"'
            lines.append(
                f'  {hid} [label="host scatter ×'
                f'{chain["scatter_passes"]}\\n(compact)", '
                f'style=dashed];')
            lines.append(f"  {ent} -> {hid} [style=dashed];")
    lines.append("}")
    return "\n".join(lines)
