"""The guarded-by registry — the declarative core of the lock rule.

Each entry names, for ONE module, the attributes (``kind="attr"``:
``obj.<name>`` accesses) or module globals (``kind="global"``: bare
names under a module-level lock) whose access must lexically sit inside
``with <lock>:``. The checker is intentionally name-based — matching the
lock *object* would need points-to analysis; matching the lock *name*
catches the real bug class (a new call path touching guarded state
off-lock) at zero false-positive cost in a codebase where lock names are
unique per module.

``writes_only=True`` entries allow lock-free reads: these are the
documented benign-staleness probes (``device.ready()``, the codec
loader's double-checked fast path, the ``paused`` backpressure flag read
by collectors) where a stale read is part of the design and only the
check-then-act WRITE must serialize.

Accesses inside ``__init__``/``__new__`` (attr kind) and at module top
level (global kind) are exempt: construction precedes sharing.

Adding state to a guarded structure? Extend the entry (or add one) in
the same PR — the lint gate then enforces the discipline on every
future caller.
"""

from __future__ import annotations

import os

from dataclasses import dataclass
from typing import Dict, Tuple

__all__ = ["GuardEntry", "GUARDS", "LAUNCH_ENTRIES", "BUDGET_PARAMS",
           "budget_path", "lock_baseline_path", "copy_budget_path",
           "fusion_plan_path"]

# -- fbtpu-xray (analysis/launchgraph.py) declarative plumbing ---------

#: Chain entry points the launch-graph walker roots at: the batched
#: plugin fast path, the raw grep path, and the flux absorb commit.
LAUNCH_ENTRIES: Tuple[str, ...] = ("process_batch", "filter_raw",
                                   "absorb_batch")

#: Canonical evaluation point for the symbolic transfer-byte algebra —
#: the committed analysis/launch_budget.json is evaluated here (2
#: double-buffered staging slots, the default FBTPU_SEGMENT_RECORDS,
#: the grep max_len default, the simulated 8-device mesh, one flux
#: group, HLL p=12 registers, the CMS 4×16384 table — the FluxSpec
#: defaults).
BUDGET_PARAMS: Dict[str, int] = {
    "R": 2, "seg": 4096, "L": 512, "n_dev": 8, "G": 1,
    "M_hll": 1 << 12, "M_cms": 4 * 16384,
}


def budget_path() -> str:
    """Path of the committed launch/transfer budget baseline."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "launch_budget.json")


def lock_baseline_path() -> str:
    """Path of the committed fbtpu-locksmith findings baseline."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lock_baseline.json")


def copy_budget_path() -> str:
    """Path of the committed fbtpu-memscope copy budget baseline."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "copy_budget.json")


def fusion_plan_path() -> str:
    """Path of the committed fbtpu-fuseplan fusion plan baseline."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fusion_plan.json")


@dataclass(frozen=True)
class GuardEntry:
    #: module path suffix the entry applies to (posix separators)
    module: str
    #: lock name: the terminal attribute (``self._lock`` → ``_lock``) or
    #: the bare global holding the lock
    lock: str
    #: guarded attribute / global names
    attrs: Tuple[str, ...]
    #: True → lock-free reads are a documented part of the design
    writes_only: bool = False
    #: "attr" = obj.<name> accesses; "global" = module-level bare names
    kind: str = "attr"
    #: why the entry exists (shown in findings)
    note: str = ""


GUARDS: Tuple[GuardEntry, ...] = (
    # -- engine: the asyncio-loop / collector-thread / caller boundary --
    GuardEntry(
        "fluentbit_tpu/core/engine.py", "_ingest_lock",
        ("_ingest_src", "_backlog", "_task_map"),
        note="ingest path state: appends run on collector threads and "
             "library callers while flush_all runs on the engine loop "
             "(and flush_now on any thread)",
    ),
    GuardEntry(
        "fluentbit_tpu/core/engine.py", "_event_queue_lock",
        ("_event_queue",),
        note="priority bucket queue: enqueued from any thread, drained "
             "on the engine loop",
    ),
    GuardEntry(
        "fluentbit_tpu/core/engine.py", "ingest_lock", ("pool",),
        note="per-input chunk pool: parallel raw-path ingest appends "
             "race flush_all's drain without the input's lock",
    ),
    GuardEntry(
        "fluentbit_tpu/core/engine.py", "ingest_lock", ("paused",),
        writes_only=True,
        note="backpressure flag: collectors read it lock-free (benign "
             "staleness) but the check-then-act pause/resume flip must "
             "not double-fire plugin callbacks",
    ),
    GuardEntry(
        "fluentbit_tpu/core/plugin.py", "ingest_lock", ("paused",),
        writes_only=True,
        note="same flag, defining module (InputInstance.set_paused)",
    ),
    GuardEntry(
        "fluentbit_tpu/core/engine.py", "_ingest_lock",
        ("traces", "_retired_names", "_retired_outputs"),
        writes_only=True,
        note="hot-reload/trace bookkeeping (fbtpu-locksmith): trace "
             "installs, retired-name tombstones and the retired-output "
             "reap list are mutated by reload commits, trace admin "
             "calls, the reap timer and stop, racing each other; "
             "reads are lock-free probes by design",
    ),
    # -- fbtpu-guard: flights/breakers/shed touched from the engine
    #    loop, flush_now callers, and sync-fallback flushes --
    GuardEntry(
        "fluentbit_tpu/core/guard.py", "_lock",
        ("_flights", "_abandoned", "_shed"),
        note="guard plane state: the watchdog (engine loop or a "
             "flush_now caller thread) races flush done-callbacks and "
             "result recording",
    ),
    GuardEntry(
        "fluentbit_tpu/core/guard.py", "_lock",
        ("_breakers", "_unhealthy"), writes_only=True,
        note="breaker map + not-closed count: the dispatch hot path's "
             "health probe (maybe_shed's early-out) reads lock-free "
             "by design (benign staleness of one flush cycle); "
             "mutation serializes",
    ),
    GuardEntry(
        "fluentbit_tpu/core/guard.py", "_ingest_lock",
        ("_task_map", "_backlog"),
        note="engine ingest-path state read/written by the guard "
             "(occupancy, shed readmission): same discipline as "
             "core/engine.py's own entry",
    ),
    # -- fbtpu-qos: tenant registry + fair dispatch queue --
    GuardEntry(
        "fluentbit_tpu/core/qos.py", "_lock",
        ("_tenants", "_queue"),
        note="qos plane state: ingest threads resolve tenants while "
             "the engine loop / flush_now callers pop the fair queue "
             "and reload transactions re-declare contracts",
    ),
    GuardEntry(
        "fluentbit_tpu/core/qos.py", "_ingest_lock",
        ("_backlog", "_task_map"),
        note="engine ingest-path state written by the reload "
             "generation swap (removed-input drain, list swap): same "
             "discipline as core/engine.py's own entry",
    ),
    GuardEntry(
        "fluentbit_tpu/core/qos.py", "ingest_lock", ("pool",),
        note="per-input chunk pools drained by the reload swap race "
             "parallel raw-path appends without the input's lock",
    ),
    GuardEntry(
        "fluentbit_tpu/core/qos.py", "_ingest_lock",
        ("traces", "_retired_names", "_retired_outputs"),
        writes_only=True,
        note="the reload transaction mutates the same engine "
             "hot-reload bookkeeping from the committing thread "
             "(same discipline as core/engine.py's own entry)",
    ),
    GuardEntry(
        "fluentbit_tpu/core/qos.py", "_lock", ("_graded",),
        writes_only=True,
        note="priority-grading flag: the dispatch hot path reads it "
             "lock-free (benign staleness of one flush cycle); "
             "recomputation serializes with tenant changes",
    ),
    # -- metrics: counters incremented from every thread family --
    GuardEntry(
        "fluentbit_tpu/core/metrics.py", "_lock",
        ("_values", "_counts", "_sums", "_metrics"),
        note="cmetrics state: ingest threads, the engine loop, output "
             "workers and the admin server all touch the same registry",
    ),
    # -- shared sqlite handle registry --
    GuardEntry(
        "fluentbit_tpu/core/sqldb.py", "_lock", ("_open_dbs",),
        kind="global",
        note="shared-handle registry: open_db/close run from any "
             "plugin thread; every access serializes on the module "
             "lock (fbtpu-locksmith registry gap)",
    ),
    # -- lock-order witness recorder (fbtpu-locksmith ground truth) --
    GuardEntry(
        "fluentbit_tpu/core/lockorder.py", "_edges_guard", ("_edges",),
        kind="global",
        note="witness edge set: every acquiring thread records into "
             "it; snapshot/reset serialize on the guard",
    ),
    # -- host-copy witness recorder (fbtpu-memscope ground truth) --
    GuardEntry(
        "fluentbit_tpu/core/copywitness.py", "_counts_guard",
        ("_counts",), kind="global",
        note="copy-witness accumulator: every ingest/replay thread "
             "records into it; snapshot/reset serialize on the guard",
    ),
    GuardEntry(
        "fluentbit_tpu/core/copywitness.py", "_counts_guard",
        ("_enabled",), writes_only=True, kind="global",
        note="witness enable flag: the ingest hot path reads it "
             "lock-free by design (one falsy load when disabled); the "
             "refresh() flip serializes",
    ),
    # -- native loaders: double-checked module singletons --
    GuardEntry(
        "fluentbit_tpu/codec/_native_codec.py", "_lock",
        ("_mod", "_tried"), writes_only=True, kind="global",
        note="codec loader: lock-free settled-state fast path is "
             "documented; the build/load transition must serialize",
    ),
    GuardEntry(
        "fluentbit_tpu/native/__init__.py", "_lock",
        ("_lib", "_tried"), writes_only=True, kind="global",
        note="data-plane loader: same double-checked pattern",
    ),
    # -- device attach controller --
    GuardEntry(
        "fluentbit_tpu/ops/device.py", "_lock",
        ("_state", "_error", "_attach_seconds", "_platform", "_thread",
         "_attempts", "_retry_history", "_next_retry_at", "_generation"),
        writes_only=True, kind="global",
        note="attach state machine (retry-world, fbtpu-armor): "
             "ready()/failed()/generation()/status() are lock-free "
             "probes by design; transitions and retry bookkeeping "
             "serialize",
    ),
    # -- fbtpu-armor device fault domain --
    GuardEntry(
        "fluentbit_tpu/ops/fault.py", "_lock",
        ("_stats", "_lost", "_ok_since_shrink", "_mesh", "_mesh_key"),
        writes_only=True,
        note="device-lane failover state: stats()/current_mesh() "
             "fast-path reads are benign-staleness probes; mutation "
             "(launch outcomes, shrink/regrow) serializes",
    ),
    GuardEntry(
        "fluentbit_tpu/ops/fault.py", "_registry_lock",
        ("_lanes",), kind="global",
        note="process-global lane registry: created from plugin init "
             "on any thread, read by health/bench snapshots",
    ),
    GuardEntry(
        "fluentbit_tpu/ops/fault.py", "_listener_lock",
        ("_listeners",), kind="global",
        note="fault event listener list: engines register/release on "
             "start/stop while lanes notify from worker threads",
    ),
    # -- fbtpu-relay: forward fan-in dedup ledger + partition spool --
    GuardEntry(
        "fluentbit_tpu/core/relay.py", "_lock",
        ("_seen", "dedup_hits"),
        note="dedup ledger map + hit counter: the server's event loop "
             "absorbs while health snapshots and the soak audit read; "
             "seen/record/GC must serialize (a torn check-then-record "
             "IS a double-absorb)",
    ),
    GuardEntry(
        "fluentbit_tpu/core/relay.py", "_lock", ("_seq",),
        note="spool sequence counter: concurrent degrades must never "
             "mint the same file name (replay order is the name order)",
    ),
    # -- analyzer caches (fbtpu-locksmith lockset scope) --
    GuardEntry(
        "fluentbit_tpu/analysis/speccheck.py", "_cache_lock",
        ("_programs_cache",), writes_only=True, kind="global",
        note="shipped-programs cache: double-checked build — the "
             "lock-free settled fast path is documented, the "
             "build/store transition must serialize (speccheck runs "
             "from tests and the CLI concurrently)",
    ),
)
