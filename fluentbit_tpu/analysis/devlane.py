"""device-unguarded-dispatch rule.

fbtpu-armor (ops/fault.py) wraps every engine/plugin entry into the
jit/pjit/shard_map plane in a :class:`DeviceLane`: breaker, launch
deadline, bit-exact CPU fallback, mesh shrink/regrow. The whole
fault-domain contract rests on that invariant — a device dispatch added
later that calls the kernel directly would reintroduce exactly the
failure modes the lane exists to contain (a wedged launch stalling
ingest, an XlaRuntimeError dropping a segment's verdict, a consumed
donated buffer read on retry), and nothing at runtime would notice
until the first real fault.

``device-unguarded-dispatch`` makes the invariant machine-checked (the
``qos-unmetered-ingest`` pattern): in ``fluentbit_tpu/plugins/`` and
``fluentbit_tpu/flux/`` modules, every PUBLIC function from which a
*device dispatch call* is reachable (directly or through same-module
helpers) must also reach a lane-guarded launch — a ``.run(`` /
``.begin(`` / ``.finish(`` call on something whose name chain mentions
``lane``. Dispatch calls are matched by name: the GrepProgram mesh/
sharded matchers, the sketch sharded updates and device_* compute
variants, and ``.dispatch(``/``.match(`` on a ``*program*`` chain.
Reachability is the same intentionally-lexical same-module call-name
closure the qos rule uses. The kernel layer itself (``ops/``) is out of
scope — lanes are the *boundary*, not the internals.

Suppress with ``# fbtpu-lint: allow(device-unguarded-dispatch)`` plus a
justification — e.g. a bench-only diagnostic path that wants the raw
failure.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from . import Finding, Module, Rule

__all__ = ["UnguardedDispatchRule"]

#: Engine-facing device planes; ops/ (the kernel layer the lanes wrap)
#: and bench/test harnesses are out of scope.
SCOPES = ("fluentbit_tpu/plugins/", "fluentbit_tpu/flux/")

#: Calls that enter the jit/pjit/shard_map plane by simple name.
DISPATCH_NAMES = frozenset({
    "dispatch_mesh", "match_mesh", "match_sharded",
    "sharded_hll_update", "sharded_cms_update",
    "sharded_hll_registers", "sharded_cms_table",
    "sharded_segment_counts", "device_registers", "device_table",
})

#: Attr names that count as dispatch only on a ``*program*`` chain
#: (``self._program.dispatch(...)`` / ``_program.match(...)``).
PROGRAM_ATTRS = frozenset({"dispatch", "match"})

LANE_GUARDS = frozenset({"run", "begin", "finish"})


def _chain_names(node) -> Set[str]:
    out: Set[str] = set()
    while True:
        if isinstance(node, ast.Attribute):
            out.add(node.attr)
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func  # self._lane().run — walk through the call
        else:
            break
    if isinstance(node, ast.Name):
        out.add(node.id)
    return out


def _is_dispatch(call: ast.Call) -> bool:
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    if name in DISPATCH_NAMES:
        return True
    if isinstance(f, ast.Attribute) and f.attr in PROGRAM_ATTRS:
        return any("program" in n for n in _chain_names(f.value))
    return False


def _is_lane_guard(call: ast.Call) -> bool:
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr in LANE_GUARDS
            and any("lane" in n for n in _chain_names(f.value)))


class _FnInfo:
    __slots__ = ("node", "dispatches", "guarded", "calls")

    def __init__(self, node):
        self.node = node
        self.dispatches: List[ast.Call] = []
        self.guarded = False
        self.calls: Set[str] = set()


def _analyze(fn) -> _FnInfo:
    """One function's dispatch calls, lane guards, and called simple
    names. Nested closures (the lane launch/fallback lambdas) count
    toward the enclosing function — the guard and the dispatch live in
    the same logical launch path."""
    info = _FnInfo(fn)
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if _is_lane_guard(node):
            info.guarded = True
        elif _is_dispatch(node):
            info.dispatches.append(node)
        f = node.func
        if isinstance(f, ast.Name):
            info.calls.add(f.id)
        elif isinstance(f, ast.Attribute):
            info.calls.add(f.attr)
    return info


class UnguardedDispatchRule(Rule):
    name = "device-unguarded-dispatch"
    description = ("engine/plugin path reaches a jit/pjit/shard_map "
                   "dispatch without going through the fbtpu-armor "
                   "DeviceLane — device faults would stall or drop "
                   "instead of failing over (ops/fault.py)")

    def check(self, module: Module) -> List[Finding]:
        if not any(s in module.path for s in SCOPES):
            return []
        by_name: Dict[str, List[_FnInfo]] = {}
        infos: List[_FnInfo] = []
        nested: Set[ast.AST] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = _analyze(node)
                infos.append(info)
                by_name.setdefault(node.name, []).append(info)
                for sub in ast.walk(node):
                    if sub is not node and isinstance(
                            sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        nested.add(sub)

        def closure(start: _FnInfo) -> Tuple[List[ast.Call], bool]:
            dispatches: List[ast.Call] = list(start.dispatches)
            guarded = start.guarded
            seen: Set[str] = {start.node.name}
            frontier = set(start.calls)
            while frontier:
                name = frontier.pop()
                if name in seen:
                    continue
                seen.add(name)
                for callee in by_name.get(name, ()):
                    dispatches.extend(callee.dispatches)
                    guarded = guarded or callee.guarded
                    frontier.update(callee.calls)
            return dispatches, guarded

        out: List[Finding] = []
        for info in infos:
            name = info.node.name
            if name.startswith("_"):
                continue  # helpers are covered via their public callers
            if info.node in nested:
                continue  # closures are reached via their container
            dispatches, guarded = closure(info)
            if not dispatches or guarded:
                continue
            f = self.finding(
                module, info.node,
                f"device path {name!r} reaches a jit/shard_map dispatch "
                f"(line "
                f"{', '.join(str(d.lineno) for d in dispatches[:3])}) "
                f"without the fbtpu-armor DeviceLane (lane.run/begin/"
                f"finish) — device faults must fail over bit-exactly, "
                f"not stall or drop (ops/fault.py)",
                extra_lines=tuple(d.lineno for d in dispatches))
            if f is not None:
                out.append(f)
        return out
