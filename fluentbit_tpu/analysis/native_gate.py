"""Static-analysis gate for the native C/C++ data plane.

The codec extension (native/fbtpu_codec.c, ~1.4k LoC of hand-rolled
msgpack/JSON byte walking) and the ctypes data plane
(native/fbtpu_native.cpp) had ZERO static checking — exactly the code
whose bug classes (out-of-bounds cursor reads over hostile bytes,
container headers whose declared lengths drift from what gets emitted,
error paths leaking allocations) the sanitizer tests only catch when a
test vector happens to hit them. This module runs four layers, each
degrading to a note (never a silent pass) when its tool is missing:

1. **clang-tidy** with the repo profile (``.clang-tidy`` at the root):
   the bugprone-*/clang-analyzer-* checks tuned for this codebase.
2. **gcc -fanalyzer** (the GCC static analyzer): interprocedural
   path-sensitive malloc/leak/null/overflow analysis. Always available
   where the native build itself is (same gcc).
3. **codec invariant checker** (Python over ``clang.cindex``): the
   repo-specific contracts no generic tool knows —

   - ``codec-balance``: every msgpack container header emitted with a
     literal fixmap/fixarray byte must be balanced by exactly the
     declared number of element emissions (``pack_obj``/header calls)
     in straight-line emitter functions;
   - ``codec-bounds``: every function advancing/dereferencing a reader
     cursor (``r->p`` / ``t->p``) must bounds-check (a ``need()`` call
     or an ``end`` comparison), and every raw ``memcpy``/``memmove``
     into the writer buffer must be dominated by ``wr_reserve``;
   - ``codec-leak``: a function that ``PyMem_Malloc``s must free on its
     error paths (function-level heuristic: an alloc with no
     ``PyMem_Free``/``free`` anywhere in the function).

4. **untrusted-bytes bounds checker** (``untrusted-bounds``, also over
   ``clang.cindex``, fbtpu-memscope's native layer): every function
   whose byte-pointer parameters carry wire/chunk bytes is an
   untrusted scope — dereferences and cursor advances there must be
   dominated by a bounds check against the span end, and the check
   must be the overflow-safe subtraction form (``len <= end - p``),
   never the addition form (``p + len <= end``, which wraps on
   adversarial lengths).

Suppressions use the same syntax as the Python side, in C comments on
the flagged line or the line above::

    static uint64_t rd_be(rd *r, int n) { /* fbtpu-lint: allow(codec-bounds) */

Results are cached under ``native/build/analysis-cache/`` keyed by the
source digest + tool identity, so the test gate pays the (~25 s g++
analyzer) cost once per source change, not per run.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import subprocess
import sys
import sysconfig
import tempfile
from typing import Dict, List, Optional, Sequence, Tuple

from . import Finding

__all__ = [
    "native_sources", "run_native_gate", "run_gcc_analyzer",
    "run_clang_tidy", "run_codec_checker", "check_codec_file",
    "run_bounds_checker", "check_bounds_file", "NATIVE_RULES",
]

NATIVE_RULES = ("clang-tidy", "gcc-analyzer", "codec-balance",
                "codec-bounds", "codec-leak", "untrusted-bounds")

_DIAG_RE = re.compile(
    r"^(?P<path>[^:\s][^:]*):(?P<line>\d+):(?P<col>\d+):\s+"
    r"(?P<sev>warning|error):\s+(?P<msg>.*?)"
    r"(?:\s+\[(?P<opt>[-\w.,=+]+)\])?$")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def native_sources(root: Optional[str] = None) -> List[Tuple[str, str]]:
    """→ [(path, lang)] for the native data plane."""
    root = root or repo_root()
    out = []
    for name, lang in (("fbtpu_codec.c", "c"), ("fbtpu_native.cpp", "c++")):
        p = os.path.join(root, "native", name)
        if os.path.exists(p):
            out.append((p, lang))
    return out


def _py_include() -> Optional[str]:
    inc = sysconfig.get_paths().get("include")
    if inc and os.path.exists(os.path.join(inc, "Python.h")):
        return inc
    return None


def _gcc_builtin_include() -> Optional[str]:
    """GCC's builtin headers (stddef.h/limits.h) — libclang ships
    without its own resource dir in this environment, and GCC's set
    parses fine for analysis purposes."""
    try:
        out = subprocess.run(["gcc", "-print-file-name=include"],
                             capture_output=True, text=True, timeout=10)
    except (OSError, subprocess.TimeoutExpired):
        return None
    path = out.stdout.strip()
    return path if path and os.path.isdir(path) else None


# ---------------------------------------------------------------------
# C-side suppressions + result cache
# ---------------------------------------------------------------------

#: the Python side's allow() syntax, minus the `#` (C comments)
_C_ALLOW_RE = re.compile(r"fbtpu-lint:\s*allow\(([^)]*)\)")


def _c_allowed(lines: Sequence[str], rule: str, line: int) -> bool:
    """``fbtpu-lint: allow(<rule>)`` in a comment on the flagged line or
    the line above (C twin of Module.allowed)."""
    for ln in (line, line - 1):
        if 1 <= ln <= len(lines):
            m = _C_ALLOW_RE.search(lines[ln - 1])
            if m:
                names = {p.strip() for p in m.group(1).split(",")}
                if rule in names or "*" in names:
                    return True
    return False


def _filter_allowed(findings: List[Finding],
                    src_lines: Dict[str, List[str]]) -> List[Finding]:
    out = []
    for f in findings:
        lines = src_lines.get(f.path)
        if lines is not None and _c_allowed(lines, f.rule, f.line):
            continue
        out.append(f)
    return out


def _cache_dir(root: str) -> str:
    return os.path.join(root, "native", "build", "analysis-cache")


def _cache_load(root: str, name: str, digest: str) -> Optional[list]:
    try:
        with open(os.path.join(_cache_dir(root), name + ".json")) as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return None
    if data.get("digest") != digest:
        return None
    return data.get("findings", [])


def _cache_store(root: str, name: str, digest: str,
                 findings: List[Finding]) -> None:
    try:
        os.makedirs(_cache_dir(root), exist_ok=True)
        with open(os.path.join(_cache_dir(root), name + ".json"),
                  "w") as fh:
            json.dump({"digest": digest,
                       "findings": [f.__dict__ for f in findings]}, fh)
    except OSError:
        pass  # cache is an optimization; the gate re-runs without it


def _digest(parts: Sequence[str]) -> str:
    h = hashlib.sha256()
    for p in parts:
        h.update(p.encode("utf-8", "replace"))
        h.update(b"\x00")
    return h.hexdigest()


def _rel(root: str, path: str) -> str:
    return os.path.relpath(path, root).replace(os.sep, "/")


def _read_lines(paths: Sequence[str], root: str) -> Dict[str, List[str]]:
    out: Dict[str, List[str]] = {}
    for p in paths:
        try:
            with open(p, "r", encoding="utf-8", errors="replace") as fh:
                out[_rel(root, p)] = fh.read().splitlines()
        except OSError:
            pass
    return out


# ---------------------------------------------------------------------
# layer 1: clang-tidy (repo profile in .clang-tidy)
# ---------------------------------------------------------------------

def run_clang_tidy(root: Optional[str] = None, cache: bool = True
                   ) -> Tuple[List[Finding], List[str]]:
    root = root or repo_root()
    notes: List[str] = []
    tidy = shutil.which("clang-tidy")
    if tidy is None:
        return [], ["clang-tidy: not installed — layer skipped "
                    "(install clang-tidy to enable the profile in "
                    ".clang-tidy)"]
    inc = _py_include()
    findings: List[Finding] = []
    try:
        with open(os.path.join(root, ".clang-tidy"), encoding="utf-8",
                  errors="replace") as fh:
            profile = fh.read()
    except OSError:
        profile = ""
    for src, lang in native_sources(root):
        base_args = ["-I", inc] if inc else []
        if lang == "c++":
            base_args += ["-std=c++17", "-pthread"]
        # the profile is an input too: editing .clang-tidy must miss
        # the cache, or a new check silently never runs
        digest = _digest([open(src, encoding="utf-8",
                               errors="replace").read(),
                          " ".join(base_args), profile, "tidy-v1"])
        name = "tidy-" + os.path.basename(src)
        if cache:
            hit = _cache_load(root, name, digest)
            if hit is not None:
                findings.extend(Finding(**d) for d in hit)
                notes.append(f"clang-tidy: {os.path.basename(src)} "
                             f"(cached)")
                continue
        try:
            proc = subprocess.run(
                [tidy, "--quiet", src, "--"] + base_args,
                capture_output=True, text=True, timeout=600, cwd=root)
        except (OSError, subprocess.TimeoutExpired) as e:
            notes.append(f"clang-tidy: failed on {src}: {e}")
            continue
        got = _parse_diags(proc.stdout + proc.stderr, root,
                           rule="clang-tidy")
        got = [f for f in got if f.path.startswith("native/")]
        _cache_store(root, name, digest, got)
        findings.extend(got)
        notes.append(f"clang-tidy: {os.path.basename(src)} analyzed")
    src_lines = _read_lines([s for s, _l in native_sources(root)], root)
    return _filter_allowed(findings, src_lines), notes


def _parse_diags(text: str, root: str, rule: str,
                 only_analyzer: bool = False) -> List[Finding]:
    out: List[Finding] = []
    seen = set()
    for line in text.splitlines():
        m = _DIAG_RE.match(line.strip())
        if not m:
            continue
        opt = m.group("opt") or ""
        if only_analyzer and not opt.startswith("-Wanalyzer"):
            continue
        path = m.group("path")
        if not os.path.isabs(path):
            path = os.path.join(root, path)
        rel = _rel(root, path)
        msg = m.group("msg")
        if opt:
            msg = f"{msg} [{opt}]"
        sev = "error" if m.group("sev") == "error" else "warning"
        key = (rel, int(m.group("line")), msg)
        if key in seen:
            continue
        seen.add(key)
        out.append(Finding(rel, int(m.group("line")),
                           int(m.group("col")), rule, msg, sev))
    return out


# ---------------------------------------------------------------------
# layer 2: gcc -fanalyzer
# ---------------------------------------------------------------------

def run_gcc_analyzer(root: Optional[str] = None, cache: bool = True,
                     sources: Optional[List[Tuple[str, str]]] = None
                     ) -> Tuple[List[Finding], List[str]]:
    root = root or repo_root()
    notes: List[str] = []
    findings: List[Finding] = []
    inc = _py_include()
    srcs = sources if sources is not None else native_sources(root)
    for src, lang in srcs:
        cc = shutil.which("g++" if lang == "c++" else "gcc")
        if cc is None:
            notes.append(f"gcc-analyzer: no compiler for {src} — skipped")
            continue
        args = [cc, "-fanalyzer", "-O0", "-c"]
        if inc:
            args += ["-I", inc]
        if lang == "c++":
            args += ["-std=c++17", "-pthread"]
        digest = _digest([open(src, encoding="utf-8",
                               errors="replace").read(),
                          " ".join(args), "fanalyzer-v1"])
        name = "fanalyzer-" + os.path.basename(src)
        if cache and sources is None:
            hit = _cache_load(root, name, digest)
            if hit is not None:
                findings.extend(Finding(**d) for d in hit)
                notes.append(f"gcc-analyzer: {os.path.basename(src)} "
                             f"(cached)")
                continue
        with tempfile.TemporaryDirectory() as td:
            obj = os.path.join(td, "out.o")
            try:
                proc = subprocess.run(args + [src, "-o", obj],
                                      capture_output=True, text=True,
                                      timeout=600, cwd=root)
            except (OSError, subprocess.TimeoutExpired) as e:
                notes.append(f"gcc-analyzer: failed on {src}: {e}")
                continue
        got = _parse_diags(proc.stderr, root, rule="gcc-analyzer",
                           only_analyzer=True)
        if proc.returncode != 0 and not got:
            notes.append(f"gcc-analyzer: compile failed for {src}: "
                         f"{proc.stderr[-300:]}")
            continue
        if cache and sources is None:
            _cache_store(root, name, digest, got)
        findings.extend(got)
        notes.append(f"gcc-analyzer: {os.path.basename(src)} analyzed")
    src_lines = _read_lines([s for s, _l in srcs], root)
    return _filter_allowed(findings, src_lines), notes


# ---------------------------------------------------------------------
# layer 3: codec invariant checker (clang.cindex)
# ---------------------------------------------------------------------

#: emitter functions whose calls form the msgpack output stream
_EMITTERS = {"wr_u8", "wr_be", "wr_bytes", "pack_obj", "pack_header"}
#: emitters encoding exactly one complete msgpack value per call
_VALUE_EMITTERS = {"pack_obj"}


def _load_cindex():
    try:
        import clang.cindex as ci
        ci.Index.create()  # probes libclang itself
        return ci
    except Exception:
        return None


def check_codec_file(path: str, root: Optional[str] = None,
                     extra_args: Sequence[str] = ()
                     ) -> Tuple[List[Finding], List[str]]:
    """Run the codec invariant checks over one C file. Separated from
    the gate wrapper so fixture tests can feed known-bad snippets."""
    root = root or repo_root()
    ci = _load_cindex()
    if ci is None:
        return [], ["codec-checker: clang.cindex/libclang unavailable "
                    "— layer skipped"]
    args: List[str] = list(extra_args)
    inc = _py_include()
    if inc:
        args += ["-I", inc]
    gccinc = _gcc_builtin_include()
    if gccinc:
        args += ["-isystem", gccinc]
    try:
        tu = ci.Index.create().parse(path, args=args)
    except Exception as e:
        return [], [f"codec-checker: parse failed for {path}: {e}"]
    errs = [d for d in tu.diagnostics
            if d.severity >= ci.Diagnostic.Error]
    if errs:
        return [], [f"codec-checker: {len(errs)} parse errors in "
                    f"{path} (first: {errs[0]}) — layer skipped"]
    rel = _rel(root, path) if os.path.isabs(path) else path
    try:
        with open(path, encoding="utf-8", errors="replace") as fh:
            lines = fh.read().splitlines()
    except OSError:
        lines = []
    findings: List[Finding] = []

    def emit(rule: str, line: int, col: int, msg: str) -> None:
        if not _c_allowed(lines, rule, line):
            findings.append(Finding(rel, line, col, rule, msg, "error"))

    main_file = os.path.basename(path)
    for fn in tu.cursor.get_children():
        if fn.kind != ci.CursorKind.FUNCTION_DECL or not fn.is_definition():
            continue
        if not fn.location.file or \
                os.path.basename(fn.location.file.name) != main_file:
            continue
        toks = [t.spelling for t in fn.get_tokens()]
        _check_bounds(ci, fn, toks, emit)
        _check_leak(ci, fn, toks, emit)
        _check_balance(ci, fn, emit)
    return findings, [f"codec-checker: {os.path.basename(path)} analyzed"]


def _check_bounds(ci, fn, toks: List[str], emit) -> None:
    """Cursor derefs need a need()/end guard; raw buffer copies need a
    wr_reserve in the same function."""
    has_cursor = any(a == "->" and b == "p"
                     for a, b in zip(toks, toks[1:]))
    if has_cursor and "need" not in toks and "end" not in toks:
        emit("codec-bounds", fn.location.line, fn.location.column,
             f"`{fn.spelling}` advances/dereferences a reader cursor "
             f"(`->p`) with no `need()` call or `end` comparison in "
             f"scope — a torn buffer reads past the end")
    copies = {"memcpy", "memmove"} & set(toks)
    # the WRITER buffer specifically (`w->buf`), not stack locals that
    # happen to be named buf — those carry their own sizeof guards
    touches_writer = any(a == "->" and b == "buf"
                         for a, b in zip(toks, toks[1:]))
    if copies and touches_writer and "wr_reserve" not in toks \
            and fn.spelling != "wr_reserve":
        emit("codec-bounds", fn.location.line, fn.location.column,
             f"`{fn.spelling}` copies into the writer buffer without a "
             f"`wr_reserve` bound in the same function — the write can "
             f"land past the allocation")


def _check_leak(ci, fn, toks: List[str], emit) -> None:
    allocs = {"PyMem_Malloc", "malloc", "calloc"} & set(toks)
    if not allocs:
        return
    if "PyMem_Free" in toks or "free" in toks:
        return
    emit("codec-leak", fn.location.line, fn.location.column,
         f"`{fn.spelling}` allocates ({'/'.join(sorted(allocs))}) but "
         f"never frees in any path of this function — error returns "
         f"leak the buffer")


def _container_slots(v: int) -> Optional[int]:
    """fixmap/fixarray header byte → element emissions it declares."""
    if 0x80 <= v <= 0x8F:
        return 2 * (v & 0x0F)  # map: key+value per pair
    if 0x90 <= v <= 0x9F:
        return v & 0x0F
    return None


def _int_literal(ci, node) -> Optional[int]:
    for t in node.get_tokens():
        s = t.spelling
        try:
            return int(s, 0)
        except ValueError:
            continue
    return None


def _check_balance(ci, fn, emit) -> None:
    """Straight-line container emission balance: headers written with a
    literal fixmap/fixarray byte must be matched by exactly the declared
    number of value emissions. Functions with loops/switches (data-
    dependent emission counts) are out of scope by design."""
    loops = {ci.CursorKind.FOR_STMT, ci.CursorKind.WHILE_STMT,
             ci.CursorKind.DO_STMT, ci.CursorKind.SWITCH_STMT}
    calls = []
    for n in fn.walk_preorder():
        if n.kind in loops:
            return
        if n.kind == ci.CursorKind.CALL_EXPR and n.spelling in _EMITTERS:
            calls.append(n)
    if not calls:
        return
    seq = []  # ("container", slots, node) | ("value", node)
    for c in calls:
        if c.spelling == "wr_u8":
            args = list(c.get_arguments())
            v = _int_literal(ci, args[1]) if len(args) > 1 else None
            if v is None:
                return  # computed byte: not statically checkable
            slots = _container_slots(v)
            if slots is not None:
                seq.append(("container", slots, c))
            else:
                seq.append(("value", 0, c))
        elif c.spelling in _VALUE_EMITTERS:
            seq.append(("value", 0, c))
        else:
            return  # wr_be/wr_bytes build multi-call scalars: skip fn
    if not any(kind == "container" for kind, _s, _c in seq):
        return
    stack: List[int] = []

    def consume():
        while stack and stack[-1] == 0:
            stack.pop()
        if stack:
            stack[-1] -= 1

    for kind, slots, _node in seq:
        consume()
        if kind == "container":
            stack.append(slots)
    while stack and stack[-1] == 0:
        stack.pop()
    if stack:
        emit("codec-balance", fn.location.line, fn.location.column,
             f"`{fn.spelling}` emits a container header declaring more "
             f"elements than the function packs ({stack[-1]} slot(s) "
             f"unfilled) — decoders read the next record's bytes as "
             f"this container's tail")


def run_codec_checker(root: Optional[str] = None, cache: bool = True
                      ) -> Tuple[List[Finding], List[str]]:
    root = root or repo_root()
    src = os.path.join(root, "native", "fbtpu_codec.c")
    if not os.path.exists(src):
        return [], ["codec-checker: native/fbtpu_codec.c missing"]
    digest = _digest([open(src, encoding="utf-8",
                           errors="replace").read(), "codec-v1"])
    if cache:
        hit = _cache_load(root, "codec-checker", digest)
        if hit is not None:
            return [Finding(**d) for d in hit], ["codec-checker: cached"]
    findings, notes = check_codec_file(src, root)
    if not any("skipped" in n or "failed" in n for n in notes):
        _cache_store(root, "codec-checker", digest, findings)
    return findings, notes


# ---------------------------------------------------------------------
# layer 4: untrusted-bytes bounds checker (clang.cindex, both sources)
# ---------------------------------------------------------------------

#: helpers that perform (and signal) their own bounds checking — a
#: function that routes every read through one of these, checking its
#: failure return, is dominated by a guard even with no inline `end`
#: comparison of its own
_BOUNDS_HELPERS = frozenset({
    "need", "skip_obj", "read_array_hdr", "read_map_hdr",
    "read_str_hdr", "mp_skip_span", "mp_skip_n", "mp_str_hdr",
    "utf8_valid", "decode_obj", "jt_value",
})

_CMP_OPS = frozenset({"<", "<=", ">", ">=", "==", "!="})

#: 64-bit-wide integer type words: `ptr + n` with one of these can wrap
#: before a `<= end` comparison sees it (the overflow-prone idiom)
_WIDE_WORDS = ("long long", "int64_t", "Py_ssize_t", "ssize_t",
               "ptrdiff_t", "size_t", "uint64_t")


def _endish(s: str) -> bool:
    return s == "end" or s.endswith("_end")


def _lenish(s: str) -> bool:
    return ("len" in s or s in ("n", "size", "cap", "avail", "left",
                                "remaining", "count"))


def _collect_vars(ci, fn):
    """(byte-pointer names incl. params, byte-pointer PARAM names,
    64-bit-wide integer names) declared in/for this function."""
    byteptrs, params, wide = set(), set(), set()
    for n in fn.walk_preorder():
        if n.kind not in (ci.CursorKind.PARM_DECL,
                          ci.CursorKind.VAR_DECL):
            continue
        ts = n.type.spelling.replace("const", "").strip()
        if "*" in ts and any(b in ts for b in
                             ("uint8_t", "unsigned char", "char")):
            byteptrs.add(n.spelling)
            if n.kind == ci.CursorKind.PARM_DECL:
                params.add(n.spelling)
        elif "*" not in ts and any(w in ts for w in _WIDE_WORDS):
            wide.add(n.spelling)
    return byteptrs, params, wide


def _check_untrusted(ci, fn, emit) -> None:
    """Every load through a pointer derived from an untrusted byte
    buffer must be dominated by a bounds check; pointer+offset bounds
    comparisons must use the overflow-safe subtraction form when the
    offset is 64-bit."""
    byteptrs, params, wide = _collect_vars(ci, fn)
    if not params:
        return  # no untrusted-buffer parameter: out of scope
    spell = [t.spelling for t in fn.get_tokens()]
    lines = {i: t.location.line for i, t in enumerate(fn.get_tokens())}
    typeish = {"uint8_t", "char", "unsigned", "const", "void", "int8_t"}
    deref = False
    for i in range(len(spell) - 1):
        a, b = spell[i], spell[i + 1]
        if (a in byteptrs and b in ("[", "++")) \
                or (a == "++" and b in byteptrs):
            deref = True
            break
        # `*p` load — but not the `uint8_t *p` declaration form
        if a == "*" and b in byteptrs \
                and (i == 0 or spell[i - 1] not in typeish):
            deref = True
            break
    guarded = any(
        (a in _CMP_OPS and (_endish(b) or _lenish(b)))
        or ((_endish(a) or _lenish(a)) and b in _CMP_OPS)
        or (_endish(a) and b == "-") or (a == "-" and _endish(b))
        for a, b in zip(spell, spell[1:]))
    helper = any(s in _BOUNDS_HELPERS and s != fn.spelling
                 for s in spell)
    if deref and not (guarded or helper):
        emit("untrusted-bounds", fn.location.line, fn.location.column,
             f"`{fn.spelling}` dereferences a pointer derived from an "
             f"untrusted byte buffer with no bounds check in scope (no "
             f"`end` comparison, no length comparison, no bounds-"
             f"checking helper call) — hostile chunk bytes read past "
             f"the buffer")
    # overflow-prone idiom: `p + n <cmp> end` / `end <cmp> p + n` with a
    # 64-bit n — the addition wraps before the comparison runs; the
    # safe form is `n > end - p` (what need() does)
    for i in range(len(spell) - 4):
        a, op1, b, op2, c = spell[i:i + 5]
        wrap = ((a in byteptrs and op1 == "+" and b in wide
                 and op2 in _CMP_OPS and _endish(c))
                or (_endish(a) and op1 in _CMP_OPS and b in byteptrs
                    and op2 == "+" and c in wide))
        if wrap:
            emit("untrusted-bounds", lines.get(i, fn.location.line), 0,
                 f"`{fn.spelling}` bounds-checks with pointer+offset "
                 f"(`{a} {op1} {b} {op2} {c}`) where the offset is "
                 f"64-bit: the addition can wrap before the comparison "
                 f"— use the subtraction form `off > end - p` instead")


#: analysis-only shim for the SSE2 intrinsics the scanner kernels use:
#: libclang ships without its own resource headers here, and gcc's
#: emmintrin.h leans on gcc-only builtins clang cannot parse. The shim
#: pre-claims the gcc header's include guard and declares just enough
#: (the vector type + the 5 intrinsics in use) for a faithful AST —
#: the bounds analysis never looks inside the intrinsics anyway.
_SSE_SHIM = """
#define _EMMINTRIN_H_INCLUDED 1
#define _XMMINTRIN_H_INCLUDED 1
typedef long long __m128i __attribute__((vector_size(16)));
static inline __m128i _mm_set1_epi8(char a) { __m128i r = {0, 0}; (void)a; return r; }
static inline __m128i _mm_loadu_si128(const __m128i *p) { return *p; }
static inline __m128i _mm_cmpeq_epi8(__m128i a, __m128i b) { (void)b; return a; }
static inline __m128i _mm_or_si128(__m128i a, __m128i b) { (void)b; return a; }
static inline int _mm_movemask_epi8(__m128i a) { (void)a; return 0; }
"""


def check_bounds_file(path: str, root: Optional[str] = None,
                      lang: str = "c", extra_args: Sequence[str] = ()
                      ) -> Tuple[List[Finding], List[str]]:
    """Run the untrusted-bytes bounds checks over one source file
    (separated from the gate wrapper so fixture tests can feed
    known-bad snippets)."""
    root = root or repo_root()
    ci = _load_cindex()
    if ci is None:
        return [], ["bounds-checker: clang.cindex/libclang unavailable "
                    "— layer skipped"]
    args: List[str] = list(extra_args)
    unsaved = None
    if lang == "c++":
        args += ["-std=c++17"]
        shim = os.path.join(os.path.dirname(path), "_fbtpu_sse_shim.h")
        args += ["-include", shim]
        unsaved = [(shim, _SSE_SHIM)]
    inc = _py_include()
    if inc:
        args += ["-I", inc]
    gccinc = _gcc_builtin_include()
    if gccinc:
        args += ["-isystem", gccinc]
    try:
        tu = ci.Index.create().parse(path, args=args,
                                     unsaved_files=unsaved)
    except Exception as e:
        return [], [f"bounds-checker: parse failed for {path}: {e}"]
    errs = [d for d in tu.diagnostics
            if d.severity >= ci.Diagnostic.Error]
    if errs:
        return [], [f"bounds-checker: {len(errs)} parse errors in "
                    f"{path} (first: {errs[0]}) — layer skipped"]
    rel = _rel(root, path) if os.path.isabs(path) else path
    try:
        with open(path, encoding="utf-8", errors="replace") as fh:
            lines = fh.read().splitlines()
    except OSError:
        lines = []
    findings: List[Finding] = []

    def emit(rule: str, line: int, col: int, msg: str) -> None:
        if not _c_allowed(lines, rule, line):
            findings.append(Finding(rel, line, col, rule, msg, "error"))

    main_file = os.path.basename(path)
    # preorder walk, not get_children(): the C++ plane wraps its entry
    # points in extern "C" linkage blocks the top level doesn't show
    for fn in tu.cursor.walk_preorder():
        if fn.kind not in (ci.CursorKind.FUNCTION_DECL,
                           ci.CursorKind.CXX_METHOD) \
                or not fn.is_definition():
            continue
        if not fn.location.file or \
                os.path.basename(fn.location.file.name) != main_file:
            continue
        _check_untrusted(ci, fn, emit)
    return findings, [f"bounds-checker: {os.path.basename(path)} "
                      f"analyzed"]


def run_bounds_checker(root: Optional[str] = None, cache: bool = True
                       ) -> Tuple[List[Finding], List[str]]:
    root = root or repo_root()
    findings: List[Finding] = []
    notes: List[str] = []
    for src, lang in native_sources(root):
        digest = _digest([open(src, encoding="utf-8",
                               errors="replace").read(), lang,
                          "bounds-v1"])
        name = "bounds-" + os.path.basename(src)
        if cache:
            hit = _cache_load(root, name, digest)
            if hit is not None:
                findings.extend(Finding(**d) for d in hit)
                notes.append(f"bounds-checker: "
                             f"{os.path.basename(src)} (cached)")
                continue
        got, ns = check_bounds_file(src, root, lang)
        if not any("skipped" in n or "failed" in n for n in ns):
            _cache_store(root, name, digest, got)
        findings.extend(got)
        notes.extend(ns)
    return findings, notes


# ---------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------

def run_native_gate(root: Optional[str] = None, cache: bool = True
                    ) -> Tuple[List[Finding], List[str]]:
    """All three layers; findings sorted, notes say what actually ran
    (a missing tool is a visible note, never a silent green)."""
    root = root or repo_root()
    findings: List[Finding] = []
    notes: List[str] = []
    for runner in (run_clang_tidy, run_gcc_analyzer, run_codec_checker,
                   run_bounds_checker):
        got, ns = runner(root, cache=cache)
        findings.extend(got)
        notes.extend(ns)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, notes
