"""Flush-path I/O deadline rule.

``await-no-deadline``: a raw socket/upstream ``await`` inside an output
flush path with no deadline. A hung peer then parks the flush coroutine
— and its task-map slot — forever: exactly the head-of-line failure the
fbtpu-guard plane (core/guard.py) exists to contain. The engine-level
flush deadline is the backstop, not an excuse: a local bound fails the
ONE sick await with a ``TimeoutError`` the plugin's own error handling
turns into a clean RETRY, instead of soft-killing the whole attempt.

Scope (deliberately lexical — no call-graph chasing): ``async`` methods
of classes that look like output plugins (a base mentioning
``OutputPlugin``, or a class name ending in ``Output``), plus
module-level ``async def flush``/``_flush*`` functions, on data-path
modules. Flagged awaits:

- stream/socket primitives — ``drain``, ``read``, ``readexactly``,
  ``readuntil``, ``readline``, ``sendall``, ``recv``, ``getaddrinfo`` —
  awaited directly (wrap in ``asyncio.wait_for(...)`` or
  ``guard.io_deadline(...)``);
- ``open_connection(...)`` without a ``timeout=`` argument (the helper
  bounds the whole multi-address dial when one is passed).

Helper calls (``self._connect()``) are not flagged — the rule fires
where the raw primitive is awaited, which is also where the wrapper
belongs. Suppress deliberate unbounded awaits (a long-poll reader, a
server-push loop) with ``# fbtpu-lint: allow(await-no-deadline)`` and a
justification.
"""

from __future__ import annotations

import ast
from typing import List, Set

from . import Finding, Module, Rule
from .silent import DATA_PATH_PREFIXES

__all__ = ["AwaitNoDeadlineRule"]

#: Raw awaitable I/O primitives (terminal callee names).
IO_CALLS: Set[str] = {
    "drain", "read", "readexactly", "readuntil", "readline",
    "sendall", "recv", "getaddrinfo",
}

#: Dial helpers that take (and internally honor) a ``timeout=`` kwarg.
CONNECT_CALLS: Set[str] = {"open_connection"}

#: Deadline wrappers: an await of one of these is already bounded.
WRAPPERS: Set[str] = {"wait_for", "io_deadline"}


def _callee_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _has_timeout_arg(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "timeout":
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is None)
    return False


def _looks_like_output_class(cls: ast.ClassDef) -> bool:
    if cls.name.endswith("Output"):
        return True
    for base in cls.bases:
        try:
            if "OutputPlugin" in ast.unparse(base):
                return True
        except Exception:
            continue
    return False


class AwaitNoDeadlineRule(Rule):
    name = "await-no-deadline"
    description = ("raw socket/upstream await in an output flush path "
                   "with no deadline — a hung peer parks the flush "
                   "(and its task-map slot) forever")
    severity = "warning"

    def check(self, module: Module) -> List[Finding]:
        if not any(p in module.path for p in DATA_PATH_PREFIXES):
            return []
        out: List[Finding] = []
        seen: Set[int] = set()  # nested class/function double-walk guard
        for node in ast.walk(module.tree):
            scan = None
            if isinstance(node, ast.ClassDef) and \
                    _looks_like_output_class(node):
                scan = node
            elif isinstance(node, ast.AsyncFunctionDef) and (
                    node.name == "flush"
                    or node.name.startswith("_flush")):
                scan = node
            if scan is None:
                continue
            for fn in ast.walk(scan):
                if not isinstance(fn, ast.AsyncFunctionDef) or \
                        id(fn) in seen:
                    continue
                seen.add(id(fn))
                out.extend(self._scan_function(module, fn))
        return out

    def _scan_function(self, module: Module,
                       fn: ast.AsyncFunctionDef) -> List[Finding]:
        out: List[Finding] = []
        # walk WITHOUT descending into nested defs: a nested async def
        # is scanned as its own function (never double-reported), a
        # nested sync def/lambda has no awaits
        stack = list(fn.body)
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(sub))
            if not isinstance(sub, ast.Await) or \
                    not isinstance(sub.value, ast.Call):
                continue
            name = _callee_name(sub.value)
            if name in WRAPPERS:
                continue  # the wrapper IS the deadline
            msg = None
            if name in IO_CALLS:
                msg = (f"`await {name}(...)` in a flush path has no "
                       f"deadline — a hung peer parks this flush (and "
                       f"its task-map slot) until the guard soft-kill; "
                       f"wrap it in `asyncio.wait_for(...)` or "
                       f"`guard.io_deadline(...)`")
            elif name in CONNECT_CALLS and \
                    not _has_timeout_arg(sub.value):
                msg = (f"`await {name}(...)` without `timeout=` — the "
                       f"dial is unbounded; pass a connect timeout")
            if msg is None:
                continue
            f = self.finding(module, sub, msg)
            if f is not None:
                out.append(f)
        return out
