"""qos-unmetered-ingest rule.

fbtpu-qos (core/qos.py) meters every ingest entry point against the
tenant token bucket: ``Engine.input_log_append`` and
``input_event_append`` call ``self.qos.admit(...)`` before any work.
The whole multi-tenant isolation contract rests on that invariant — an
ingest path added later that appends straight into a chunk pool would
silently bypass quotas, and nothing at runtime would notice (the
records flow fine; only the flooding tenant's neighbors pay).

``qos-unmetered-ingest`` makes the invariant machine-checked: in
``fluentbit_tpu/core/`` modules, every PUBLIC function from which a
``<x>.pool.append(...)`` call is reachable (directly or through
same-module helpers — the engine's ``_log_append_decoded`` /
``_ingest_raw`` shape) must also reach a ``*.qos.admit(...)`` call.
Private helpers are not flagged on their own: they are only reachable
through an admitted entry point, which is exactly what the closure
check verifies. Reachability is a same-module call-name closure (the
same intentionally-lexical altitude as the guarded-by rule): calls are
matched by simple name, so ``self._helper()`` and ``helper()`` both
resolve to local definitions of that name.

Suppress with ``# fbtpu-lint: allow(qos-unmetered-ingest)`` on the
entry point's ``def`` line (or the offending append line) with a
justification — e.g. an internal replay path whose records were
already admitted once.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from . import Finding, Module, Rule

__all__ = ["UnmeteredIngestRule"]

#: Only engine-level modules host ingest entry points; plugins ingest
#: through Engine.input_*_append, which is already metered.
SCOPE = "fluentbit_tpu/core/"


def _chain_names(node) -> Set[str]:
    out: Set[str] = set()
    while isinstance(node, ast.Attribute):
        out.add(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        out.add(node.id)
    return out


def _is_pool_append(call: ast.Call) -> bool:
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "append"
            and isinstance(f.value, ast.Attribute)
            and f.value.attr == "pool")


def _is_admit(call: ast.Call) -> bool:
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "admit"
            and "qos" in _chain_names(f.value))


class _FnInfo:
    __slots__ = ("node", "appends", "admits", "calls")

    def __init__(self, node):
        self.node = node
        self.appends: List[ast.Call] = []
        self.admits = False
        self.calls: Set[str] = set()


def _analyze(fn) -> _FnInfo:
    """Collect one function's pool appends, admit calls, and the simple
    names it calls. Nested closures count toward the enclosing
    function (the engine schedules its ``_create``-style closures from
    the same logical path)."""
    info = _FnInfo(fn)
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if _is_pool_append(node):
            info.appends.append(node)
        elif _is_admit(node):
            info.admits = True
        f = node.func
        if isinstance(f, ast.Name):
            info.calls.add(f.id)
        elif isinstance(f, ast.Attribute):
            info.calls.add(f.attr)
    return info


class UnmeteredIngestRule(Rule):
    name = "qos-unmetered-ingest"
    description = ("public ingest entry point reaches a chunk-pool "
                   "append without passing tenant admission "
                   "(qos.admit) — quotas are bypassed")

    def check(self, module: Module) -> List[Finding]:
        if SCOPE not in module.path:
            return []
        by_name: Dict[str, List[_FnInfo]] = {}
        infos: List[_FnInfo] = []
        nested: Set[ast.AST] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = _analyze(node)
                infos.append(info)
                by_name.setdefault(node.name, []).append(info)
                # closures stay in the call graph (their appends count
                # against the enclosing caller via closure()) but are
                # never entry points themselves: the admit call lives
                # in the public function that contains them
                for sub in ast.walk(node):
                    if sub is not node and isinstance(
                            sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        nested.add(sub)

        def closure(start: _FnInfo) -> Tuple[List[ast.Call], bool]:
            """(reachable pool appends, admit reachable) over the
            same-module call-name graph."""
            appends: List[ast.Call] = list(start.appends)
            admits = start.admits
            seen: Set[str] = {start.node.name}
            frontier = set(start.calls)
            while frontier:
                name = frontier.pop()
                if name in seen:
                    continue
                seen.add(name)
                for callee in by_name.get(name, ()):
                    appends.extend(callee.appends)
                    admits = admits or callee.admits
                    frontier.update(callee.calls)
            return appends, admits

        out: List[Finding] = []
        for info in infos:
            name = info.node.name
            if name.startswith("_"):
                continue  # helpers are covered via their public callers
            if info.node in nested:
                continue  # closures are reached via their container
            appends, admits = closure(info)
            if not appends or admits:
                continue
            f = self.finding(
                module, info.node,
                f"ingest entry point {name!r} reaches a chunk-pool "
                f"append (line "
                f"{', '.join(str(a.lineno) for a in appends[:3])}) "
                f"without a tenant-admission qos.admit(...) call — "
                f"every ingest path must be metered (core/qos.py)",
                extra_lines=tuple(a.lineno for a in appends))
            if f is not None:
                out.append(f)
        return out
