"""Batch-exactness dataflow rules for ``FilterPlugin.process_batch``.

The batched fast path (PERF.md) carries delicate contracts the type
system cannot see: the engine treats ``return None`` / any raise from
``process_batch`` as a *decline* and re-runs the chain per-record from
the declining filter onward (``engine._ingest_raw`` + the decoded-tail
continuation). That rerun is bit-exact ONLY when the declining hook has
not yet committed side effects — a counter already incremented or a
record already re-emitted through a hidden emitter fires a second time
on the rerun. These rules encode the contract as an interprocedural
forward dataflow over every ``process_batch`` implementation and the
``self.<method>()`` calls reachable from it:

- ``batch-decline-after-commit``: an explicit decline site (``return
  None`` / bare ``return`` / ``raise FallbackError``) reachable after a
  committed side effect (metric ``inc``/``observe``, emitter
  ``add_record``/``add_event``, flux-state
  ``absorb_batch``/``absorb_events`` — the fbtpu-flux surface: an
  absorbed batch is observable in every later window emission, so a
  rerun absorbs the same records twice). The decoded-tail rerun
  replays the commit — counters double-count, emits duplicate,
  windows double-aggregate.
- ``batch-commit-replay``: an emitter append (``add_record``/
  ``add_event``) after an earlier commit with no enclosing
  ``try``/``except``. The call raising IS an implicit decline, with the
  same replay consequence; guard it and degrade like backpressure.
- ``batch-stateful-unmarked``: ``process_batch`` commits side effects
  but the class does not declare ``stateful_batch = True`` — the engine
  keys the decoded-tail continuation off that attribute, so an unmarked
  stateful hook makes a downstream decline restart the WHOLE chain and
  replay everything this hook committed.
- ``batch-no-fallback``: a class advertising ``can_process_batch`` whose
  ``process_batch`` has no reachable decline site at all — configs
  outside the fast set then have no bit-exact per-record escape.
- ``batch-unordered-emit``: a ``for`` loop feeding an emit (or building
  the output buffer) from an unordered iterable (``set``/``frozenset``
  constructors or literals, set comprehensions, ``np.unique`` — which
  sorts). Span-gather re-emits must preserve FIRST-SEEN record order to
  stay byte-exact with the per-record path's pending-dict insertion
  order.

The dataflow is a may-analysis: branches merge with OR, loop bodies run
a two-iteration fixpoint (so a commit on iteration N is visible to the
same statement on iteration N+1 — the emit-loop replay case), and
``self.<method>()`` calls inline the callee's effects. A method called
in *tail position* (``return self._impl(chunk)``) contributes its
decline sites to the caller; a statement call contributes only its
commits.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import Finding, Module, Rule

__all__ = ["BatchExactnessRules"]

#: metric-commit terminals: observable counter/histogram updates
_METRIC_COMMITS = {"inc", "observe"}
#: emitter-append terminals: records re-entering the pipeline
_EMIT_COMMITS = {"add_record", "add_event"}
#: flux-state commit terminals (fbtpu-flux): absorbing a batch into
#: per-tenant sketch/window state is observable in every later window
#: emission and metric export — a decline after it makes the decoded
#: rerun absorb the same records twice (double-counted windows,
#: inflated sketches). Same contract as the metric commits, new surface.
_FLUX_COMMITS = {"absorb_batch", "absorb_events"}
#: unordered-iterable constructor terminals (np.unique SORTS, which is
#: just as order-destroying as a set walk)
_UNORDERED = {"set", "frozenset", "unique"}


def _terminal(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _is_none(expr: Optional[ast.AST]) -> bool:
    return expr is None or (isinstance(expr, ast.Constant)
                            and expr.value is None)


def _self_method(call: ast.Call) -> Optional[str]:
    """``self.<name>(...)`` → name (the interprocedural edge)."""
    f = call.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id == "self":
        return f.attr
    return None


def _receiver_names(call: ast.Call) -> Set[str]:
    out: Set[str] = set()
    f = call.func
    if isinstance(f, ast.Attribute):
        for node in ast.walk(f.value):
            if isinstance(node, ast.Name):
                out.add(node.id)
            elif isinstance(node, ast.Attribute):
                out.add(node.attr)
    return out


def _calls_in_order(node: ast.AST) -> List[ast.Call]:
    """Call expressions in source order (good enough for left-to-right
    evaluation within one statement)."""
    calls = [n for n in ast.walk(node) if isinstance(n, ast.Call)]
    calls.sort(key=lambda c: (c.lineno, c.col_offset))
    return calls


class _State:
    """May-have-committed lattice element."""

    __slots__ = ("committed",)

    def __init__(self, committed: bool = False):
        self.committed = committed

    def copy(self) -> "_State":
        return _State(self.committed)


class _ClassScan:
    """One class's process_batch analyzed with its reachable methods."""

    def __init__(self, rule: "BatchExactnessRules", module: Module,
                 cls: ast.ClassDef):
        self.rule = rule
        self.module = module
        self.cls = cls
        self.methods: Dict[str, ast.FunctionDef] = {}
        self.stateful = False
        self.has_can = False
        for node in cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[node.name] = node
                if node.name == "can_process_batch":
                    self.has_can = True
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) \
                            and tgt.id == "stateful_batch" \
                            and isinstance(node.value, ast.Constant) \
                            and node.value.value is True:
                        self.stateful = True
        self.findings: List[Finding] = []
        self.any_commit = False
        self.any_decline = False
        self._inlining: Set[Tuple[str, bool]] = set()

    # -- reporting ----------------------------------------------------

    def _emit(self, node: ast.AST, rule: str, message: str,
              severity: str = "error") -> None:
        line = getattr(node, "lineno", 1)
        if not self.module.allowed(rule, line):
            self.findings.append(Finding(
                self.module.path, line, getattr(node, "col_offset", 0),
                rule, message, severity))

    # -- the dataflow -------------------------------------------------

    def run(self) -> List[Finding]:
        fn = self.methods.get("process_batch")
        if fn is None:
            return []
        self._stmts(fn.body, _State(), guarded=False, tail=True, depth=0)
        if self.has_can and not self.any_decline:
            self._emit(fn, "batch-no-fallback",
                       f"`{self.cls.name}.process_batch` advertises "
                       f"can_process_batch but has no reachable decline "
                       f"site (`return None` / FallbackError): configs "
                       f"outside the fast set have no bit-exact "
                       f"per-record escape")
        if self.any_commit and not self.stateful:
            self._emit(fn, "batch-stateful-unmarked",
                       f"`{self.cls.name}.process_batch` commits side "
                       f"effects (counter incs / emitter appends) but "
                       f"the class does not declare `stateful_batch = "
                       f"True` — a downstream decline then restarts the "
                       f"whole raw chain and replays them")
        return self.findings

    def _decline(self, node: ast.AST, state: _State, what: str) -> None:
        self.any_decline = True
        if state.committed:
            self._emit(node, "batch-decline-after-commit",
                       f"{what} after a committed side effect: the "
                       f"engine's decoded-tail rerun re-executes this "
                       f"filter per-record and replays the commit "
                       f"(double-counted counters / duplicate emits) — "
                       f"decline BEFORE committing, or guard the "
                       f"committing call and succeed")

    def _inline(self, name: str, state: _State, guarded: bool,
                tail: bool, depth: int) -> None:
        callee = self.methods.get(name)
        if callee is None or depth >= 6:
            return
        key = (name, tail)
        if key in self._inlining:
            return
        self._inlining.add(key)
        try:
            self._stmts(callee.body, state, guarded, tail, depth + 1)
        finally:
            self._inlining.discard(key)

    def _calls(self, node: ast.AST, state: _State, guarded: bool,
               depth: int) -> None:
        """Effect pass over every call inside one statement/expression."""
        for call in _calls_in_order(node):
            t = _terminal(call.func)
            m = _self_method(call)
            if m is not None and m in self.methods:
                # statement-position inline: commits propagate, the
                # callee's returns are the CALLER's values (not declines)
                self._inline(m, state, guarded, tail=False, depth=depth)
                continue
            if t in _EMIT_COMMITS:
                if state.committed and not guarded:
                    self._emit(call, "batch-commit-replay",
                               f"emitter `.{t}()` after an earlier "
                               f"committed effect with no enclosing "
                               f"try/except: a raise here declines the "
                               f"batch and the per-record rerun replays "
                               f"the earlier commit — guard it and "
                               f"degrade like backpressure")
                state.committed = True
                self.any_commit = True
            elif t in _METRIC_COMMITS or t in _FLUX_COMMITS:
                # flux absorbs are idempotent-or-guarded by the same
                # rule metric incs are: committed state the decoded
                # rerun would replay
                state.committed = True
                self.any_commit = True
            elif t == "set" and isinstance(call.func, ast.Attribute) \
                    and "metric" in " ".join(_receiver_names(call)):
                # gauge .set() on a metric receiver commits too
                state.committed = True
                self.any_commit = True

    def _check_loop_order(self, loop: ast.For) -> None:
        unordered = None
        for sub in ast.walk(loop.iter):
            if isinstance(sub, (ast.Set, ast.SetComp)):
                unordered = "a set"
                break
            if isinstance(sub, ast.Call) \
                    and _terminal(sub.func) in _UNORDERED:
                unordered = f"`{_terminal(sub.func)}(...)`"
                break
        if unordered is None:
            return
        def _builds_output(aug: ast.AugAssign) -> bool:
            # `out += span` style concatenation onto the chunk's output
            # buffer is order-sensitive; an order-independent reduction
            # (`total += counts[tag]`) is not
            if not isinstance(aug.op, ast.Add):
                return False
            t = _terminal(aug.target)
            return t is not None and any(
                frag in t.lower() for frag in ("out", "buf", "payload"))

        feeds_emit = any(
            isinstance(n, ast.Call) and _terminal(n.func) in _EMIT_COMMITS
            for n in ast.walk(loop)
        ) or any(isinstance(n, ast.AugAssign) and _builds_output(n)
                 for n in ast.walk(loop))
        if feeds_emit:
            self._emit(loop, "batch-unordered-emit",
                       f"re-emit loop iterates {unordered}: span-gather "
                       f"re-emits must preserve first-seen record order "
                       f"to stay byte-exact with the per-record path — "
                       f"key groups by first contributing index "
                       f"(insertion-ordered dict / sorted-by-first)")

    def _stmts(self, stmts: List[ast.stmt], state: _State, guarded: bool,
               tail: bool, depth: int) -> None:
        for stmt in stmts:
            self._stmt(stmt, state, guarded, tail, depth)

    def _stmt(self, stmt: ast.stmt, state: _State, guarded: bool,
              tail: bool, depth: int) -> None:
        if isinstance(stmt, ast.Return):
            if _is_none(stmt.value):
                if tail:
                    self._decline(stmt, state, "`return None`")
                return
            call = stmt.value if isinstance(stmt.value, ast.Call) else None
            m = _self_method(call) if call is not None else None
            if m is not None and m in self.methods and tail:
                # tail call: inline ONCE, with decline semantics (the
                # callee's `return None` IS a decline of process_batch).
                # Only the call's arguments get the plain effect pass —
                # running _calls on the whole expression would inline
                # the callee a second time at statement position and
                # pollute `state` with its commits BEFORE the tail walk,
                # falsely flagging decline-before-commit callees.
                for arg in list(call.args) + [k.value for k in
                                              call.keywords]:
                    self._calls(arg, state, guarded, depth)
                self._inline(m, state, guarded, tail=True, depth=depth)
            else:
                self._calls(stmt.value, state, guarded, depth)
            return
        if isinstance(stmt, ast.Raise):
            names = {n.id for n in ast.walk(stmt) if isinstance(n, ast.Name)}
            names |= {n.attr for n in ast.walk(stmt)
                      if isinstance(n, ast.Attribute)}
            if any("FallbackError" in n for n in names):
                self._decline(stmt, state, "`raise FallbackError`")
            return
        if isinstance(stmt, ast.If):
            self._calls(stmt.test, state, guarded, depth)
            s_then, s_else = state.copy(), state.copy()
            self._stmts(stmt.body, s_then, guarded, tail, depth)
            self._stmts(stmt.orelse, s_else, guarded, tail, depth)
            state.committed = s_then.committed or s_else.committed
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            if isinstance(stmt, ast.For):
                self._check_loop_order(stmt)
            self._calls(stmt.iter, state, guarded, depth)
            # two-iteration fixpoint: a commit on iteration N reaches
            # the same statement on iteration N+1
            body_state = state.copy()
            self._stmts(stmt.body, body_state, guarded, tail, depth)
            if body_state.committed:
                self._stmts(stmt.body, body_state, guarded, tail, depth)
            self._stmts(stmt.orelse, body_state, guarded, tail, depth)
            state.committed = state.committed or body_state.committed
            return
        if isinstance(stmt, ast.While):
            self._calls(stmt.test, state, guarded, depth)
            body_state = state.copy()
            self._stmts(stmt.body, body_state, guarded, tail, depth)
            if body_state.committed:
                self._stmts(stmt.body, body_state, guarded, tail, depth)
            state.committed = state.committed or body_state.committed
            return
        if isinstance(stmt, ast.Try):
            # any handler makes body raises recoverable at this level
            body_guarded = guarded or bool(stmt.handlers)
            body_state = state.copy()
            self._stmts(stmt.body, body_state, body_guarded, tail, depth)
            merged = body_state.committed
            for handler in stmt.handlers:
                h_state = body_state.copy()
                self._stmts(handler.body, h_state, guarded, tail, depth)
                merged = merged or h_state.committed
            state.committed = state.committed or merged
            self._stmts(stmt.orelse, state, guarded, tail, depth)
            self._stmts(stmt.finalbody, state, guarded, tail, depth)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._calls(item.context_expr, state, guarded, depth)
            self._stmts(stmt.body, state, guarded, tail, depth)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs run later; their calls are not ours
        # plain statement: effect pass over its expressions
        self._calls(stmt, state, guarded, depth)


class BatchExactnessRules(Rule):
    name = "batch-exactness"  # umbrella; findings carry precise rules
    description = ("process_batch contract dataflow: decline-after-"
                   "commit, unguarded emit replay, missing fallback, "
                   "unmarked stateful hooks, order-destroying re-emits")

    RULE_NAMES = ("batch-decline-after-commit", "batch-commit-replay",
                  "batch-stateful-unmarked", "batch-no-fallback",
                  "batch-unordered-emit")

    def check(self, module: Module) -> List[Finding]:
        if "process_batch" not in module.source:
            return []
        out: List[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(_ClassScan(self, module, node).run())
        out.sort(key=lambda f: (f.line, f.col))
        return out
