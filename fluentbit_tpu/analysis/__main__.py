"""CLI: ``python -m fluentbit_tpu.analysis [options] [paths...]``.

Exit status 0 = clean, 1 = findings (or unparseable files), 2 = usage
error. With no paths, lints the installed ``fluentbit_tpu`` package
tree — the invocation ``tests/test_lint.py`` gates every PR with.

Modes:

- (default)           Python rule packs over the tree/paths
- ``--native``        native C gate only (clang-tidy profile +
                      gcc -fanalyzer + codec invariant checker)
- ``--all``           both — the full PR gate
- ``--json``          machine-readable findings (incl. severity)
- ``--baseline F``    subtract the findings recorded in F (CI diffs
                      new findings instead of failing on legacy debt);
                      exit 0 when nothing NEW
- ``--write-baseline F``  snapshot current findings into F and exit 0

Baseline entries match on (path, rule, message) — line-insensitive, so
reformatting never churns the file. Every suppression in code uses
``# fbtpu-lint: allow(<rule>)`` (``/* fbtpu-lint: allow(...) */`` in C)
with an inline justification; the baseline is for inherited debt, the
suppression for reviewed exceptions.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import RULES, Finding, lint_paths


def _load_baseline(path: str):
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    keys = set()
    for d in data.get("findings", []):
        keys.add((d["path"], d["rule"], d["message"]))
    return keys


def _write_baseline(path: str, findings) -> None:
    payload = {
        "version": 1,
        "findings": [
            {"path": f.path, "rule": f.rule, "message": f.message,
             "severity": f.severity}
            for f in findings
        ],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m fluentbit_tpu.analysis",
        description="fbtpu-lint: concurrency + JAX-purity + batch-"
                    "exactness + silent-failure analysis, and the "
                    "native C static-analysis gate (see ANALYSIS.md)")
    ap.add_argument("paths", nargs="*",
                    help="files or directories (default: the "
                         "fluentbit_tpu package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings")
    ap.add_argument("--all", action="store_true", dest="run_all",
                    help="Python rules AND the native C gate")
    ap.add_argument("--native", action="store_true", dest="native_only",
                    help="native C gate only")
    ap.add_argument("--no-cache", action="store_true",
                    help="ignore the native gate's result cache")
    ap.add_argument("--baseline", metavar="FILE",
                    help="subtract findings recorded in FILE; exit 0 "
                         "when nothing new")
    ap.add_argument("--write-baseline", metavar="FILE",
                    help="snapshot current findings into FILE, exit 0")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule set and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        from .batch import BatchExactnessRules
        from .native_gate import NATIVE_RULES

        for r in RULES:
            if isinstance(r, BatchExactnessRules):
                for n in r.RULE_NAMES:
                    print(f"{n}: (batch-exactness pack) {r.description}")
            elif r.name == "jax-purity":
                for n in ("jax-host-sync", "jax-side-effect",
                          "jax-retrace"):
                    print(f"{n}: (jax-purity pack) {r.description}")
            else:
                print(f"{r.name}: {r.description}")
        for n in NATIVE_RULES:
            print(f"{n}: native C gate (analysis.native_gate; "
                  f"--all/--native)")
        return 0

    findings: list = []
    notes: list = []

    if not args.native_only:
        paths = args.paths or [
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ]
        try:
            findings.extend(lint_paths(paths))
        except FileNotFoundError as e:
            print(e, file=sys.stderr)
            return 2

    if args.run_all or args.native_only:
        from .native_gate import run_native_gate

        nf, notes = run_native_gate(cache=not args.no_cache)
        findings.extend(nf)

    if args.write_baseline:
        _write_baseline(args.write_baseline, findings)
        print(f"fbtpu-lint: baseline of {len(findings)} finding(s) "
              f"written to {args.write_baseline}")
        return 0

    baselined = 0
    if args.baseline:
        try:
            keys = _load_baseline(args.baseline)
        except (OSError, ValueError, KeyError) as e:
            print(f"fbtpu-lint: unreadable baseline "
                  f"{args.baseline!r}: {e}", file=sys.stderr)
            return 2
        kept = []
        for f in findings:
            if f.baseline_key() in keys:
                baselined += 1
            else:
                kept.append(f)
        findings = kept

    if args.as_json:
        if args.run_all or args.native_only:
            # the native gate's notes travel with the findings: a
            # machine consumer must be able to tell "analyzed clean"
            # from "every layer skipped" (never a silent green)
            print(json.dumps(
                {"findings": [f.__dict__ for f in findings],
                 "notes": notes}, indent=2))
        else:
            print(json.dumps([f.__dict__ for f in findings], indent=2))
    else:
        for n in notes:
            print(f"# {n}")
        for f in findings:
            print(f.render())
        n = len(findings)
        tail = f" ({baselined} baselined)" if baselined else ""
        print(f"fbtpu-lint: {n} finding{'s' if n != 1 else ''}{tail}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
