"""CLI: ``python -m fluentbit_tpu.analysis [paths...]``.

Exit status 0 = clean, 1 = findings (or unparseable files). With no
paths, lints the installed ``fluentbit_tpu`` package tree — the same
invocation ``tests/test_lint.py`` gates every PR with.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import RULES, lint_paths


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m fluentbit_tpu.analysis",
        description="fbtpu-lint: concurrency + JAX-purity + "
                    "silent-failure analysis (see ANALYSIS.md)")
    ap.add_argument("paths", nargs="*",
                    help="files or directories (default: the "
                         "fluentbit_tpu package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule set and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in RULES:
            print(f"{r.name}: {r.description}")
        return 0

    paths = args.paths or [
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ]
    try:
        findings = lint_paths(paths)
    except FileNotFoundError as e:
        print(e, file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps([f.__dict__ for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        n = len(findings)
        print(f"fbtpu-lint: {n} finding{'s' if n != 1 else ''} in "
              f"{', '.join(paths)}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
