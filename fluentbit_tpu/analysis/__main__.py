"""CLI: ``python -m fluentbit_tpu.analysis [options] [paths...]``.

Exit status 0 = clean, 1 = findings (or unparseable files), 2 = usage
error. With no paths, lints the installed ``fluentbit_tpu`` package
tree — the invocation ``tests/test_lint.py`` gates every PR with.

Modes:

- (default)           Python rule packs over the tree/paths
- ``--native``        native C gate only (clang-tidy profile +
                      gcc -fanalyzer + codec invariant checker)
- ``--all``           both — the full PR gate, plus the fbtpu-xray
                      launch/transfer budget comparison against the
                      committed ``analysis/launch_budget.json``
- ``--changed``       git-diff-scoped run: Python rules over the .py
                      files changed vs HEAD only (fast pre-commit)
- ``--json``          machine-readable findings (incl. severity)
- ``--graph MODE``    emit the fbtpu-xray per-tag device launch graph
                      (``json`` with the budget snapshot + regression
                      diff, or ``dot`` for graphviz) and exit
- ``--baseline F``    subtract the findings recorded in F (CI diffs
                      new findings instead of failing on legacy debt);
                      exit 0 when nothing NEW
- ``--write-baseline F``  snapshot current findings into F and exit 0
- ``--write-budget``  regenerate ``analysis/launch_budget.json`` (the
                      launch-graph findings baseline + the gated
                      budget snapshot) and exit 0
- ``--write-copy-budget``  regenerate ``analysis/copy_budget.json``
                      (the fbtpu-memscope findings baseline + the
                      host copy census + the eliminated-pass ledger)
                      and exit 0
- ``--write-fusion-plan``  regenerate ``analysis/fusion_plan.json``
                      (the fbtpu-fuseplan findings baseline + the
                      gated boundary-verdict / planned-program
                      snapshot) and exit 0
- ``--write-baselines``  refresh ALL committed baselines (launch
                      budget, lock baseline, copy budget, fusion
                      plan) in one atomic pass and exit 0 — the one
                      command to run after deliberately changing any
                      gated plane

Baseline entries match on (path, rule, message) — line-insensitive, so
reformatting never churns the file. Every suppression in code uses
``# fbtpu-lint: allow(<rule>)`` (``/* fbtpu-lint: allow(...) */`` in C)
with an inline justification; the baseline is for inherited debt, the
suppression for reviewed exceptions.

``analysis/launch_budget.json`` is ALSO an implicit baseline: when no
``--baseline`` is given, its recorded launch-graph findings (today's
multi-launch reality — ROADMAP item 1's debt) are subtracted
automatically, so the default invocation stays a zero-findings gate
while the debt remains visible, diffable, and gated (see ANALYSIS.md
"fbtpu-xray"). ``analysis/lock_baseline.json``,
``analysis/copy_budget.json`` and ``analysis/fusion_plan.json`` play
the same role for the locksmith, memscope and fuseplan packs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import RULES, Finding, lint_paths


def _canon(path: str) -> str:
    """Package-relative form of a finding path, so baseline keys match
    whether the CLI was handed absolute or relative paths."""
    path = path.replace(os.sep, "/")
    idx = path.find("fluentbit_tpu/")
    return path[idx:] if idx >= 0 else path


def _load_baseline(path: str):
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    keys = set()
    for d in data.get("findings", []):
        keys.add((_canon(d["path"]), d["rule"], d["message"]))
    return keys


def _subtract(findings, keys):
    kept, hit = [], 0
    for f in findings:
        if (_canon(f.path), f.rule, f.message) in keys:
            hit += 1
        else:
            kept.append(f)
    return kept, hit


def _changed_paths():
    """The .py files changed vs HEAD (staged + unstaged), for the fast
    pre-commit invocation. Deleted files drop out; a non-git tree is a
    usage error (the caller asked for a diff that cannot exist)."""
    import subprocess

    pkg_parent = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    proc = subprocess.run(
        ["git", "diff", "--name-only", "HEAD", "--", "*.py"],
        capture_output=True, text=True, cwd=pkg_parent)
    if proc.returncode != 0:
        raise RuntimeError(proc.stderr.strip()
                           or "git diff failed (not a git tree?)")
    out = []
    for rel in proc.stdout.splitlines():
        p = os.path.join(pkg_parent, rel.strip())
        if rel.strip() and os.path.isfile(p):
            out.append(p)
    return out


def _budget_findings():
    """Compare the live launch graph against the committed budget file:
    growth in launches-per-segment / un-donated bytes / scatter passes
    (or an unbaselined device chain) is an error finding; improvements
    come back as notes. A missing budget file is itself a finding —
    the gate must never silently lose its baseline."""
    from .launchgraph import (budget_snapshot, build_launch_graph,
                              compare_budget)
    from .registry import budget_path

    bpath = budget_path()
    rel = _canon(bpath)
    if not os.path.isfile(bpath):
        return [Finding(rel, 1, 0, "launch-budget-regression",
                        "analysis/launch_budget.json is missing: the "
                        "launch/transfer budget gate has no baseline — "
                        "regenerate it with --write-budget")], []
    with open(bpath, "r", encoding="utf-8") as fh:
        baseline = json.load(fh)
    current = budget_snapshot(build_launch_graph())
    regressions, notes = compare_budget(current,
                                        baseline.get("budget", {}))
    findings = [Finding(rel, 1, 0, "launch-budget-regression", msg)
                for msg in regressions]
    return findings, notes


def _write_budget() -> str:
    """Regenerate analysis/launch_budget.json: the launch-graph rule
    findings on the shipped tree (the implicit baseline) plus the
    regression-gated budget snapshot."""
    from .launchgraph import (LaunchGraphRules, budget_snapshot,
                              build_launch_graph)
    from .registry import budget_path

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    names = set(LaunchGraphRules.RULE_NAMES)
    findings = [f for f in lint_paths([pkg]) if f.rule in names]
    payload = {
        "version": 1,
        "findings": [
            {"path": _canon(f.path), "rule": f.rule,
             "message": f.message, "severity": f.severity}
            for f in findings
        ],
        "budget": budget_snapshot(build_launch_graph()),
    }
    path = budget_path()
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path


def _lock_findings(current_findings):
    """The fbtpu-locksmith ``--all`` leg: cross-module lock-order
    cycles from the whole-program graph (the per-module rule pass only
    sees intra-module cycles), a missing committed baseline, and stale
    baseline entries (debt that no longer exists must leave the file —
    a stale key could otherwise mask a future regression with the same
    message)."""
    from .locksmith import LocksmithRules, graph_cycle_findings
    from .registry import lock_baseline_path

    lpath = lock_baseline_path()
    rel = _canon(lpath)
    findings = list(graph_cycle_findings())
    if not os.path.isfile(lpath):
        return findings + [Finding(
            rel, 1, 0, "lock-baseline-stale",
            "analysis/lock_baseline.json is missing: the concurrency "
            "gate has no baseline — regenerate it with "
            "--write-lock-baseline", "error")]
    keys = _load_baseline(lpath)
    names = set(LocksmithRules.RULE_NAMES)
    live = {(_canon(f.path), f.rule, f.message)
            for f in list(current_findings) + findings
            if f.rule in names}
    for key in sorted(keys - live):
        findings.append(Finding(
            rel, 1, 0, "lock-baseline-stale",
            f"baseline entry no longer matches any finding (fixed "
            f"debt? remove it): {key[1]} @ {key[0]}: {key[2]}",
            "warning"))
    return findings


def _write_lock_baseline() -> str:
    """Regenerate analysis/lock_baseline.json: the locksmith rule
    findings on the shipped tree (justified debt, see ANALYSIS.md)
    plus the order-graph node/edge counts the tests pin."""
    from .locksmith import LocksmithRules, build_lock_graph, \
        graph_cycle_findings
    from .registry import lock_baseline_path

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    names = set(LocksmithRules.RULE_NAMES)
    findings = [f for f in lint_paths([pkg]) if f.rule in names]
    findings.extend(graph_cycle_findings())
    graph = build_lock_graph()
    payload = {
        "version": 1,
        "findings": [
            {"path": _canon(f.path), "rule": f.rule,
             "message": f.message, "severity": f.severity}
            for f in findings
        ],
        "graph": {"nodes": len(graph["nodes"]),
                  "edges": len(graph["edges"]),
                  "cycles": len(graph["cycles"])},
    }
    path = lock_baseline_path()
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path


def _copy_findings(current_findings):
    """The fbtpu-memscope ``--all`` leg: compare the live host copy
    census against the committed ``analysis/copy_budget.json`` —
    growth in copy/walk passes per ingest entry, a new entry or
    witness site, or an unbudgeted ``copywitness.count`` site is an
    error finding; improvements come back as notes. A missing budget
    file and stale baseline entries surface too (the gate must never
    silently lose its baseline, and fixed debt must leave the file)."""
    from .memscope import (MemscopeRules, build_copy_census,
                           census_snapshot, compare_copy_budget)
    from .registry import copy_budget_path

    cpath = copy_budget_path()
    rel = _canon(cpath)
    if not os.path.isfile(cpath):
        return [Finding(rel, 1, 0, "copy-budget-regression",
                        "analysis/copy_budget.json is missing: the "
                        "host copy-census gate has no baseline — "
                        "regenerate it with --write-copy-budget")], []
    with open(cpath, "r", encoding="utf-8") as fh:
        baseline = json.load(fh)
    current = census_snapshot(build_copy_census())
    regressions, notes = compare_copy_budget(current,
                                             baseline.get("census", {}))
    findings = [Finding(rel, 1, 0, "copy-budget-regression", msg)
                for msg in regressions]
    keys = _load_baseline(cpath)
    names = set(MemscopeRules.RULE_NAMES)
    live = {(_canon(f.path), f.rule, f.message)
            for f in current_findings if f.rule in names}
    for key in sorted(keys - live):
        findings.append(Finding(
            rel, 1, 0, "copy-baseline-stale",
            f"baseline entry no longer matches any finding (fixed "
            f"debt? remove it): {key[1]} @ {key[0]}: {key[2]}",
            "warning"))
    return findings, notes


def _write_copy_budget() -> str:
    """Regenerate analysis/copy_budget.json: the memscope rule
    findings on the shipped tree (justified debt), the regression-
    gated census snapshot, and the eliminated-pass ledger that keeps
    the zero-copy work's wins reviewable in the diff."""
    from .memscope import (ELIMINATED, MemscopeRules, build_copy_census,
                           census_snapshot)
    from .registry import copy_budget_path

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    names = set(MemscopeRules.RULE_NAMES)
    findings = [f for f in lint_paths([pkg]) if f.rule in names]
    payload = {
        "version": 1,
        "findings": [
            {"path": _canon(f.path), "rule": f.rule,
             "message": f.message, "severity": f.severity}
            for f in findings
        ],
        "census": census_snapshot(build_copy_census()),
        "eliminated": list(ELIMINATED),
    }
    path = copy_budget_path()
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path


def _fusion_findings(current_findings):
    """The fbtpu-fuseplan ``--all`` leg: compare the live fusion plan
    against the committed ``analysis/fusion_plan.json`` — boundary
    growth, planned-launch/byte growth, an unplanned chain, or a
    FUSABLE verdict turning BLOCKED is an error finding; shrinkage
    comes back as a note. A missing plan file and stale baseline
    entries surface too (same contract as the other three gates)."""
    from .fuseplan import (FuseplanRules, build_fusion_plan,
                           compare_fusion_plan, plan_snapshot)
    from .registry import fusion_plan_path

    fpath = fusion_plan_path()
    rel = _canon(fpath)
    if not os.path.isfile(fpath):
        return [Finding(rel, 1, 0, "fusion-plan-regression",
                        "analysis/fusion_plan.json is missing: the "
                        "fusion-plan gate has no baseline — "
                        "regenerate it with --write-fusion-plan")], []
    with open(fpath, "r", encoding="utf-8") as fh:
        baseline = json.load(fh)
    current = plan_snapshot(build_fusion_plan())
    regressions, notes = compare_fusion_plan(current,
                                             baseline.get("plan", {}))
    findings = [Finding(rel, 1, 0, "fusion-plan-regression", msg)
                for msg in regressions]
    keys = _load_baseline(fpath)
    names = set(FuseplanRules.RULE_NAMES)
    live = {(_canon(f.path), f.rule, f.message)
            for f in current_findings if f.rule in names}
    for key in sorted(keys - live):
        findings.append(Finding(
            rel, 1, 0, "fusion-plan-regression",
            f"baseline entry no longer matches any finding (fixed "
            f"debt? remove it): {key[1]} @ {key[0]}: {key[2]}",
            "warning"))
    return findings, notes


def _write_fusion_plan() -> str:
    """Regenerate analysis/fusion_plan.json: the fuseplan rule
    findings on the shipped tree (open boundaries are planned debt)
    plus the regression-gated boundary-verdict / planned-program
    snapshot. stale-suppression findings are deliberately NOT
    baselined — a stale waiver must fail the gate until removed."""
    from .fuseplan import (FuseplanRules, build_fusion_plan,
                           plan_snapshot)
    from .registry import fusion_plan_path

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    names = set(FuseplanRules.RULE_NAMES)
    findings = [f for f in lint_paths([pkg]) if f.rule in names]
    payload = {
        "version": 1,
        "findings": [
            {"path": _canon(f.path), "rule": f.rule,
             "message": f.message, "severity": f.severity}
            for f in findings
        ],
        "plan": plan_snapshot(build_fusion_plan()),
    }
    path = fusion_plan_path()
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path


def _write_baseline(path: str, findings) -> None:
    payload = {
        "version": 1,
        "findings": [
            {"path": f.path, "rule": f.rule, "message": f.message,
             "severity": f.severity}
            for f in findings
        ],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m fluentbit_tpu.analysis",
        description="fbtpu-lint: concurrency + JAX-purity + batch-"
                    "exactness + silent-failure analysis, and the "
                    "native C static-analysis gate (see ANALYSIS.md)")
    ap.add_argument("paths", nargs="*",
                    help="files or directories (default: the "
                         "fluentbit_tpu package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings")
    ap.add_argument("--all", action="store_true", dest="run_all",
                    help="Python rules AND the native C gate")
    ap.add_argument("--native", action="store_true", dest="native_only",
                    help="native C gate only")
    ap.add_argument("--no-cache", action="store_true",
                    help="ignore the native gate's result cache")
    ap.add_argument("--changed", action="store_true",
                    help="lint only the .py files changed vs HEAD "
                         "(fast pre-commit; Python rules only)")
    ap.add_argument("--graph", metavar="MODE",
                    choices=("json", "dot", "lock", "lock-dot",
                             "fusion", "fusion-dot"),
                    help="emit the fbtpu-xray device launch graph "
                         "(json: graph + budget snapshot + regression "
                         "diff; dot: graphviz), the fbtpu-locksmith "
                         "lock acquisition-order graph (lock: json; "
                         "lock-dot: graphviz), or the fbtpu-fuseplan "
                         "boundary plan (fusion: json + regression "
                         "diff; fusion-dot: graphviz) and exit")
    ap.add_argument("--baseline", metavar="FILE",
                    help="subtract findings recorded in FILE; exit 0 "
                         "when nothing new")
    ap.add_argument("--write-baseline", metavar="FILE",
                    help="snapshot current findings into FILE, exit 0")
    ap.add_argument("--write-budget", action="store_true",
                    help="regenerate analysis/launch_budget.json and "
                         "exit")
    ap.add_argument("--write-lock-baseline", action="store_true",
                    help="regenerate analysis/lock_baseline.json and "
                         "exit")
    ap.add_argument("--write-copy-budget", action="store_true",
                    help="regenerate analysis/copy_budget.json and "
                         "exit")
    ap.add_argument("--write-fusion-plan", action="store_true",
                    help="regenerate analysis/fusion_plan.json and "
                         "exit")
    ap.add_argument("--write-baselines", action="store_true",
                    help="refresh launch budget, lock baseline, copy "
                         "budget AND fusion plan in one pass, then "
                         "exit")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule set and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        from .batch import BatchExactnessRules
        from .fuseplan import FuseplanRules
        from .launchgraph import LaunchGraphRules
        from .locksmith import LocksmithRules
        from .memscope import MemscopeRules
        from .native_gate import NATIVE_RULES
        from .speccheck import SpecCheckRules

        for r in RULES:
            if isinstance(r, FuseplanRules):
                for n in r.RULE_NAMES:
                    print(f"{n}: (fuseplan pack) {r.description}")
            elif isinstance(r, LocksmithRules):
                for n in r.RULE_NAMES:
                    print(f"{n}: (locksmith pack) {r.description}")
            elif isinstance(r, MemscopeRules):
                for n in r.RULE_NAMES:
                    print(f"{n}: (memscope pack) {r.description}")
            elif isinstance(r, BatchExactnessRules):
                for n in r.RULE_NAMES:
                    print(f"{n}: (batch-exactness pack) {r.description}")
            elif isinstance(r, LaunchGraphRules):
                for n in r.RULE_NAMES:
                    print(f"{n}: (launch-graph pack) {r.description}")
            elif isinstance(r, SpecCheckRules):
                for n in r.RULE_NAMES:
                    print(f"{n}: (speccheck pack) {r.description}")
            elif r.name == "jax-purity":
                for n in ("jax-host-sync", "jax-side-effect",
                          "jax-retrace"):
                    print(f"{n}: (jax-purity pack) {r.description}")
            else:
                print(f"{r.name}: {r.description}")
        for n in NATIVE_RULES:
            print(f"{n}: native C gate (analysis.native_gate; "
                  f"--all/--native)")
        return 0

    if args.graph in ("lock", "lock-dot"):
        from .locksmith import build_lock_graph, lock_graph_to_dot

        lgraph = build_lock_graph()
        if args.graph == "lock-dot":
            print(lock_graph_to_dot(lgraph))
        else:
            print(json.dumps(lgraph, indent=2, sort_keys=True))
        return 0

    if args.graph in ("fusion", "fusion-dot"):
        from .fuseplan import (build_fusion_plan, compare_fusion_plan,
                               fusion_plan_to_dot, plan_snapshot)
        from .registry import fusion_plan_path

        fplan = build_fusion_plan()
        if args.graph == "fusion-dot":
            print(fusion_plan_to_dot(fplan))
            return 0
        snapshot = plan_snapshot(fplan)
        regressions, fnotes = [], []
        if os.path.isfile(fusion_plan_path()):
            with open(fusion_plan_path(), "r", encoding="utf-8") as fh:
                baseline = json.load(fh)
            regressions, fnotes = compare_fusion_plan(
                snapshot, baseline.get("plan", {}))
        fplan["plan"] = snapshot
        fplan["plan_regressions"] = regressions
        fplan["plan_notes"] = fnotes
        print(json.dumps(fplan, indent=2, sort_keys=True))
        return 0

    if args.graph:
        from .launchgraph import (budget_snapshot, build_launch_graph,
                                  compare_budget, graph_to_dot)
        from .registry import budget_path

        graph = build_launch_graph()
        if args.graph == "dot":
            print(graph_to_dot(graph))
            return 0
        snapshot = budget_snapshot(graph)
        regressions, bnotes = [], []
        if os.path.isfile(budget_path()):
            with open(budget_path(), "r", encoding="utf-8") as fh:
                baseline = json.load(fh)
            regressions, bnotes = compare_budget(
                snapshot, baseline.get("budget", {}))
        graph["budget"] = snapshot
        graph["budget_regressions"] = regressions
        graph["budget_notes"] = bnotes
        print(json.dumps(graph, indent=2, sort_keys=True))
        return 0

    if args.write_budget:
        path = _write_budget()
        print(f"fbtpu-lint: launch/transfer budget written to {path}")
        return 0

    if args.write_lock_baseline:
        path = _write_lock_baseline()
        print(f"fbtpu-lint: lock baseline written to {path}")
        return 0

    if args.write_copy_budget:
        path = _write_copy_budget()
        print(f"fbtpu-lint: copy budget written to {path}")
        return 0

    if args.write_fusion_plan:
        path = _write_fusion_plan()
        print(f"fbtpu-lint: fusion plan written to {path}")
        return 0

    if args.write_baselines:
        for writer, label in ((_write_budget, "launch/transfer budget"),
                              (_write_lock_baseline, "lock baseline"),
                              (_write_copy_budget, "copy budget"),
                              (_write_fusion_plan, "fusion plan")):
            path = writer()
            print(f"fbtpu-lint: {label} written to {path}")
        return 0

    findings: list = []
    notes: list = []

    if args.changed:
        try:
            changed = _changed_paths()
        except RuntimeError as e:
            print(f"fbtpu-lint: --changed: {e}", file=sys.stderr)
            return 2
        if not changed:
            print("fbtpu-lint: --changed: no .py files changed vs "
                  "HEAD; 0 findings")
            return 0
        args.paths = changed

    if not args.native_only:
        paths = args.paths or [
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ]
        try:
            findings.extend(lint_paths(paths))
        except FileNotFoundError as e:
            print(e, file=sys.stderr)
            return 2

    if args.run_all or args.native_only:
        from .native_gate import run_native_gate

        nf, notes = run_native_gate(cache=not args.no_cache)
        findings.extend(nf)

    if args.run_all:
        bf, bnotes = _budget_findings()
        findings.extend(bf)
        notes = list(notes) + list(bnotes)
        findings.extend(_lock_findings(findings))
        cf, cnotes = _copy_findings(findings)
        findings.extend(cf)
        notes = list(notes) + list(cnotes)
        ff, fnotes = _fusion_findings(findings)
        findings.extend(ff)
        notes = list(notes) + list(fnotes)

    if args.write_baseline:
        _write_baseline(args.write_baseline, findings)
        print(f"fbtpu-lint: baseline of {len(findings)} finding(s) "
              f"written to {args.write_baseline}")
        return 0

    baselined = 0
    if args.baseline:
        try:
            keys = _load_baseline(args.baseline)
        except (OSError, ValueError, KeyError) as e:
            print(f"fbtpu-lint: unreadable baseline "
                  f"{args.baseline!r}: {e}", file=sys.stderr)
            return 2
        findings, baselined = _subtract(findings, keys)
    else:
        # the committed launch/transfer budget is an implicit baseline:
        # its recorded findings are ROADMAP item 1's known debt, gated
        # by the budget numbers rather than re-reported on every run
        # (the lock baseline plays the same role for the locksmith
        # pack — stale entries surface as lock-baseline-stale in --all)
        from .registry import budget_path, copy_budget_path, \
            fusion_plan_path, lock_baseline_path

        for bpath in (budget_path(), lock_baseline_path(),
                      copy_budget_path(), fusion_plan_path()):
            if os.path.isfile(bpath):
                keys = _load_baseline(bpath)
                findings, hit = _subtract(findings, keys)
                baselined += hit

    if args.as_json:
        if args.run_all or args.native_only:
            # the native gate's notes travel with the findings: a
            # machine consumer must be able to tell "analyzed clean"
            # from "every layer skipped" (never a silent green)
            print(json.dumps(
                {"findings": [f.__dict__ for f in findings],
                 "notes": notes}, indent=2))
        else:
            print(json.dumps([f.__dict__ for f in findings], indent=2))
    else:
        for n in notes:
            print(f"# {n}")
        for f in findings:
            print(f.render())
        n = len(findings)
        tail = f" ({baselined} baselined)" if baselined else ""
        print(f"fbtpu-lint: {n} finding{'s' if n != 1 else ''}{tail}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
