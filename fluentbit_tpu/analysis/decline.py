"""Decline-path exception-swallowing rule.

``decline-swallow``: a broad ``except Exception`` (or bare ``except`` /
``BaseException``) whose whole body is a silent *decline* — assigning
``None`` to a fast-path handle, ``return None``, ``continue`` — on a
data-path module. These are one notch above ``swallowed-error``'s
pass-only bodies: the code LOOKS like it handles the failure (the
fallback engages), but a real bug in the fast path (a typo in the
native table builder, a refactor that changed an argument type) now
manifests only as a silent, permanent performance cliff or a
per-record fallback that hides the defect forever. The decline is
fine; the silence is not. Narrow the exception to the expected decline
type (``FallbackError``, ``ValueError``), log the surprise, or justify
with ``# fbtpu-lint: allow(decline-swallow)``.

Pass-only bodies are ``swallowed-error``'s territory and are excluded
here so one site never double-reports.
"""

from __future__ import annotations

import ast
from typing import List

from . import Finding, Module, Rule
from .silent import DATA_PATH_PREFIXES, _is_broad

__all__ = ["DeclineSwallowRule"]


def _is_decline_only(body: List[ast.stmt]) -> bool:
    """True when the handler only declines: None-assignments, bare/None
    returns, continue/break — and does nothing observable."""
    saw_decline = False
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant):
            continue  # docstring / ellipsis
        if isinstance(stmt, ast.Assign) \
                and isinstance(stmt.value, ast.Constant) \
                and stmt.value.value is None:
            saw_decline = True
            continue
        if isinstance(stmt, ast.Return) and (
                stmt.value is None
                or (isinstance(stmt.value, ast.Constant)
                    and stmt.value.value is None)):
            saw_decline = True
            continue
        if isinstance(stmt, (ast.Continue, ast.Break)):
            saw_decline = True
            continue
        return False  # anything else (a log call, a raise) = observable
    return saw_decline


class DeclineSwallowRule(Rule):
    name = "decline-swallow"
    description = ("broad `except` whose body only declines (None "
                   "assignment / return None) on a data-path module — "
                   "silent fast-path loss hides real bugs")
    severity = "warning"

    def check(self, module: Module) -> List[Finding]:
        if not any(p in module.path for p in DATA_PATH_PREFIXES):
            return []
        out: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node.type) or not _is_decline_only(node.body):
                continue
            shown = (ast.unparse(node.type) if node.type is not None
                     else "bare")
            f = self.finding(
                module, node,
                f"broad `except {shown}` silently declines a fast "
                f"path: a real bug here becomes an invisible permanent "
                f"fallback — narrow the type to the expected decline "
                f"(FallbackError/ValueError), or log the surprise",
                extra_lines=tuple(s.lineno for s in node.body[:1]))
            if f is not None:
                out.append(f)
        return out
