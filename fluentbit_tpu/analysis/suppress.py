"""Unused-suppression audit: ``# fbtpu-lint: allow(<rule>)`` comments
that no longer suppress any live finding.

Every suppression in the tree is a reviewed exception with an inline
justification. When the flagged code is later fixed or deleted, the
comment tends to stay — and a stale ``allow`` is a loaded gun: it
pre-approves the *next* violation of that rule on that line. This rule
re-runs the whole rule set over the module with suppressions disabled,
diffs the result against the suppressed run, and flags any comment
whose named rules stopped matching a finding on the line it covers
(``stale-suppression``, warning).

Attribution is conservative: a rule may accept its comment away from
the flagged line (``extra_lines`` — except-handler bodies, multi-line
constructs), so a suppressed finding that cannot be pinned to any
specific comment keeps EVERY comment naming its rule alive rather
than guessing. Wildcard ``allow(*)`` comments are exempt (they are
deliberate blanket waivers, reviewed as such).
"""

from __future__ import annotations

import io
import tokenize
from typing import Dict, List, Set, Tuple

from . import _ALLOW_RE, Finding, Module, Rule

__all__ = ["StaleSuppressionRule"]


def _allow_comments(module: Module) -> List[Tuple[int, Set[str]]]:
    """(line, rule names) of every real ``allow(...)`` COMMENT token —
    tokenized, not regexed over raw lines, so the many docstrings that
    *mention* the suppression syntax never look like waivers."""
    out: List[Tuple[int, Set[str]]] = []
    try:
        toks = tokenize.generate_tokens(
            io.StringIO(module.source).readline)
        for tok in toks:
            if tok.type != tokenize.COMMENT:
                continue
            m = _ALLOW_RE.search(tok.string)
            if m:
                names = {p.strip() for p in m.group(1).split(",")
                         if p.strip()}
                if names:
                    out.append((tok.start[0], names))
    except (tokenize.TokenError, IndentationError):
        return []
    return out


class StaleSuppressionRule(Rule):
    name = "stale-suppression"
    description = ("an `# fbtpu-lint: allow(<rule>)` comment whose "
                   "named rules no longer match any finding on the "
                   "covered line — fixed code, stale waiver: remove "
                   "the comment (it pre-approves the next violation)")
    severity = "warning"

    def check(self, module: Module) -> List[Finding]:
        comments = [(ln, names) for ln, names in _allow_comments(module)
                    if "*" not in names]
        if not comments:
            return []
        suppressed = self._suppressed(module)
        by_rule: Dict[str, List[Finding]] = {}
        for f in suppressed:
            by_rule.setdefault(f.rule, []).append(f)

        def attributable(f: Finding) -> bool:
            return any(f.rule in names and f.line in (cl, cl + 1)
                       for cl, names in comments)

        out: List[Finding] = []
        for line, names in comments:
            live = False
            for rule_name in names:
                hits = by_rule.get(rule_name, [])
                if any(f.line in (line, line + 1) for f in hits):
                    live = True
                elif any(not attributable(f) for f in hits):
                    # a suppressed finding of this rule floats free of
                    # every comment (extra_lines acceptance) — keep
                    # all its comments rather than flag a live one
                    live = True
            if not live:
                listed = ", ".join(sorted(names))
                out.append(Finding(
                    module.path, line, 0, self.name,
                    f"allow({listed}) suppresses nothing: no live "
                    f"{listed} finding on line {line} or {line + 1} — "
                    f"the code it waived is gone; remove the comment",
                    self.severity))
        return out

    def _suppressed(self, module: Module) -> List[Finding]:
        """Findings that exist only because a suppression hides them:
        re-run every other rule on a clone whose ``allowed()`` always
        says no, and subtract the suppressed run. A pack that cannot
        run here (missing kernel deps) cannot prove staleness and is
        skipped — never a false positive from a half-run."""
        from . import RULES

        clone = Module(module.path, module.source)
        clone.allowed = (  # type: ignore[method-assign]
            lambda rule, line, extra_lines=(): False)
        out: List[Finding] = []
        for rule in RULES:
            if isinstance(rule, StaleSuppressionRule):
                continue
            try:
                unsuppressed = rule.check(clone)
                live = rule.check(module)
            except Exception:  # pragma: no cover - degraded host
                continue
            live_keys = {(f.rule, f.line, f.message) for f in live}
            out.extend(f for f in unsuppressed
                       if (f.rule, f.line, f.message) not in live_keys)
        return out
