"""JAX kernel purity / retrace rules.

The device kernels must stay pure and shape-stable to hold the ≥50M
lines/s line: one stray host sync serializes every dispatch behind a
device→host copy, one Python side effect fires once at trace time (or
once per retrace — silently wrong either way), and one data/shape branch
turns the compile cache into a compile storm.

Traced-function discovery is name-based and transitive:

- seeds: defs decorated with ``jit``/``pjit``/``pmap``/``vmap``/
  ``shard_map`` (incl. through ``partial``), and defs referenced in the
  arguments of ``jit``/``pjit``/``pmap``/``vmap``/``shard_map``/
  ``checkpoint``/``remat``/``lax.scan``/``fori_loop``/``while_loop``/
  ``cond`` calls — single-level aliases are followed
  (``impl = self._a if p else self._b; jax.jit(impl)`` marks both,
  including through attribute stores like ``self._impl = impl``).
- propagation: a call to a module-local def (or alias) from traced code
  marks the callee; defs nested inside traced defs are traced.

The ``pjit``/``shard_map`` coverage exists for the partitioned mesh
plane (ops/mesh.py + ops/grep.py mesh matcher): the sharded hot path
compiles ONCE per mesh and runs on every device per dispatch, so a
host callback or shape-dependent retrace that sneaks in there costs
n_devices× what it costs single-device.

Rules emitted:

- ``jax-host-sync``: ``block_until_ready``/``device_get``/``.item()``/
  ``.tolist()``/``np.asarray``/``np.array``/``np.frombuffer`` and
  1-arg ``float()``/``int()``/``bool()`` casts inside traced code;
  also host-callback escapes (``pure_callback``/``io_callback``/
  ``debug_callback``/``host_callback``) — inside a pjit/shard_map
  program each shard's step blocks on a Python round-trip.
- ``jax-side-effect``: ``print``, ``global``/``nonlocal``, and
  attribute writes on ``self`` inside traced code.
- ``jax-retrace``: ``if``/``while`` whose test touches ``.shape``/
  ``.ndim``/``.size``/``len(<param>)`` directly (per-shape recompiles),
  or references a traced parameter bare (tracer boolification —
  ``TracerBoolConversionError`` at run time).

Batched filter entry points: defs named ``process_batch`` (the engine's
whole-chunk filter hook) are additionally checked for the retrace
hazard even though they are not traced themselves — a Python branch on
an array ``.shape``/``.size``/``.ndim`` inside one re-specializes every
kernel it feeds per distinct shape, which is exactly the compile-storm
the traced rule exists for. Host syncs are legal there (it IS host
code), so only the shape-branch rule applies.

Shape-derived *locals* (``pad = G2 * m - Lk``) branching is deliberately
NOT flagged: bucketed shapes make those branches trace-stable by design
here, and chasing derivation would drown the signal in noise.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from . import Finding, Module, Rule

__all__ = ["JaxPurityRules"]

#: call/decorator terminals that trace their function arguments
_TRACERS = {"jit", "pjit", "pmap", "vmap", "shard_map", "checkpoint",
            "remat", "scan", "fori_loop", "while_loop", "cond",
            "named_call", "custom_jvp", "custom_vjp"}

#: decorator terminals that make the decorated def itself traced
_TRACER_DECOS = {"jit", "pjit", "pmap", "vmap", "shard_map"}

#: host-callback escapes: legal jax, but a per-dispatch Python round
#: trip — in a sharded program every device's step blocks on it
_HOST_CALLBACKS = {"pure_callback", "io_callback", "debug_callback",
                   "host_callback"}

#: batched filter entry points — shape-branch (retrace) checked even
#: though untraced (see module docstring)
_BATCH_ENTRIES = {"process_batch"}

_NP_SYNCS = {"asarray", "array", "frombuffer", "copy"}
_ATTR_SYNCS = {"block_until_ready", "item", "tolist", "device_get"}
_CAST_SYNCS = {"float", "int", "bool"}
_SHAPE_ATTRS = {"shape", "ndim", "size"}


def _terminal(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _ref_names(expr: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.Attribute):
            out.add(node.attr)
    return out


class JaxPurityRules(Rule):
    name = "jax-purity"  # umbrella; findings carry their precise rule
    description = ("host syncs / side effects / retrace hazards inside "
                   "jit- or scan-traced code")

    def check(self, module: Module) -> List[Finding]:
        if "jax" not in module.source \
                and not any(e in module.source for e in _BATCH_ENTRIES):
            return []
        tree = module.tree

        defs: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)

        # single-level aliases: name/attr → def names its value refers to
        aliases: Dict[str, Set[str]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                refs = _ref_names(node.value) & set(defs)
                if not refs:
                    continue
                for tgt in node.targets:
                    t = _terminal(tgt)
                    if t is not None:
                        aliases.setdefault(t, set()).update(refs)

        def resolve(names: Set[str]) -> Set[str]:
            out = names & set(defs)
            for n in names:
                out |= aliases.get(n, set()) & set(defs)
            return out

        traced: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _ref_names(dec) & _TRACER_DECOS:
                        traced.add(node.name)
            elif isinstance(node, ast.Call):
                if _terminal(node.func) in _TRACERS:
                    arg_refs: Set[str] = set()
                    for a in list(node.args) + [k.value for k in node.keywords]:
                        arg_refs |= _ref_names(a)
                    traced |= resolve(arg_refs)

        # transitive closure over module-local calls from traced code
        changed = True
        while changed:
            changed = False
            for name in list(traced):
                for d in defs.get(name, ()):
                    for node in ast.walk(d):
                        if isinstance(node, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)) \
                                and node.name not in traced:
                            traced.add(node.name)  # nested def
                            changed = True
                        elif isinstance(node, ast.Call):
                            callee = _terminal(node.func)
                            if callee is None:
                                continue
                            for t in resolve({callee}):
                                if t not in traced:
                                    traced.add(t)
                                    changed = True

        findings: List[Finding] = []
        for name in traced:
            for d in defs.get(name, ()):
                findings.extend(self._check_traced(module, d))
        # batched filter entry points: retrace (shape-branch) rule only
        # — they are host code feeding jit'd kernels, so host syncs are
        # fine but per-shape Python branches re-specialize downstream
        for name in _BATCH_ENTRIES:
            if name in traced:
                continue  # already fully checked above
            for d in defs.get(name, ()):
                findings.extend(self._check_batch_entry(module, d))
        # a def can be reached under several names; dedup by location
        seen: Set[tuple] = set()
        out = []
        for f in findings:
            key = (f.line, f.col, f.rule, f.message)
            if key not in seen:
                seen.add(key)
                out.append(f)
        out.sort(key=lambda f: (f.line, f.col))
        return out

    def _check_batch_entry(self, module: Module, fn) -> List[Finding]:
        """Retrace-only pass over a ``process_batch`` def: flag
        ``if``/``while`` tests touching array ``.shape``/``.size``/
        ``.ndim`` — each distinct shape re-specializes the kernels the
        batch feeds (bucket shapes upstream: ops.batch.bucket_size)."""
        out: List[Finding] = []
        where = f"batched entry ({fn.name})"

        def walk(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue
                if isinstance(child, (ast.If, ast.While)):
                    for sub in ast.walk(child.test):
                        if isinstance(sub, ast.Attribute) \
                                and sub.attr in _SHAPE_ATTRS:
                            self._emit(
                                module, child, "jax-retrace",
                                f"Python branch on `.{sub.attr}` in "
                                f"{where}: re-specializes the "
                                f"downstream kernel per distinct shape "
                                f"— bucket shapes upstream "
                                f"(ops.batch.bucket_size)", out)
                            break
                walk(child)

        walk(fn)
        return out

    # -- per-function checks ------------------------------------------

    def _emit(self, module: Module, node: ast.AST, rule: str,
              message: str, out: List[Finding]) -> None:
        line = getattr(node, "lineno", 1)
        if not module.allowed(rule, line):
            out.append(Finding(module.path, line,
                               getattr(node, "col_offset", 0),
                               rule, message))

    def _check_traced(self, module: Module, fn) -> List[Finding]:
        out: List[Finding] = []
        params = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                  + fn.args.kwonlyargs)} - {"self", "cls"}
        # dict-like params: subscripted with a string key somewhere in
        # the body (`t["trans_flat"]`) — these are pytree containers,
        # so `"key" in t` is static structure, not tracer data. A
        # param never string-subscripted stays array-like and keeps
        # the full retrace/boolification checks.
        dict_params: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Subscript) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in params \
                    and isinstance(node.slice, ast.Constant) \
                    and isinstance(node.slice.value, str):
                dict_params.add(node.value.id)
        where = f"traced code ({fn.name})"

        def walk(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                # nested defs are traced too but get their own pass
                # (their params differ)
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue
                self._check_node(module, child, params, where, out,
                                 dict_params)
                walk(child)

        walk(fn)
        return out

    def _check_node(self, module: Module, node: ast.AST, params: Set[str],
                    where: str, out: List[Finding],
                    dict_params: Optional[Set[str]] = None) -> None:
        if isinstance(node, ast.Call):
            t = _terminal(node.func)
            if t in _HOST_CALLBACKS:
                self._emit(module, node, "jax-host-sync",
                           f"`{t}(...)` in {where}: a host-callback "
                           f"escape blocks every device's step on a "
                           f"Python round-trip per dispatch — keep the "
                           f"sharded hot path callback-free (compute "
                           f"on-device or post-process the forced "
                           f"result)", out)
                return
            if isinstance(node.func, ast.Attribute):
                base = _terminal(node.func.value)
                if t in _NP_SYNCS and base in ("np", "numpy"):
                    self._emit(module, node, "jax-host-sync",
                               f"`{base}.{t}(...)` in {where} forces a "
                               f"device→host copy per dispatch; use jnp "
                               f"or move it outside the kernel", out)
                elif t in _ATTR_SYNCS:
                    self._emit(module, node, "jax-host-sync",
                               f"`.{t}()` in {where} synchronizes the "
                               f"host with the device stream", out)
            elif isinstance(node.func, ast.Name):
                if t in _CAST_SYNCS and len(node.args) == 1 \
                        and not node.keywords:
                    self._emit(module, node, "jax-host-sync",
                               f"`{t}(...)` in {where} concretizes a "
                               f"traced value (host sync or tracer "
                               f"error)", out)
                elif t == "print":
                    self._emit(module, node, "jax-side-effect",
                               f"`print` in {where} fires at trace "
                               f"time, not per call; use jax.debug."
                               f"print if intended", out)
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            kw = "global" if isinstance(node, ast.Global) else "nonlocal"
            self._emit(module, node, "jax-side-effect",
                       f"`{kw}` write in {where}: traced code must be "
                       f"pure — return the value instead", out)
        elif isinstance(node, ast.Attribute) \
                and isinstance(node.ctx, ast.Store) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            self._emit(module, node, "jax-side-effect",
                       f"attribute write `self.{node.attr} = ...` in "
                       f"{where}: runs once at trace time, silently "
                       f"stale after; thread state through the carry",
                       out)
        elif isinstance(node, (ast.If, ast.While)):
            self._check_branch(module, node, params, where, out,
                               dict_params or set())

    def _check_branch(self, module: Module, node, params: Set[str],
                      where: str, out: List[Finding],
                      dict_params: Set[str] = frozenset()) -> None:
        # pytree-structure membership is static at trace time: a kernel
        # taking its table pytree as a DICT param branches on
        # `"pair_maps" in t` to pick a sub-kernel — that is pytree
        # STRUCTURE (fixed per jit cache entry), not tracer data, so it
        # can never boolify a tracer (the partitioned mesh plane's
        # table-pytree idiom, ops/grep.py _super_symbols). Only params
        # the function also string-subscripts qualify: `"GET" in batch`
        # over a traced ARRAY param still iterates the tracer and must
        # keep firing.
        test = node.test
        if isinstance(test, ast.Compare) and len(test.ops) == 1 \
                and isinstance(test.ops[0], (ast.In, ast.NotIn)) \
                and isinstance(test.left, ast.Constant) \
                and isinstance(test.left.value, str) \
                and all(n.id in dict_params or n.id not in params
                        for n in ast.walk(test)
                        if isinstance(n, ast.Name)):
            return
        for sub in ast.walk(node.test):
            if isinstance(sub, ast.Attribute) and sub.attr in _SHAPE_ATTRS:
                self._emit(module, node, "jax-retrace",
                           f"Python branch on `.{sub.attr}` in {where}: "
                           f"recompiles per distinct shape — bucket "
                           f"shapes upstream or use lax.cond", out)
                return
            if isinstance(sub, ast.Call) and _terminal(sub.func) == "len" \
                    and len(sub.args) == 1 \
                    and isinstance(sub.args[0], ast.Name) \
                    and sub.args[0].id in params:
                self._emit(module, node, "jax-retrace",
                           f"Python branch on `len(...)` of a traced "
                           f"argument in {where}: recompiles per "
                           f"distinct shape", out)
                return
            if isinstance(sub, ast.Name) and sub.id in params:
                self._emit(module, node, "jax-retrace",
                           f"Python branch on traced argument "
                           f"`{sub.id}` in {where}: tracer "
                           f"boolification fails at run time — use "
                           f"jnp.where or lax.cond", out)
                return
