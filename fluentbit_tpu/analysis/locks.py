"""Lock-discipline rules.

``guarded-by``: accesses to registry-listed attributes/globals must sit
lexically inside ``with <lock>:`` for the registered lock name. The
checker understands single-level aliasing (``lock = a.ingest_lock if p
else self._ingest_lock`` followed by ``with lock:`` counts as holding
both), exempts construction (``__init__``/``__new__`` for attributes,
module top level for globals), and honors per-entry ``writes_only``.

``await-in-lock``: an ``await`` while holding a ``threading`` lock
parks the coroutine WITH the lock held — every other thread touching
that lock (collector threads, output workers, library callers) then
blocks for the full duration of the awaited I/O, and a second coroutine
on the same loop acquiring the same lock deadlocks outright. Flags any
``await`` lexically inside a synchronous ``with`` whose context
expression names a lock (terminal name containing "lock");
``async with`` (asyncio locks) is exempt.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set

from . import Finding, Module, Rule
from .registry import GUARDS, GuardEntry

__all__ = ["GuardedByRule", "AwaitUnderLockRule"]


def _terminal_names(expr: ast.AST) -> Set[str]:
    """Every bare Name id and Attribute terminal attr in ``expr``."""
    out: Set[str] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.Attribute):
            out.add(node.attr)
    return out


class _GuardVisitor(ast.NodeVisitor):
    def __init__(self, rule: "GuardedByRule", module: Module,
                 entries: Sequence[GuardEntry]):
        self.rule = rule
        self.module = module
        self.entries = entries
        self.lock_names = {e.lock for e in entries}
        #: attr name → entries guarding it (kind-separated)
        self.attr_entries: Dict[str, List[GuardEntry]] = {}
        self.global_entries: Dict[str, List[GuardEntry]] = {}
        for e in entries:
            table = (self.global_entries if e.kind == "global"
                     else self.attr_entries)
            for a in e.attrs:
                table.setdefault(a, []).append(e)
        self.held: List[Set[str]] = []
        self.func_stack: List[str] = []
        #: alias name → lock names it may carry
        self.aliases: Dict[str, Set[str]] = {}
        self.findings: List[Finding] = []

    # -- helpers ------------------------------------------------------

    def _held_names(self) -> Set[str]:
        out: Set[str] = set()
        for s in self.held:
            out |= s
        return out

    def _lock_refs(self, expr: ast.AST) -> Set[str]:
        names = _terminal_names(expr)
        out = names & self.lock_names
        for n in names:
            out |= self.aliases.get(n, set())
        return out

    def _in_ctor(self) -> bool:
        return bool(self.func_stack) and \
            self.func_stack[-1] in ("__init__", "__new__")

    def _report(self, node: ast.AST, entry: GuardEntry, what: str) -> None:
        msg = (f"{what} must hold `{entry.lock}` "
               f"(guarded-by registry: {entry.module})")
        if entry.note:
            msg += f" — {entry.note}"
        f = self.rule.finding(self.module, node, msg)
        if f is not None:
            self.findings.append(f)

    # -- traversal ----------------------------------------------------

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # a def's body runs in its own context: a closure created inside
        # `with lock:` executes later, when the lock is NOT held.
        # Aliases are function-scoped (inherited by nested defs, never
        # shared between siblings) — `lock = self._ingest_lock` in one
        # function must not legitimize `with lock:` in another
        saved_held, self.held = self.held, []
        saved_aliases = self.aliases
        self.aliases = {k: set(v) for k, v in saved_aliases.items()}
        self.func_stack.append(node.name)
        self.generic_visit(node)
        self.func_stack.pop()
        self.aliases = saved_aliases
        self.held = saved_held

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # same deferral rule as nested defs: a lambda born under the
        # lock runs later, without it
        saved, self.held = self.held, []
        self.generic_visit(node)
        self.held = saved

    def visit_With(self, node: ast.With) -> None:
        acquired: Set[str] = set()
        for item in node.items:
            acquired |= self._lock_refs(item.context_expr)
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        self.held.append(acquired)
        for stmt in node.body:
            self.visit(stmt)
        self.held.pop()

    # async with = asyncio primitives; not a threading-lock scope
    # (its body still gets visited for guarded accesses)

    def visit_Assign(self, node: ast.Assign) -> None:
        refs = self._lock_refs(node.value)
        if refs:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.aliases.setdefault(tgt.id, set()).update(refs)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        entries = self.attr_entries.get(node.attr)
        if entries and not self._in_ctor():
            held = self._held_names()
            is_read = isinstance(node.ctx, ast.Load)
            for e in entries:
                if e.writes_only and is_read:
                    continue
                if e.lock not in held:
                    verb = "read of" if is_read else "write to"
                    self._report(node, e, f"{verb} `.{node.attr}`")
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        entries = self.global_entries.get(node.id)
        # module top level (empty function stack) = import-time init
        if entries and self.func_stack:
            held = self._held_names()
            is_read = isinstance(node.ctx, ast.Load)
            for e in entries:
                if e.writes_only and is_read:
                    continue
                if e.lock not in held:
                    verb = "read of" if is_read else "write to"
                    self._report(node, e, f"{verb} global `{node.id}`")
        self.generic_visit(node)


class GuardedByRule(Rule):
    name = "guarded-by"
    description = ("registry-listed shared state accessed outside its "
                   "`with <lock>:` scope")

    def __init__(self, guards: Optional[Sequence[GuardEntry]] = None):
        self.guards = tuple(guards) if guards is not None else GUARDS

    def check(self, module: Module) -> List[Finding]:
        entries = [e for e in self.guards if module.path.endswith(e.module)]
        if not entries:
            return []
        v = _GuardVisitor(self, module, entries)
        v.visit(module.tree)
        return v.findings


class _AwaitVisitor(ast.NodeVisitor):
    def __init__(self, rule: "AwaitUnderLockRule", module: Module):
        self.rule = rule
        self.module = module
        self.held: List[str] = []
        self.findings: List[Finding] = []

    @staticmethod
    def _lockish(expr: ast.AST) -> Optional[str]:
        # the context expr's own terminal only: `with a.b.ingest_lock:`
        # → "ingest_lock"; calls like `with open(lockfile):` don't count
        if isinstance(expr, ast.Attribute):
            name = expr.attr
        elif isinstance(expr, ast.Name):
            name = expr.id
        else:
            return None
        return name if "lock" in name.lower() else None

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # a nested def's body runs in its own (later) context
        saved, self.held = self.held, []
        self.generic_visit(node)
        self.held = saved

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            n = self._lockish(item.context_expr)
            if n is not None:
                acquired.append(n)
        self.held.extend(acquired)
        for stmt in node.body:
            self.visit(stmt)
        del self.held[len(self.held) - len(acquired):]

    def visit_Await(self, node: ast.Await) -> None:
        if self.held:
            f = self.rule.finding(
                self.module, node,
                f"`await` while holding threading lock "
                f"`{self.held[-1]}` — the lock spans the suspension; "
                f"move the await outside the `with`, or use an "
                f"asyncio primitive")
            if f is not None:
                self.findings.append(f)
        self.generic_visit(node)


class AwaitUnderLockRule(Rule):
    name = "await-in-lock"
    description = "`await` inside a synchronous `with <threading lock>:`"

    def check(self, module: Module) -> List[Finding]:
        if "await" not in module.source:
            return []
        v = _AwaitVisitor(self, module)
        v.visit(module.tree)
        return v.findings
