"""fbtpu-lint — repo-native static analysis for the data plane.

Round 4's heap overflow taught us that the bug classes this codebase
actually ships are not caught by example-based tests: they live in the
gaps *between* correct components — a guarded attribute touched off-lock
by a new call path, an ``await`` slipped inside a ``threading`` lock, a
host sync added to a traced kernel. This package makes those invariants
machine-checked, the same way ``tests/test_asan_native.py`` made the
memory-safety invariant repeatable.

Six rule families (see ANALYSIS.md for the full contract):

- **lock discipline** (`guarded-by`, `await-in-lock`): a declarative
  guarded-by registry (`analysis.registry.GUARDS`) names, per module,
  the attributes/globals whose access must hold a named lock; the
  checker flags accesses outside a lexical ``with <lock>:`` scope, and
  flags ``await`` while a ``threading`` lock is held inside async code.
- **JAX kernel purity** (`jax-host-sync`, `jax-side-effect`,
  `jax-retrace`): functions reachable from ``jit``/``pmap``/
  ``shard_map``/``lax.scan``/``lax.fori_loop`` tracing must not host-sync
  (``block_until_ready``, ``np.asarray``, ``float()``/``int()`` on traced
  values), must not carry Python side effects, and must not branch on
  shapes/data in Python (recompile storms / tracer errors).
- **silent failures** (`swallowed-error`): ``except Exception: pass`` on
  data-path modules hides real errors; narrow the type, count it in a
  metric, or justify the swallow with an explicit suppression.
- **batch exactness** (`batch-decline-after-commit`,
  `batch-commit-replay`, `batch-stateful-unmarked`,
  `batch-no-fallback`, `batch-unordered-emit`): interprocedural
  dataflow over every ``FilterPlugin.process_batch`` verifying the
  batched fast path's contracts — declines dominated by zero committed
  side effects, guarded emits, a reachable per-record fallback, and
  first-seen emission order (analysis.batch).
- **decline-path swallows** (`decline-swallow`): broad excepts whose
  body only declines a fast path (None assignment / return None)
  without logging — silent permanent fallback (analysis.decline).
- **dtype narrowing** (`dtype-narrowing`): int64→int32 truncation in
  offset/index math — astype/array/cumsum with a narrow dtype on
  offset-flavored values (analysis.dtype).
- **flush-path deadlines** (`await-no-deadline`): raw socket/upstream
  awaits inside output flush paths with no ``asyncio.wait_for``/
  ``guard.io_deadline`` bound, and ``open_connection`` dials without a
  ``timeout=`` — the hung-peer shape the fbtpu-guard plane contains
  (analysis.deadline).
- **metered ingest** (`qos-unmetered-ingest`): any public ingest entry
  point in ``core/`` from which a chunk-pool append is reachable must
  also reach the fbtpu-qos tenant admission call (``qos.admit``) —
  an unmetered path silently bypasses every tenant quota
  (analysis.qos).
- **guarded device dispatch** (`device-unguarded-dispatch`): any
  public plugin/flux path from which a jit/pjit/shard_map dispatch is
  reachable must also go through the fbtpu-armor ``DeviceLane``
  (``lane.run``/``begin``/``finish``) — an unguarded dispatch would
  stall or drop on device faults instead of failing over bit-exactly
  (analysis.devlane).
- **minimized kernel DFAs** (`grep-unminimized-dfa`): any path from
  which a ``GrepProgram``/``GrepTables`` build is reachable must not
  also reach an unminimized-DFA source (raw ``DFA(...)`` construction,
  ``compile_dfa(minimize=False)``) — an un-reduced table silently
  closes the assoc gate and shrinks the stride budget
  (analysis.shrink; PERF.md "shrink").
- **launch graph / transfer budget** (`device-multi-launch-chain`,
  `device-undonated-buffer`, `device-host-roundtrip`,
  `device-sync-in-staging-loop`, `stage-redundant-copy`): the
  fbtpu-xray interprocedural walk from every plugin/flux chain entry
  to every device launch site — launches per staged segment, PCIe
  byte crossings, the donate set, host scatters
  (analysis.launchgraph; budget gated by analysis/launch_budget.json,
  rendered by ``--graph json|dot``).
- **host-memory pack** (`host-redundant-copy`,
  `host-decode-then-restage`, `host-mutable-view-escape`,
  `mmap-lifetime-escape`): the fbtpu-memscope copy census — a walk
  from every ingest entry counting the materialization passes and
  byte walks each record pays, cross-referenced against the
  ``core.copywitness`` instrumentation sites' declared per-record
  byte budgets, plus escape rules for mutable staging-arena views and
  views that outlive their mmap (analysis.memscope; census gated by
  analysis/copy_budget.json).
- **fusion pack** (`fusable-unfused-boundary`,
  `fusion-blocked-by-host-compact`, `cross-launch-restage`,
  `fused-effect-violation`, `fusion-plan-regression`): the
  fbtpu-fuseplan planner classifies every boundary between consecutive
  device launches of a chain as FUSABLE or BLOCKED (host compact,
  intervening host effect, speccheck aval incompatibility, donation
  break), prices the planned fused program, and gates it against
  analysis/fusion_plan.json (analysis.fuseplan; rendered by
  ``--graph fusion|fusion-dot``).
- **stale suppressions** (`stale-suppression`): an
  ``allow(<rule>)`` comment whose named rules no longer match any
  finding on the covered line — fixed code, stale waiver
  (analysis.suppress).

The native C/C++ data plane has its own gate (analysis.native_gate):
clang-tidy with the repo profile (.clang-tidy), the gcc ``-fanalyzer``
static analyzer, and a libclang-based checker for the codec's
invariants (container emission balance, bounds-guarded cursor reads,
error-path frees). ``python -m fluentbit_tpu.analysis --all`` runs
everything; C sources take the same ``fbtpu-lint: allow(...)``
suppressions in ``/* */`` or ``//`` comments.

Suppressions: a ``# fbtpu-lint: allow(<rule>[, <rule>...])`` comment on
the flagged line (or the line above) silences that rule there. Every
suppression must carry an inline justification.

Run: ``python -m fluentbit_tpu.analysis [paths...]`` (exit 1 on
findings); ``tests/test_lint.py`` gates the whole package tree.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Finding", "Module", "lint_source", "lint_path", "lint_paths",
    "iter_py_files", "RULES", "rule_names",
]

_ALLOW_RE = re.compile(r"#\s*fbtpu-lint:\s*allow\(([^)]*)\)")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    path: str
    line: int
    col: int
    rule: str
    message: str
    #: "error" fails the gate outright; "warning" fails too unless
    #: baselined (see __main__ --baseline) — the split exists so CI can
    #: diff legacy debt instead of flag-daying it
    severity: str = "error"

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule}] {self.severity}: {self.message}")

    def baseline_key(self) -> tuple:
        """Line/col-insensitive identity for --baseline diffs (a pure
        reformat must not churn the baseline)."""
        return (self.path, self.rule, self.message)


class Module:
    """Parsed unit handed to every rule: AST + raw lines (for the
    suppression comments ast discards) + the posix-ish path rules match
    registry entries and data-path prefixes against."""

    def __init__(self, path: str, source: str):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)

    def allowed(self, rule: str, line: int, extra_lines: Sequence[int] = ()) -> bool:
        """True when an allow(<rule>) comment covers ``line`` (or the
        line above it, or any of ``extra_lines`` — multi-line constructs
        like except handlers accept the comment on their body too)."""
        for ln in {line, line - 1, *extra_lines}:
            if 1 <= ln <= len(self.lines):
                m = _ALLOW_RE.search(self.lines[ln - 1])
                if m:
                    names = {p.strip() for p in m.group(1).split(",")}
                    if rule in names or "*" in names:
                        return True
        return False


class Rule:
    """Base rule: subclasses set ``name`` and implement ``check``."""

    name = ""
    description = ""
    severity = "error"

    def check(self, module: Module) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, module: Module, node: ast.AST, message: str,
                extra_lines: Sequence[int] = ()) -> Optional[Finding]:
        """Build a Finding unless a suppression comment covers it."""
        line = getattr(node, "lineno", 1)
        if module.allowed(self.name, line, extra_lines):
            return None
        return Finding(module.path, line, getattr(node, "col_offset", 0),
                       self.name, message, self.severity)


def _build_rules(guards=None) -> List[Rule]:
    from .batch import BatchExactnessRules
    from .deadline import AwaitNoDeadlineRule
    from .decline import DeclineSwallowRule
    from .devlane import UnguardedDispatchRule
    from .dtype import DtypeNarrowingRule
    from .fuseplan import FuseplanRules
    from .launchgraph import LaunchGraphRules
    from .locks import AwaitUnderLockRule, GuardedByRule
    from .locksmith import LocksmithRules
    from .memscope import MemscopeRules
    from .purity import JaxPurityRules
    from .qos import UnmeteredIngestRule
    from .shrink import UnminimizedDfaRule
    from .silent import SwallowedErrorRule
    from .speccheck import SpecCheckRules
    from .suppress import StaleSuppressionRule

    return [
        GuardedByRule(guards),
        AwaitUnderLockRule(),
        JaxPurityRules(),
        SwallowedErrorRule(),
        BatchExactnessRules(),
        DeclineSwallowRule(),
        DtypeNarrowingRule(),
        AwaitNoDeadlineRule(),
        UnmeteredIngestRule(),
        UnguardedDispatchRule(),
        UnminimizedDfaRule(),
        LaunchGraphRules(),
        SpecCheckRules(),
        LocksmithRules(guards),
        MemscopeRules(),
        FuseplanRules(),
        # last: the stale-suppression audit re-runs the packs above on
        # a suppression-disabled clone to prove a comment still earns
        # its keep
        StaleSuppressionRule(),
    ]


#: Default rule set (module-level so ``--list-rules`` and tests share it).
RULES: List[Rule] = _build_rules()


def rule_names() -> List[str]:
    names: List[str] = []
    for r in RULES:
        for n in ([r.name] if isinstance(r.name, str) else list(r.name)):
            if n not in names:
                names.append(n)
    return names


def lint_source(source: str, path: str, guards=None) -> List[Finding]:
    """Lint one source string as if it lived at ``path`` (the test
    fixture entry point — registry matching keys off the path)."""
    module = Module(path, source)
    rules = RULES if guards is None else _build_rules(guards)
    out: List[Finding] = []
    for rule in rules:
        out.extend(rule.check(module))
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def lint_path(path: str, guards=None) -> List[Finding]:
    with open(path, "r", encoding="utf-8", errors="replace") as fh:
        source = fh.read()
    try:
        return lint_source(source, path, guards)
    except SyntaxError as e:
        return [Finding(path.replace(os.sep, "/"), e.lineno or 1, 0,
                        "parse", f"syntax error: {e.msg}")]


def iter_py_files(paths: Iterable[str]) -> List[str]:
    """Expand paths to .py files. A path that is neither a directory
    nor an existing .py file raises — a lint gate that silently lints
    nothing on a typo'd/moved path would stay green forever."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for n in sorted(names):
                    if n.endswith(".py"):
                        files.append(os.path.join(root, n))
        elif p.endswith(".py") and os.path.isfile(p):
            files.append(p)
        else:
            raise FileNotFoundError(
                f"fbtpu-lint: not a directory or .py file: {p!r}")
    return files


def lint_paths(paths: Iterable[str], guards=None) -> List[Finding]:
    out: List[Finding] = []
    for f in iter_py_files(paths):
        out.extend(lint_path(f, guards))
    return out
