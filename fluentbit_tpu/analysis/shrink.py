"""grep-unminimized-dfa rule.

fbtpu-shrink (PERF.md "shrink") moves the whole kernel-table economy —
assoc eligibility, stride depth, native table cache footprint, mesh
replication size — onto one invariant: every ``DFA`` that reaches
``GrepProgram`` / ``GrepTables`` / ``GrepFilterTables`` passed through
the compile-path reduction pass (``regex.dfa.compile_dfa``: Hopcroft
minimization, dead-state pruning, byte-class remerge). A hand-built
``DFA(...)`` table, or a ``compile_dfa(..., minimize=False)`` escape
hatch wired into a production path, silently re-bloats S and C — the
kernel still produces correct verdicts, so nothing at runtime notices
that the assoc gate closed and the stride dropped until a bench round
asks where the throughput went.

``grep-unminimized-dfa`` makes the invariant machine-checked (the
``qos-unmetered-ingest`` / ``device-unguarded-dispatch`` registry
pattern): in ``fluentbit_tpu/`` modules (outside ``regex/`` — the
definition site — and ``analysis/``), any function from whose
same-module call closure BOTH a program/tables constructor AND an
unminimized-DFA source are reachable is flagged. Sources are matched
lexically: a bare ``DFA(...)`` construction (the dataclass constructor
bypasses the minimizer by definition) and ``compile_dfa`` called with a
constant-false ``minimize=``. The closure is the same intentionally
lexical same-module call-name walk the sibling rules use; cross-module
laundering is out of scope (and the runtime ShrinkStats audit trail on
the DFA covers it in bench output).

Suppress with ``# fbtpu-lint: allow(grep-unminimized-dfa)`` plus a
justification — e.g. a differential harness that deliberately measures
the unminimized machine.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from . import Finding, Module, Rule

__all__ = ["UnminimizedDfaRule"]

#: Where the invariant binds. The regex package is the definition site
#: (the minimizer itself must build raw tables) and analysis/ lints
#: itself; everything else in the package is a consumer.
SCOPE = "fluentbit_tpu/"
EXEMPT = ("fluentbit_tpu/regex/", "fluentbit_tpu/analysis/")

#: Kernel-table sinks: a DFA handed to any of these is on the hot path.
SINK_NAMES = frozenset({"GrepProgram", "GrepTables", "GrepFilterTables"})


def _call_name(call: ast.Call):
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _is_const_false(node) -> bool:
    return isinstance(node, ast.Constant) and not node.value


def _is_source(call: ast.Call) -> bool:
    name = _call_name(call)
    if name == "DFA":
        return True
    if name == "compile_dfa":
        return any(kw.arg == "minimize" and _is_const_false(kw.value)
                   for kw in call.keywords)
    return False


class _FnInfo:
    __slots__ = ("node", "sources", "sinks", "calls")

    def __init__(self, node):
        self.node = node
        self.sources: List[ast.Call] = []
        self.sinks: List[ast.Call] = []
        self.calls: Set[str] = set()


def _analyze(fn) -> _FnInfo:
    info = _FnInfo(fn)
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if _is_source(node):
            info.sources.append(node)
        elif _call_name(node) in SINK_NAMES:
            info.sinks.append(node)
        f = node.func
        if isinstance(f, ast.Name):
            info.calls.add(f.id)
        elif isinstance(f, ast.Attribute):
            info.calls.add(f.attr)
    return info


class UnminimizedDfaRule(Rule):
    name = "grep-unminimized-dfa"
    description = ("a DFA that bypassed the fbtpu-shrink compile-path "
                   "reduction (raw DFA(...) construction or "
                   "compile_dfa(minimize=False)) reaches GrepProgram/"
                   "GrepTables — the kernel runs on an un-minimized "
                   "table, silently closing the assoc gate and "
                   "shrinking the stride (regex/dfa.py)")

    def check(self, module: Module) -> List[Finding]:
        if SCOPE not in module.path or \
                any(e in module.path for e in EXEMPT):
            return []
        by_name: Dict[str, List[_FnInfo]] = {}
        infos: List[_FnInfo] = []
        nested: Set[ast.AST] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = _analyze(node)
                infos.append(info)
                by_name.setdefault(node.name, []).append(info)
                for sub in ast.walk(node):
                    if sub is not node and isinstance(
                            sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        nested.add(sub)

        def closure(start: _FnInfo) -> Tuple[List[ast.Call],
                                             List[ast.Call]]:
            sources = list(start.sources)
            sinks = list(start.sinks)
            seen: Set[str] = {start.node.name}
            frontier = set(start.calls)
            while frontier:
                name = frontier.pop()
                if name in seen:
                    continue
                seen.add(name)
                for callee in by_name.get(name, ()):
                    sources.extend(callee.sources)
                    sinks.extend(callee.sinks)
                    frontier.update(callee.calls)
            return sources, sinks

        out: List[Finding] = []
        flagged: Set[int] = set()
        for info in infos:
            if info.node in nested:
                continue  # closures are reached via their container
            sources, sinks = closure(info)
            if not sources or not sinks:
                continue
            for src in sources:
                if src.lineno in flagged:
                    continue
                flagged.add(src.lineno)
                kind = ("raw DFA(...) construction"
                        if _call_name(src) == "DFA"
                        else "compile_dfa(minimize=False)")
                f = self.finding(
                    module, src,
                    f"{kind} reaches a GrepProgram/GrepTables build "
                    f"(via {info.node.name!r}) without the fbtpu-shrink "
                    f"reduction pass — the kernel table ships "
                    f"un-minimized, closing the assoc gate and "
                    f"shrinking the stride budget (regex/dfa.py "
                    f"compile_dfa)",
                    extra_lines=(info.node.lineno,))
                if f is not None:
                    out.append(f)
        return out
